"""Lightweight runtime metrics: counters, gauges, and latency histograms.

The reference has no metrics subsystem (its observability is the status
snapshot and the event log; see ``pkg/status/status.go`` and
``pkg/eventlog/``).  SURVEY.md §5 calls for adding counters here because the
framework's headline numbers — committed req/s, crypto batch sizes, device
dispatch latency — are continuous quantities a snapshot cannot capture.

Design: a process-local registry of named instruments with zero hot-path
allocation (counters are plain attribute increments; histograms append to a
float list and summarize lazily).  No background threads, no exporters — a
``snapshot()`` dict is the integration surface, consumable by tests, the
bench harness, the node runtime's status output, or an external scraper.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, delta: int = 1) -> None:
        self.value += delta


class Gauge:
    """Point-in-time value."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value


class Histogram:
    """Records observations; summarizes percentiles lazily.

    Bounded: keeps the most recent ``max_samples`` observations (enough for
    stable p50/p99 of a dispatch-latency stream without unbounded growth).
    """

    __slots__ = ("name", "samples", "max_samples", "total_count", "total_sum")

    def __init__(self, name: str, max_samples: int = 4096):
        self.name = name
        self.samples: List[float] = []
        self.max_samples = max_samples
        self.total_count = 0
        self.total_sum = 0.0

    def observe(self, value: float) -> None:
        self.total_count += 1
        self.total_sum += value
        samples = self.samples
        if len(samples) >= self.max_samples:
            # Drop the oldest half in one slice (amortized O(1) per observe).
            del samples[: self.max_samples // 2]
        samples.append(value)

    def percentile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        return float(np.percentile(np.asarray(self.samples), q))

    def mean(self) -> float:
        if self.total_count == 0:
            return 0.0
        return self.total_sum / self.total_count


class Timer:
    """Context manager recording elapsed seconds into a histogram."""

    __slots__ = ("histogram", "_start")

    def __init__(self, histogram: Histogram):
        self.histogram = histogram
        self._start = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.histogram.observe(time.perf_counter() - self._start)


class Registry:
    """Named instrument registry.  Instruments are created on first use and
    shared thereafter; creation is locked, hot-path updates are not (CPython
    attribute increments are atomic enough for monitoring data, matching the
    design of mainstream client libraries)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str, max_samples: int = 4096) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    name, Histogram(name, max_samples)
                )
        return h

    def timer(self, name: str) -> Timer:
        return Timer(self.histogram(name))

    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value dict; histograms expand to _mean/_p50/_p99/_count."""
        out: Dict[str, float] = {}
        for name, c in self._counters.items():
            out[name] = c.value
        for name, g in self._gauges.items():
            out[name] = g.value
        for name, h in self._histograms.items():
            out[f"{name}_count"] = h.total_count
            out[f"{name}_mean"] = h.mean()
            out[f"{name}_p50"] = h.percentile(50)
            out[f"{name}_p99"] = h.percentile(99)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# Default process-wide registry (tests and embedders may build their own).
default_registry = Registry()


def counter(name: str) -> Counter:
    return default_registry.counter(name)


def gauge(name: str) -> Gauge:
    return default_registry.gauge(name)


def histogram(name: str) -> Histogram:
    return default_registry.histogram(name)


def timer(name: str) -> Timer:
    return default_registry.timer(name)


def snapshot() -> Dict[str, float]:
    return default_registry.snapshot()
