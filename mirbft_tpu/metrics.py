"""Lightweight runtime metrics: counters, gauges, and latency histograms.

The reference has no metrics subsystem (its observability is the status
snapshot and the event log; see ``pkg/status/status.go`` and
``pkg/eventlog/``).  SURVEY.md §5 calls for adding counters here because the
framework's headline numbers — committed req/s, crypto batch sizes, device
dispatch latency — are continuous quantities a snapshot cannot capture.

Design: a process-local registry of named instruments with zero hot-path
allocation (counters are plain attribute increments; histograms append to a
float list and summarize lazily).  No background threads — the integration
surfaces are a ``snapshot()`` dict (tests, the bench harness, the node
runtime's status output) and ``render_prometheus()``, a text-exposition
renderer an external scraper can consume (docs/OBSERVABILITY.md).

Instruments may carry labels (e.g. ``{"node": "3"}``): label sets are part
of the instrument identity, so ``histogram("commit_latency_seconds",
labels={"node": "0"})`` and the node-1 twin are distinct series, rendered
with proper Prometheus label syntax.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np


def escape_label_value(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def format_labels(labels: Optional[Dict[str, str]]) -> str:
    """Canonical ``{k="v",...}`` rendering (sorted keys); "" for no labels."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels) if labels else {}
        self.value = 0

    def inc(self, delta: int = 1) -> None:
        self.value += delta


class Gauge:
    """Point-in-time value."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels) if labels else {}
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value


class Histogram:
    """Records observations; summarizes percentiles lazily.

    Bounded: keeps the most recent ``max_samples`` observations (enough for
    stable p50/p99 of a dispatch-latency stream without unbounded growth).
    """

    __slots__ = (
        "name", "labels", "samples", "max_samples", "total_count", "total_sum"
    )

    def __init__(
        self,
        name: str,
        max_samples: int = 4096,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.labels = dict(labels) if labels else {}
        self.samples: List[float] = []
        self.max_samples = max_samples
        self.total_count = 0
        self.total_sum = 0.0

    def observe(self, value: float) -> None:
        self.total_count += 1
        self.total_sum += value
        samples = self.samples
        if len(samples) >= self.max_samples:
            # Drop the oldest half in one slice (amortized O(1) per observe).
            del samples[: self.max_samples // 2]
        samples.append(value)

    def percentile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        return float(np.percentile(np.asarray(self.samples), q))

    def mean(self) -> float:
        if self.total_count == 0:
            return 0.0
        return self.total_sum / self.total_count


class Timer:
    """Context manager recording elapsed seconds into a histogram."""

    __slots__ = ("histogram", "_start")

    def __init__(self, histogram: Histogram):
        self.histogram = histogram
        self._start = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.histogram.observe(time.perf_counter() - self._start)


class Registry:
    """Named instrument registry.  Instruments are created on first use and
    shared thereafter; creation is locked, hot-path updates are not (CPython
    attribute increments are atomic enough for monitoring data, matching the
    design of mainstream client libraries)."""

    def __init__(self):
        # Creation-only lock; reads are deliberately lock-free (class
        # docstring: CPython attribute increments are atomic enough for
        # monitoring data).
        # mirlint: allow(lock-map)
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # Highest value ever reported per monotonic key (counters and
        # histogram _count/_sum): snapshot() clamps to these so a scrape
        # racing an unlocked `value += delta` (a torn read-modify-write
        # can briefly publish a stale lower value) never shows a counter
        # going backward across two snapshots.
        self._last_mono: Dict[str, float] = {}

    def counter(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> Counter:
        key = name + format_labels(labels)
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(name, labels))
        return c

    def gauge(
        self, name: str, labels: Optional[Dict[str, str]] = None
    ) -> Gauge:
        key = name + format_labels(labels)
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(name, labels))
        return g

    def histogram(
        self,
        name: str,
        max_samples: int = 4096,
        labels: Optional[Dict[str, str]] = None,
    ) -> Histogram:
        key = name + format_labels(labels)
        h = self._histograms.get(key)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    key, Histogram(name, max_samples, labels)
                )
        return h

    def timer(self, name: str) -> Timer:
        return Timer(self.histogram(name))

    def _instruments(
        self,
    ) -> Tuple[List[Counter], List[Gauge], List[Histogram]]:
        """Consistent instrument lists, taken under the creation lock so a
        concurrent first-use creation cannot mutate the dicts mid-iteration
        (``RuntimeError: dictionary changed size during iteration``)."""
        with self._lock:
            return (
                list(self._counters.values()),
                list(self._gauges.values()),
                list(self._histograms.values()),
            )

    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value dict; histograms expand to
        _count/_sum/_mean/_p50/_p99.  Labeled instruments keep their label
        block in the key (``name{k="v"}``); ``render_prometheus`` is the
        properly-labeled exposition surface."""
        counters, gauges, histograms = self._instruments()
        out: Dict[str, float] = {}

        def mono(key: str, value: float) -> float:
            prev = self._last_mono.get(key)
            if prev is not None and value < prev:
                return prev
            self._last_mono[key] = value
            return value

        with self._lock:
            for c in counters:
                key = c.name + format_labels(c.labels)
                out[key] = mono(key, c.value)
            for h in histograms:
                key = h.name + format_labels(h.labels)
                out[f"{key}_count"] = mono(f"{key}_count", h.total_count)
                out[f"{key}_sum"] = mono(f"{key}_sum", h.total_sum)
                out[f"{key}_mean"] = h.mean()
                out[f"{key}_p50"] = h.percentile(50)
                out[f"{key}_p99"] = h.percentile(99)
        for g in gauges:
            out[g.name + format_labels(g.labels)] = g.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._last_mono.clear()


def _fmt_value(value: float) -> str:
    if isinstance(value, bool):
        return str(int(value))
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def render_prometheus(
    registry: Optional[Registry] = None,
    extra_labels: Optional[Dict[str, str]] = None,
) -> str:
    """Render a registry in the Prometheus text exposition format (0.0.4).

    Counters and gauges render as their own types; histograms render as
    ``summary`` series — our histograms are sample-windowed with lazy
    percentiles, which maps to quantile/sum/count, not to fixed buckets.
    ``extra_labels`` (e.g. ``{"node": "3"}``) are merged into every series,
    the per-node labeling the node runtime's exposition surface uses;
    instrument-level labels win on key collisions."""
    reg = registry if registry is not None else default_registry
    counters, gauges, histograms = reg._instruments()
    extra = dict(extra_labels) if extra_labels else {}
    lines: List[str] = []
    seen_types: Dict[str, str] = {}

    def type_line(name: str, kind: str) -> None:
        if name not in seen_types:
            seen_types[name] = kind
            lines.append(f"# TYPE {name} {kind}")

    def merged(labels: Dict[str, str]) -> Dict[str, str]:
        out = dict(extra)
        out.update(labels)
        return out

    for c in sorted(counters, key=lambda i: (i.name, sorted(i.labels.items()))):
        type_line(c.name, "counter")
        lines.append(
            f"{c.name}{format_labels(merged(c.labels))} {_fmt_value(c.value)}"
        )
    for g in sorted(gauges, key=lambda i: (i.name, sorted(i.labels.items()))):
        type_line(g.name, "gauge")
        lines.append(
            f"{g.name}{format_labels(merged(g.labels))} {_fmt_value(g.value)}"
        )
    for h in sorted(
        histograms, key=lambda i: (i.name, sorted(i.labels.items()))
    ):
        type_line(h.name, "summary")
        base = merged(h.labels)
        for q, pct in (("0.5", 50), ("0.99", 99)):
            labels = dict(base)
            labels["quantile"] = q
            lines.append(
                f"{h.name}{format_labels(labels)} "
                f"{_fmt_value(h.percentile(pct))}"
            )
        suffix_labels = format_labels(base)
        lines.append(f"{h.name}_sum{suffix_labels} {_fmt_value(h.total_sum)}")
        lines.append(
            f"{h.name}_count{suffix_labels} {_fmt_value(h.total_count)}"
        )
    return "\n".join(lines) + ("\n" if lines else "")


# Default process-wide registry (tests and embedders may build their own).
default_registry = Registry()


def counter(name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
    return default_registry.counter(name, labels)


def gauge(name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
    return default_registry.gauge(name, labels)


def histogram(
    name: str, labels: Optional[Dict[str, str]] = None
) -> Histogram:
    return default_registry.histogram(name, labels=labels)


def timer(name: str) -> Timer:
    return default_registry.timer(name)


def snapshot() -> Dict[str, float]:
    return default_registry.snapshot()
