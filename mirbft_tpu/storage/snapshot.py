"""Checkpoint snapshot store + state transfer over the socket plane (L4).

The testengine's c5 suite proves checkpoint state transfer in simulation:
a lagging node asks ``App.transfer_to(seq, value)`` for the snapshot body
matching a checkpoint attestation.  This module makes that real for
mirnet processes:

* :class:`SnapshotStore` keeps snapshot bodies on disk as
  ``snap-<sha256>.bin``, written tmp-then-rename with a directory fsync
  so a crash can never leave a half-written body under a valid name.
  Content addressing doubles as integrity: ``load`` re-hashes the file
  and refuses a body that does not match its digest.
* The **transfer protocol** rides the transport's new ``KIND_SNAPSHOT``
  frame kind (``net/framing.py``).  A fetcher dials a peer's listener,
  sends one request frame naming the digest, and reads back either a
  ``missing`` frame or the body as a sequence of chunked frames (1 MiB
  chunks, so a large app state never trips ``MAX_FRAME_PAYLOAD``).  The
  serving side is ``TcpTransport._serve_snapshot``; both ends use the
  pack/unpack helpers here.

Every *verified* received body increments
``snapshot_transfer_bytes_total`` (requester side — the drill's proof
that catch-up went over the wire, docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from .. import metrics
from ..net.framing import FrameDecoder, KIND_SNAPSHOT, encode_frame
from .segments import fsync_dir

DIGEST_LEN = hashlib.sha256().digest_size

# Subframe types inside a KIND_SNAPSHOT payload.
SNAP_REQUEST = 0
SNAP_CHUNK = 1
SNAP_MISSING = 2

CHUNK_BYTES = 1024 * 1024

# subtype, chunk seq, chunk total (seq/total zero for request/missing).
_SNAP_HEADER = struct.Struct(">BII")


class SnapshotStore:
    """Content-addressed on-disk snapshot bodies.  Lock-free: writers
    publish via atomic rename, readers verify by re-hashing, so a torn
    concurrent view is impossible by construction."""

    def __init__(self, path: str):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _path(self, digest: bytes) -> Path:
        return self.dir / f"snap-{digest.hex()}.bin"

    def save(self, blob: bytes) -> bytes:
        """Persist ``blob``; returns its sha256 digest (the snapshot id)."""
        digest = hashlib.sha256(blob).digest()
        final = self._path(digest)
        if final.exists():
            return digest
        tmp = final.with_suffix(".tmp")
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
        fsync_dir(self.dir)
        return digest

    def load(self, digest: bytes) -> Optional[bytes]:
        path = self._path(digest)
        try:
            blob = path.read_bytes()
        except OSError:
            return None
        if hashlib.sha256(blob).digest() != digest:
            return None  # corrupt body: treat as missing, refetch
        return blob

    def has(self, digest: bytes) -> bool:
        return self._path(digest).exists()


# --- wire helpers (both ends of the transfer) ---------------------------


def encode_request(digest: bytes) -> bytes:
    return _SNAP_HEADER.pack(SNAP_REQUEST, 0, 0) + digest


def encode_missing(digest: bytes) -> bytes:
    return _SNAP_HEADER.pack(SNAP_MISSING, 0, 0) + digest


def unpack(payload: bytes) -> Tuple[int, int, int, bytes]:
    """``(subtype, seq, total, body)`` of one KIND_SNAPSHOT payload."""
    if len(payload) < _SNAP_HEADER.size:
        raise ValueError("short snapshot frame")
    subtype, seq, total = _SNAP_HEADER.unpack_from(payload)
    return subtype, seq, total, payload[_SNAP_HEADER.size :]


def chunk_payloads(blob: bytes) -> List[bytes]:
    """Split a snapshot body into ordered SNAP_CHUNK payloads (at least
    one, so an empty body still yields a complete reply)."""
    total = max(1, (len(blob) + CHUNK_BYTES - 1) // CHUNK_BYTES)
    return [
        _SNAP_HEADER.pack(SNAP_CHUNK, seq, total)
        + blob[seq * CHUNK_BYTES : (seq + 1) * CHUNK_BYTES]
        for seq in range(total)
    ]


def serve_request(payload: bytes, load) -> List[bytes]:
    """Server side: turn a request payload into reply payloads using
    ``load(digest) -> Optional[bytes]``."""
    subtype, _, _, digest = unpack(payload)
    if subtype != SNAP_REQUEST or len(digest) != DIGEST_LEN:
        raise ValueError(f"bad snapshot request (subtype {subtype})")
    blob = load(digest)
    if blob is None:
        return [encode_missing(digest)]
    return chunk_payloads(blob)


# --- fetch side ---------------------------------------------------------


def fetch_snapshot(
    addr: Tuple[str, int], digest: bytes, timeout_s: float = 5.0
) -> Optional[bytes]:
    """Fetch the snapshot body for ``digest`` from one peer's transport
    listener.  Returns the verified body, or None if the peer lacks it,
    the connection fails, or verification fails."""
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            sock.settimeout(timeout_s)
            sock.sendall(encode_frame(KIND_SNAPSHOT, encode_request(digest)))
            decoder = FrameDecoder()
            chunks: dict = {}
            total: Optional[int] = None
            while total is None or len(chunks) < total:
                data = sock.recv(65536)
                if not data:
                    return None
                for kind, payload in decoder.feed(data):
                    if kind != KIND_SNAPSHOT:
                        return None
                    subtype, seq, count, body = unpack(payload)
                    if subtype == SNAP_MISSING:
                        return None
                    if subtype != SNAP_CHUNK or count == 0:
                        return None
                    total = count
                    chunks[seq] = body
    except (OSError, ValueError):
        return None
    blob = b"".join(chunks.get(i, b"") for i in range(total))
    if len(chunks) != total or hashlib.sha256(blob).digest() != digest:
        return None
    metrics.counter("snapshot_transfer_bytes_total").inc(len(blob))
    return blob


def fetch_snapshot_from_peers(
    addrs: Iterable[Tuple[str, int]],
    digest: bytes,
    timeout_s: float = 5.0,
) -> Optional[bytes]:
    """Try each peer in turn until one serves a verified body."""
    for addr in addrs:
        blob = fetch_snapshot(addr, digest, timeout_s=timeout_s)
        if blob is not None:
            return blob
    return None
