"""Durable storage engine (L4): group-commit WAL, log-structured request
store with checkpoint-keyed GC, and snapshot state transfer over the
socket plane.  See docs/STORAGE.md for the design and recovery
invariants; ``simplewal.py``/``reqstore.py`` remain as the minimal
reference implementations of the same interfaces."""

from .logstore import LogStore
from .segments import (
    SCAN_CLEAN,
    SCAN_CRC,
    SCAN_TORN,
    cut_torn_tail,
    encode_record,
    fsync_dir,
    iter_records,
    valid_prefix,
)
from .snapshot import (
    SnapshotStore,
    fetch_snapshot,
    fetch_snapshot_from_peers,
)
from .wal import GroupCommitWAL, wal_segment_report

__all__ = [
    "GroupCommitWAL",
    "LogStore",
    "SnapshotStore",
    "SCAN_CLEAN",
    "SCAN_CRC",
    "SCAN_TORN",
    "cut_torn_tail",
    "encode_record",
    "fetch_snapshot",
    "fetch_snapshot_from_peers",
    "fsync_dir",
    "iter_records",
    "valid_prefix",
    "wal_segment_report",
]
