"""Group-commit segmented WAL (L4): fsync batching behind the WAL barrier.

``simplewal.py`` is correct but pays one ``write``+``fsync`` round trip per
``sync()`` call on the calling thread.  Under concurrent durability traffic
(several worker categories, several nodes sharing a disk, the storage
bench's appender fleet) those fsyncs serialize at device latency.  This
engine keeps the exact ``processor.WAL`` contract — ``sync()`` returns only
when every prior ``write``/``truncate`` is durable — but moves the disk
work to a dedicated **syncer thread**:

* ``write``/``truncate`` append an operation to a lock-guarded buffer and
  return immediately (appends are not durable until a ``sync``).
* ``sync`` takes a ticket for the operations buffered so far, wakes the
  syncer, and blocks until the ticket is durable.
* The syncer drains the whole buffer at once — every record lands in one
  ``write`` — and issues a **single fsync** for the batch, then releases
  every waiter whose ticket it covered.  Concurrent ``sync`` calls
  coalesce into one device round trip (group commit).
* An **adaptive batch window** (measure-then-adapt in the spirit of
  ``testengine.crypto.WaveController``) delays the flush a few hundred
  microseconds only while lingering demonstrably gathers committers
  that the fsync round trip itself would not have — collapsing to zero
  (no latency tax) for a lone writer or when arrivals already coalesce
  naturally during the flush.

On disk this is a directory of ``seg-<first_index>.wal`` segment files of
CRC-framed records (``storage/segments.py``), rotated at
``segment_max_bytes``, with the same lazy front-truncation and ``lowmark``
bookkeeping as ``simplewal`` — plus directory fsyncs after every segment
create/unlink so recovery can trust the directory listing.  Recovery cuts
any torn or corrupt tail off the active segment before appending.

Metrics (docs/OBSERVABILITY.md "Storage engine"): ``wal_append_bytes_total``,
``wal_fsync_seconds``, ``wal_group_commit_size``.
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from .. import metrics, wire
from ..messages import Persistent
from .segments import (
    SCAN_CLEAN,
    SCAN_CRC,
    cut_torn_tail,
    encode_record,
    fsync_dir,
    iter_records,
    valid_prefix,
)

_LOW_MARK_FILE = "lowmark"

# Shared-state declaration for mirlint's lock-discipline pass: the op
# buffer and ticket counters are filled by node worker threads and drained
# by the syncer thread, so every touch happens under the condition
# (docs/STATIC_ANALYSIS.md).  The file handle and active-segment path are
# syncer-owned after __init__ and stay out of the map.
MIRLINT_SHARED_STATE = {
    "GroupCommitWAL._pending": "_cond",
    "GroupCommitWAL._ops": "_cond",
    "GroupCommitWAL._durable_ops": "_cond",
    "GroupCommitWAL._sync_waiting": "_cond",
    "GroupCommitWAL._release": "_cond",
    "GroupCommitWAL._active_est": "_cond",
    "GroupCommitWAL._have_active": "_cond",
    "GroupCommitWAL._next_index": "_cond",
    "GroupCommitWAL._low_index": "_cond",
    "GroupCommitWAL._closing": "_cond",
    "GroupCommitWAL._syncer_error": "_cond",
}


class _BatchWindow:
    """Adaptive group-commit window: how long the syncer lingers before
    flushing, hoping more committers join the batch.  Measure-then-adapt
    in the spirit of ``WaveController`` (testengine/crypto.py), keyed to
    the one signal that matters: did lingering actually gather waiters
    the fsync itself would not have?  Committers that arrive DURING a
    flush coalesce for free, so a sleep only pays off when arrivals are
    staggered relative to the device round trip.  The window doubles
    while each linger demonstrably gathers extra waiters, collapses to
    zero the moment one doesn't (with a cooldown before re-probing), and
    a lone writer never sleeps at all."""

    __slots__ = ("window_s", "floor_s", "ceiling_s", "_ceiling_cfg", "_cooldown")

    def __init__(
        self,
        initial_s: float = 0.0,
        floor_s: float = 0.0002,
        ceiling_s: float = 0.002,
    ):
        self.window_s = initial_s
        self.floor_s = floor_s
        self.ceiling_s = ceiling_s
        self._ceiling_cfg = ceiling_s
        self._cooldown = 0

    def note_fsync(self, seconds: float) -> None:
        """Cap the window at half the device's observed fsync cost: a
        linger longer than that costs more than the fsync it would save,
        no matter how well it coalesces."""
        self.ceiling_s = min(self._ceiling_cfg, max(0.0, seconds * 0.5))
        if self.window_s > self.ceiling_s:
            self.window_s = self.ceiling_s

    def propose(self, waiters: int) -> float:
        """Seconds to linger before grabbing a batch with ``waiters``
        committers already blocked on it."""
        if self.window_s > 0.0:
            return self.window_s
        if waiters >= 2 and self._cooldown == 0:
            return self.floor_s  # probe: would lingering gather more?
        if self._cooldown:
            self._cooldown -= 1
        return 0.0

    def observe(self, slept_s: float, gathered: int) -> None:
        """``gathered`` = waiters that joined while the syncer slept."""
        if slept_s <= 0.0:
            return
        if gathered > 0:
            self.window_s = min(
                self.ceiling_s, max(slept_s * 2, self.floor_s)
            )
        else:
            self.window_s = 0.0
            self._cooldown = 8


class _BatchRelease:
    """One batch's completion signal.  ``durable``/``error`` are written
    by the syncer before ``event.set()`` and read by waiters only after
    ``event.wait()`` returns — the Event provides the happens-before, so
    released committers never touch the WAL lock on the way out (a
    notify_all there makes every group commit end in a serial convoy of
    lock reacquisitions, one per waiter)."""

    __slots__ = ("event", "durable", "error")

    def __init__(self):
        self.event = threading.Event()
        self.durable = 0
        self.error: Optional[BaseException] = None


class SyncTicket:
    """In-flight durability barrier (``GroupCommitWAL.sync_begin``): the
    registration half of ``sync()`` without the blocking half, so a caller
    can overlap further writes with the fsync and ``wait()`` later — the
    pipeline scheduler's WAL stage runs batch k+1's writes while batch k's
    fsync is on disk.  ``wait()`` returns once every op buffered before
    ``sync_begin`` is durable, or raises if the syncer failed."""

    __slots__ = ("_wal", "_ticket", "_first")

    def __init__(self, wal: "GroupCommitWAL", ticket: int, first):
        self._wal = wal
        self._ticket = ticket
        self._first = first

    def done(self) -> bool:
        """True when the ticket is already durable (never blocks)."""
        if self._first is None:
            return True
        return self._wal._ticket_done(self._ticket)

    def wait(self) -> None:
        if self._first is None:
            return
        self._wal._wait_ticket(self._ticket, self._first)
        self._first = None


class GroupCommitWAL:
    """File-backed ``processor.WAL`` with fsync-batched group commit."""

    def __init__(
        self,
        path: str,
        segment_max_bytes: int = 4 * 1024 * 1024,
        batch_window: Optional[_BatchWindow] = None,
    ):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = segment_max_bytes

        # Two conditions over ONE lock: committers wait on _cond for
        # durability, the syncer waits on _work for work — so a sync()
        # enqueue wakes only the syncer, never the other blocked
        # committers (notify_all there is O(waiters) spurious wakeups per
        # append).  Uniformly entered via ``with self._cond`` (the shared
        # lock) so the lock-discipline map stays single-named.
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._work = threading.Condition(self._lock)
        # Op buffer: ("rec", frame-bytes) | ("rotate", first_index) |
        # ("truncate", index).  Tickets count ops ever buffered / made
        # durable; sync(ticket) returns once _durable_ops covers it.
        self._pending: List[Tuple[str, object]] = []
        self._ops = 0
        self._durable_ops = 0
        self._sync_waiting = 0
        self._release = _BatchRelease()
        self._closing = False
        self._syncer_error: Optional[BaseException] = None

        self._low_index = self._read_low_mark()
        self._next_index: Optional[int] = None

        # Syncer-owned file state (single-threaded after this point).
        self._fh = None
        self._active_path: Optional[Path] = None
        self._window = batch_window if batch_window else _BatchWindow()

        segments = self._segments()
        self._have_active = bool(segments)
        self._active_est = 0
        if segments:
            # Reopening after a crash: cut any torn/corrupt tail BEFORE
            # appending, or new frames land after garbage and are lost.
            _, self._active_path = segments[-1]
            self._active_est = cut_torn_tail(self._active_path)
            self._fh = open(self._active_path, "ab")

        self._append_bytes = metrics.counter("wal_append_bytes_total")
        self._batch_size = metrics.histogram("wal_group_commit_size")

        self._syncer = threading.Thread(
            target=self._syncer_loop, name="wal-syncer", daemon=True
        )
        self._syncer.start()

    # --- low-watermark bookkeeping (syncer side) ---

    def _read_low_mark(self) -> int:
        mark = self.dir / _LOW_MARK_FILE
        if mark.exists():
            return int(mark.read_text())
        return 1

    def _write_low_mark(self, index: int) -> None:
        tmp = self.dir / (_LOW_MARK_FILE + ".tmp")
        tmp.write_text(str(index))
        os.replace(tmp, self.dir / _LOW_MARK_FILE)
        fsync_dir(self.dir)

    def _segments(self) -> List[Tuple[int, Path]]:
        segments = []
        for entry in self.dir.iterdir():
            if entry.name.startswith("seg-") and entry.name.endswith(".wal"):
                segments.append((int(entry.name[4:-4]), entry))
        return sorted(segments)

    # --- WAL protocol (caller side) ---

    def write(self, index: int, entry: Persistent) -> None:
        payload = wire.encode(entry)
        frame = encode_record(index, payload)
        with self._cond:
            self._check_open()
            if self._next_index is not None and index != self._next_index:
                raise ValueError(
                    f"WAL out of order: expected index {self._next_index}, "
                    f"got {index}"
                )
            if (
                not self._have_active
                or self._active_est >= self.segment_max_bytes
            ):
                self._pending.append(("rotate", index))
                self._ops += 1
                self._have_active = True
                self._active_est = 0
            self._pending.append(("rec", frame))
            self._ops += 1
            self._active_est += len(frame)
            self._next_index = index + 1
        self._append_bytes.inc(len(payload))

    def truncate(self, index: int) -> None:
        """Logically drop entries below ``index``; whole segments entirely
        below it are unlinked by the syncer at the next flush."""
        with self._cond:
            self._check_open()
            if index < self._low_index:
                raise ValueError(
                    f"truncate to {index} below low index {self._low_index}"
                )
            self._low_index = index
            self._pending.append(("truncate", index))
            self._ops += 1

    def sync(self) -> None:
        """Durability barrier: block until every op buffered before this
        call has been written and fsynced (one group fsync may cover many
        concurrent callers)."""
        self.sync_begin().wait()

    def sync_begin(self) -> SyncTicket:
        """Register a durability barrier without blocking: takes a ticket
        for the ops buffered so far and wakes the syncer, exactly like
        ``sync()``, but returns a ``SyncTicket`` instead of waiting — the
        in-flight/complete notification surface the pipeline scheduler
        overlaps WAL writes with fsyncs through.  ``sync()`` is
        ``sync_begin().wait()``."""
        with self._cond:
            self._check_open()
            ticket = self._ops
            if self._durable_ops >= ticket:
                return SyncTicket(self, ticket, None)
            self._sync_waiting += 1
            release = self._release
            self._work.notify()
        return SyncTicket(self, ticket, release)

    def _ticket_done(self, ticket: int) -> bool:
        with self._cond:
            if self._syncer_error is not None:
                return True  # wait() will raise; don't report in-flight
            return self._durable_ops >= ticket

    def _wait_ticket(self, ticket: int, release: _BatchRelease) -> None:
        while True:
            release.event.wait()
            if release.error is not None:
                raise RuntimeError("WAL syncer failed") from release.error
            if release.durable >= ticket:
                return
            # Our ops rode a batch that was already in flight when we
            # registered; wait for the next release to cover the ticket.
            with self._cond:
                self._check_open()
                release = self._release

    def load_all(self, for_each: Callable[[int, Persistent], None]) -> None:
        self.sync()  # everything buffered must be visible to the scan
        with self._cond:
            low_index = self._low_index
        records: List[Tuple[int, bytes]] = []
        for _, path in self._segments():
            for index, payload, _, _ in iter_records(path.read_bytes()):
                if index >= low_index:
                    records.append((index, payload))
        records.sort(key=lambda r: r[0])
        expected = None
        for index, payload in records:
            if expected is not None and index != expected:
                raise ValueError(
                    f"WAL gap: expected index {expected}, found {index}"
                )
            for_each(index, wire.decode(payload))
            expected = index + 1
        if expected is not None:
            with self._cond:
                self._next_index = expected

    def close(self) -> None:
        with self._cond:
            if self._closing:
                return
            self._closing = True
            self._work.notify()
        self._syncer.join()
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def _check_open(self) -> None:
        # Every caller holds self._cond — the guard is real, just not
        # lexical from this helper's point of view.
        if self._closing:  # mirlint: allow(lock-discipline)
            raise ValueError("WAL is closed")
        if self._syncer_error is not None:  # mirlint: allow(lock-discipline)
            raise RuntimeError("WAL syncer failed") from self._syncer_error  # mirlint: allow(lock-discipline)

    # --- syncer thread ---

    def _syncer_loop(self) -> None:
        release: Optional[_BatchRelease] = None
        try:
            while True:
                with self._cond:
                    # Flush only when a committer is actually waiting on
                    # durability (or at close): bare writes buffer in
                    # memory, exactly like simplewal's buffer in the OS
                    # page cache, and cost no fsync until a sync() lands.
                    while not self._closing and self._sync_waiting == 0:
                        self._work.wait()
                    if self._closing and not self._pending:
                        return
                    waiting_before = self._sync_waiting
                # Group-commit window: linger briefly (outside the lock)
                # iff the controller judges more committers would join.
                window = self._window.propose(waiting_before)
                if window > 0.0:
                    time.sleep(window)
                with self._cond:
                    batch = self._pending
                    self._pending = []
                    waiters = self._sync_waiting
                    self._sync_waiting = 0
                    release = self._release
                    self._release = _BatchRelease()
                records = self._apply_batch(batch)
                if records:
                    self._batch_size.observe(records)
                self._window.observe(window, waiters - waiting_before)
                with self._cond:
                    self._durable_ops += len(batch)
                    release.durable = self._durable_ops
                release.event.set()
        except BaseException as exc:  # propagate to callers, never die mute
            with self._cond:
                self._syncer_error = exc
                self._durable_ops = self._ops
                current = self._release
            # Force-release everyone: waiters on the in-flight batch (if
            # any) and waiters already registered on the next one.
            for rel in (release, current):
                if rel is not None:
                    rel.error = exc
                    rel.event.set()

    def _apply_batch(self, batch: List[Tuple[str, object]]) -> int:
        """Write every op of the batch, then make it durable with a single
        fsync.  Returns the number of records written."""
        records = 0
        for op, arg in batch:
            if op == "rec":
                self._fh.write(arg)
                records += 1
            elif op == "rotate":
                self._rotate(arg)
            else:  # "truncate"
                self._apply_truncate(arg)
        if batch and self._fh is not None:
            start = time.perf_counter()
            with metrics.timer("wal_fsync_seconds"):
                self._fh.flush()
                os.fsync(self._fh.fileno())
            self._window.note_fsync(time.perf_counter() - start)
        return records

    def _rotate(self, first_index: int) -> None:
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
        self._active_path = self.dir / f"seg-{first_index}.wal"
        self._fh = open(self._active_path, "ab")
        fsync_dir(self.dir)  # the new segment's dirent must survive a crash

    def _apply_truncate(self, index: int) -> None:
        self._write_low_mark(index)
        segments = self._segments()
        unlinked = False
        for i, (first, path) in enumerate(segments):
            next_first = segments[i + 1][0] if i + 1 < len(segments) else None
            if (
                next_first is not None
                and next_first <= index
                and path != self._active_path
            ):
                path.unlink()
                unlinked = True
        if unlinked:
            fsync_dir(self.dir)  # make the unlinks stick


def wal_segment_report(wal_dir: Path) -> dict:
    """Offline dump/verify of a WAL directory (the ``mircat --wal`` core):
    per-segment record counts, CRC/torn-tail status, and cross-segment
    index continuity above the lowmark.  Pure read-only."""
    wal_dir = Path(wal_dir)
    mark = wal_dir / _LOW_MARK_FILE
    low_index = int(mark.read_text()) if mark.exists() else 1
    segments = sorted(
        p for p in wal_dir.iterdir()
        if p.name.startswith("seg-") and p.name.endswith(".wal")
    )
    report = {
        "dir": str(wal_dir),
        "low_index": low_index,
        "segments": [],
        "problems": [],
    }
    indexes: List[int] = []
    for pos, path in enumerate(segments):
        data = path.read_bytes()
        valid, reason = valid_prefix(data)
        recs = list(iter_records(data))
        seg = {
            "name": path.name,
            "bytes": len(data),
            "valid_bytes": valid,
            "records": len(recs),
            "first_index": recs[0][0] if recs else None,
            "last_index": recs[-1][0] if recs else None,
            "status": reason,
        }
        report["segments"].append(seg)
        if reason == SCAN_CRC:
            report["problems"].append(
                f"{path.name}: CRC mismatch at byte {valid} "
                f"({len(data) - valid} bytes dropped)"
            )
        elif reason != SCAN_CLEAN and pos != len(segments) - 1:
            # A torn tail is expected only on the *active* (last) segment;
            # anywhere else it means a sealed segment lost bytes.
            report["problems"].append(
                f"{path.name}: torn tail in a sealed segment at byte {valid}"
            )
        indexes.extend(i for i, _, _, _ in recs)
    live = sorted(i for i in indexes if i >= low_index)
    for prev, cur in zip(live, live[1:]):
        if cur not in (prev, prev + 1):
            report["problems"].append(
                f"index gap: {prev} -> {cur} (entries lost above lowmark)"
            )
    report["live_records"] = len(live)
    report["ok"] = not report["problems"]
    return report
