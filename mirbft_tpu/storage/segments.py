"""Shared on-disk record framing for the storage engine (L4).

Both halves of the engine — the group-commit WAL (``storage/wal.py``) and
the log-structured request store (``storage/logstore.py``) — persist
append-only segment files built from one CRC-framed record shape::

    uvarint(payload_len) || uvarint(tag) || u32be crc32(payload) || payload

The ``tag`` is the WAL entry index for WAL segments and a record-type
discriminator for request-store segments.  The CRC is the recovery
contract: a scan stops at the first record whose length runs past the
file (a torn tail from a crash mid-append) *or* whose CRC does not match
(bit rot, or a torn tail that happens to parse), and the valid prefix is
everything before it.  Unlike ``simplewal``'s length-only framing, a torn
write can never smuggle garbage bytes into a decoded entry.

``fsync_dir`` closes the rename/create durability hole: after creating,
renaming, or unlinking a file inside a directory, the *directory* entry
itself must reach disk or a crash can resurrect an unlinked segment (or
lose a created one) — see docs/STORAGE.md "Recovery invariants".
"""

from __future__ import annotations

import os
import struct
import zlib
from pathlib import Path
from typing import Iterator, Tuple

from .. import wire

_CRC = struct.Struct(">I")

# Scan-stop reasons (``valid_prefix``): the whole file parsed, the last
# record was torn (crash mid-append; expected, survivable), or a CRC
# mismatch (corruption — survivable, but worth reporting loudly).
SCAN_CLEAN = "clean"
SCAN_TORN = "torn"
SCAN_CRC = "crc"


def encode_record(tag: int, payload: bytes) -> bytes:
    head = bytearray()
    wire.write_uvarint(head, len(payload))
    wire.write_uvarint(head, tag)
    head += _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF)
    return bytes(head) + payload


def iter_records(data: bytes) -> Iterator[Tuple[int, bytes, int, int]]:
    """Yield ``(tag, payload, start, end)`` for every valid record in the
    prefix of ``data``; stops silently at the first torn or corrupt one
    (use :func:`valid_prefix` to learn where and why)."""
    view = memoryview(data)
    pos = 0
    while pos < len(view):
        start = pos
        try:
            length, pos = wire.read_uvarint(view, pos)
            tag, pos = wire.read_uvarint(view, pos)
        except ValueError:
            return
        if pos + _CRC.size > len(view):
            return
        (crc,) = _CRC.unpack_from(view, pos)
        pos += _CRC.size
        if pos + length > len(view):
            return
        payload = bytes(view[pos : pos + length])
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return
        pos += length
        yield tag, payload, start, pos


def valid_prefix(data: bytes) -> Tuple[int, str]:
    """``(byte_length, reason)`` of the valid record prefix of ``data``.

    ``reason`` is SCAN_CLEAN when the file ends exactly on a record
    boundary, SCAN_TORN when the trailing bytes are an incomplete record,
    and SCAN_CRC when a complete-looking record failed its checksum."""
    view = memoryview(data)
    pos = 0
    while pos < len(view):
        start = pos
        try:
            length, pos = wire.read_uvarint(view, pos)
            _, pos = wire.read_uvarint(view, pos)
        except ValueError:
            return start, SCAN_TORN
        if pos + _CRC.size > len(view):
            return start, SCAN_TORN
        (crc,) = _CRC.unpack_from(view, pos)
        pos += _CRC.size
        if pos + length > len(view):
            return start, SCAN_TORN
        if zlib.crc32(view[pos : pos + length]) & 0xFFFFFFFF != crc:
            return start, SCAN_CRC
        pos += length
    return pos, SCAN_CLEAN


def cut_torn_tail(path: Path) -> int:
    """Truncate ``path`` to its valid record prefix (fsyncing the cut) and
    return the new length.  No-op when the file is already clean."""
    data = path.read_bytes()
    valid, reason = valid_prefix(data)
    if reason != SCAN_CLEAN:
        with open(path, "r+b") as fh:
            fh.truncate(valid)
            fh.flush()
            os.fsync(fh.fileno())
    return valid


def fsync_dir(path: Path) -> None:
    """fsync a directory so create/rename/unlink of its entries is durable.
    Best-effort on platforms whose directories reject O_RDONLY opens."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
