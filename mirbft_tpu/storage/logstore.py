"""Log-structured request store with checkpoint-keyed GC (L4).

``reqstore.Store`` (sqlite) never reclaims space: every request payload a
client ever submitted stays in the database forever.  This engine stores
the same keyspace — request payloads by ``(client_id, req_no, digest)``
and allocation digests by ``(client_id, req_no)`` — as append-only
CRC-framed segment files (``storage/segments.py``) with an **in-memory
index**, and garbage-collects **keyed to the stable-checkpoint
watermark**:

* ``note_checkpoint(index, watermarks)`` records the per-client low
  watermarks carried by a checkpoint ``CEntry`` the moment it is
  persisted (``processor/serial.py``).
* ``gc(index)`` runs when the state machine emits ``ActionTruncate`` for
  that entry — i.e. only once the checkpoint is *stable* (signed by a
  quorum; ``statemachine/persisted.py``).  Entries whose ``req_no`` is
  below their client's watermark are dead: compaction rewrites the live
  entries of mostly-dead sealed segments into the active segment and
  unlinks the old files atomically (fsync data, fsync directory, then
  unlink, then fsync directory again — see docs/STORAGE.md).

Durability matches the sqlite store's contract: ``sync()`` is the
barrier, and concurrent callers coalesce — the lock holder fsyncs once
and every waiter that queued behind it finds its writes already durable.

Metrics (docs/OBSERVABILITY.md): ``store_gc_reclaimed_bytes_total``.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .. import metrics, wire
from ..messages import RequestAck
from .segments import cut_torn_tail, encode_record, fsync_dir, iter_records

TAG_REQUEST = 1
TAG_ALLOCATION = 2
# GC marker: the per-client low watermarks a compaction applied.  Replay
# re-applies the newest one, or entries GC dropped from the index (but
# whose records sit in surviving, not-compacted segments) would resurrect
# on restart.
TAG_WATERMARK = 3

# Shared-state declaration for mirlint's lock-discipline pass: the index,
# segment table, and active file handle are shared across node worker
# threads, so every touch happens under the store lock
# (docs/STATIC_ANALYSIS.md).
MIRLINT_SHARED_STATE = {
    "LogStore._requests": "_lock",
    "LogStore._allocations": "_lock",
    "LogStore._segs": "_lock",
    "LogStore._active_id": "_lock",
    "LogStore._active_fh": "_lock",
    "LogStore._active_size": "_lock",
    "LogStore._seq": "_lock",
    "LogStore._durable_seq": "_lock",
    "LogStore._watermarks": "_lock",
    "LogStore._gc_low": "_lock",
    "LogStore._closed": "_lock",
}


def _encode_request(ack: RequestAck, data: bytes) -> Tuple[bytes, int]:
    """Returns ``(payload, data_offset_within_payload)``."""
    buf = bytearray()
    wire.write_uvarint(buf, ack.client_id)
    wire.write_uvarint(buf, ack.req_no)
    wire.write_uvarint(buf, len(ack.digest))
    buf += ack.digest
    wire.write_uvarint(buf, len(data))
    data_off = len(buf)
    buf += data
    return bytes(buf), data_off


def _decode_request(payload: bytes) -> Tuple[int, int, bytes, int, int]:
    """Returns ``(client_id, req_no, digest, data_off, data_len)``."""
    client_id, pos = wire.read_uvarint(payload, 0)
    req_no, pos = wire.read_uvarint(payload, pos)
    dlen, pos = wire.read_uvarint(payload, pos)
    digest = bytes(payload[pos : pos + dlen])
    pos += dlen
    data_len, pos = wire.read_uvarint(payload, pos)
    return client_id, req_no, digest, pos, data_len


def _encode_allocation(client_id: int, req_no: int, digest: bytes) -> bytes:
    buf = bytearray()
    wire.write_uvarint(buf, client_id)
    wire.write_uvarint(buf, req_no)
    wire.write_uvarint(buf, len(digest))
    buf += digest
    return bytes(buf)


def _decode_allocation(payload: bytes) -> Tuple[int, int, bytes]:
    client_id, pos = wire.read_uvarint(payload, 0)
    req_no, pos = wire.read_uvarint(payload, pos)
    dlen, pos = wire.read_uvarint(payload, pos)
    return client_id, req_no, bytes(payload[pos : pos + dlen])


def _encode_watermark(watermarks: Dict[int, int]) -> bytes:
    buf = bytearray()
    wire.write_uvarint(buf, len(watermarks))
    for client_id in sorted(watermarks):
        wire.write_uvarint(buf, client_id)
        wire.write_uvarint(buf, watermarks[client_id])
    return bytes(buf)


def _decode_watermark(payload: bytes) -> Dict[int, int]:
    count, pos = wire.read_uvarint(payload, 0)
    out: Dict[int, int] = {}
    for _ in range(count):
        client_id, pos = wire.read_uvarint(payload, pos)
        low, pos = wire.read_uvarint(payload, pos)
        out[client_id] = low
    return out


class LogStore:
    """File-backed ``processor.RequestStore`` over append-only segments."""

    def __init__(self, path: str, segment_max_bytes: int = 4 * 1024 * 1024):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = segment_max_bytes
        # RLock: the private append/rotate/read helpers re-acquire it
        # so their shared-state accesses are lexically guarded too.
        self._lock = threading.RLock()

        # (client_id, req_no, digest) -> (seg_id, file_data_off, data_len, rec_len)
        self._requests: Dict[Tuple[int, int, bytes], Tuple[int, int, int, int]] = {}
        # (client_id, req_no) -> (digest, seg_id, rec_len)
        self._allocations: Dict[Tuple[int, int], Tuple[bytes, int, int]] = {}
        self._segs: Dict[int, Path] = {}
        self._watermarks: Dict[int, Dict[int, int]] = {}
        self._gc_low: Dict[int, int] = {}
        self._seq = 0
        self._durable_seq = 0
        self._closed = False

        self._reclaimed = metrics.counter("store_gc_reclaimed_bytes_total")

        seg_ids = sorted(
            int(p.name[6:-4])
            for p in self.dir.iterdir()
            if p.name.startswith("store-") and p.name.endswith(".seg")
        )
        for seg_id in seg_ids:
            self._segs[seg_id] = self.dir / f"store-{seg_id}.seg"
        if seg_ids:
            # Only the highest-id segment can have a torn tail (it was the
            # append target at crash time); cut it before replay.
            cut_torn_tail(self._segs[seg_ids[-1]])
        for seg_id in seg_ids:
            self._replay_segment(seg_id)
        if self._gc_low:
            # Re-apply the newest persisted GC watermark: dead entries in
            # surviving segments must stay dead across a restart.
            low = self._gc_low
            self._requests = {
                k: v
                for k, v in self._requests.items()
                if k[1] >= low.get(k[0], 0)
            }
            self._allocations = {
                k: v
                for k, v in self._allocations.items()
                if k[1] >= low.get(k[0], 0)
            }

        self._active_id = (seg_ids[-1] if seg_ids else 0) + 1
        active_path = self.dir / f"store-{self._active_id}.seg"
        self._segs[self._active_id] = active_path
        self._active_fh = open(active_path, "ab")
        self._active_size = 0
        fsync_dir(self.dir)

    def _replay_segment(self, seg_id: int) -> None:
        # __init__ only; later records override earlier ones (same
        # last-write-wins the sqlite store gets from INSERT OR REPLACE).
        with self._lock:
            data = self._segs[seg_id].read_bytes()
            for tag, payload, start, end in iter_records(data):
                head = end - start - len(payload)
                if tag == TAG_REQUEST:
                    cid, req_no, digest, data_off, data_len = _decode_request(payload)
                    self._requests[(cid, req_no, digest)] = (
                        seg_id, start + head + data_off, data_len, end - start,
                    )
                elif tag == TAG_ALLOCATION:
                    cid, req_no, digest = _decode_allocation(payload)
                    self._allocations[(cid, req_no)] = (digest, seg_id, end - start)
                elif tag == TAG_WATERMARK:
                    self._gc_low = _decode_watermark(payload)

    # --- append path (callers hold self._lock; RLock re-entry is free) ---

    def _append(self, tag: int, payload: bytes) -> Tuple[int, int, int]:
        """Append one record to the active segment; returns
        ``(seg_id, payload_file_off, rec_len)``."""
        with self._lock:
            if self._active_size >= self.segment_max_bytes:
                self._rotate()
            frame = encode_record(tag, payload)
            seg_id = self._active_id
            payload_off = self._active_size + (len(frame) - len(payload))
            self._active_fh.write(frame)
            self._active_size += len(frame)
            self._seq += 1
            return seg_id, payload_off, len(frame)

    def _rotate(self) -> None:
        with self._lock:
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            self._active_fh.close()
            self._active_id += 1
            path = self.dir / f"store-{self._active_id}.seg"
            self._segs[self._active_id] = path
            self._active_fh = open(path, "ab")
            self._active_size = 0
            fsync_dir(self.dir)

    def _read(self, seg_id: int, off: int, length: int) -> bytes:
        with self._lock:
            if seg_id == self._active_id:
                self._active_fh.flush()
            with open(self._segs[seg_id], "rb") as fh:
                fh.seek(off)
                return fh.read(length)

    # --- RequestStore protocol ---

    def put_request(self, ack: RequestAck, data: bytes) -> None:
        payload, data_off = _encode_request(ack, data)
        with self._lock:
            seg_id, payload_off, rec_len = self._append(TAG_REQUEST, payload)
            self._requests[(ack.client_id, ack.req_no, ack.digest)] = (
                seg_id, payload_off + data_off, len(data), rec_len,
            )

    def get_request(self, ack: RequestAck) -> Optional[bytes]:
        with self._lock:
            loc = self._requests.get((ack.client_id, ack.req_no, ack.digest))
            if loc is None:
                return None
            seg_id, data_off, data_len, _ = loc
            return self._read(seg_id, data_off, data_len)

    def put_allocation(self, client_id: int, req_no: int, digest: bytes) -> None:
        payload = _encode_allocation(client_id, req_no, digest)
        with self._lock:
            seg_id, _, rec_len = self._append(TAG_ALLOCATION, payload)
            self._allocations[(client_id, req_no)] = (digest, seg_id, rec_len)

    def get_allocation(self, client_id: int, req_no: int) -> Optional[bytes]:
        with self._lock:
            loc = self._allocations.get((client_id, req_no))
            return loc[0] if loc is not None else None

    def sync(self) -> None:
        """Durability barrier with group fsync: the lock holder fsyncs for
        everything appended so far, so callers that queued behind it find
        ``_durable_seq`` already past their writes and return without
        touching the device."""
        with self._lock:
            if self._closed:
                raise ValueError("request store is closed")
            if self._durable_seq >= self._seq:
                return
            target = self._seq
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            self._durable_seq = target

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            self._active_fh.close()

    # --- checkpoint-keyed GC ---

    def note_checkpoint(self, index: int, watermarks: Dict[int, int]) -> None:
        """Record per-client low watermarks carried by the checkpoint entry
        persisted at WAL ``index`` (not yet authoritative — the checkpoint
        may never become stable)."""
        with self._lock:
            self._watermarks[index] = dict(watermarks)

    def gc(self, index: int) -> int:
        """Compact using the newest noted checkpoint at or below WAL
        ``index`` — called when the state machine truncates its log there,
        i.e. once that checkpoint is stable.  Returns bytes reclaimed."""
        with self._lock:
            noted = [i for i in self._watermarks if i <= index]
            if not noted:
                return 0
            anchor = max(noted)
            watermarks = self._watermarks[anchor]
            for i in noted:
                if i != anchor:
                    del self._watermarks[i]

            # Persist the applied watermark before any compaction so a
            # replay filters the same dead set this pass drops.
            self._gc_low = dict(watermarks)
            self._append(TAG_WATERMARK, _encode_watermark(watermarks))

            def dead(client_id: int, req_no: int) -> bool:
                low = watermarks.get(client_id)
                return low is not None and req_no < low

            self._requests = {
                k: v for k, v in self._requests.items() if not dead(k[0], k[1])
            }
            self._allocations = {
                k: v for k, v in self._allocations.items() if not dead(k[0], k[1])
            }

            # Per-segment live accounting over the sealed segments.
            live_bytes: Dict[int, int] = {
                seg_id: 0 for seg_id in self._segs if seg_id != self._active_id
            }
            live_reqs: Dict[int, List[Tuple[int, int, bytes]]] = {}
            live_allocs: Dict[int, List[Tuple[int, int]]] = {}
            for key, (seg_id, _, _, rec_len) in self._requests.items():
                if seg_id in live_bytes:
                    live_bytes[seg_id] += rec_len
                    live_reqs.setdefault(seg_id, []).append(key)
            for key, (_, seg_id, rec_len) in self._allocations.items():
                if seg_id in live_bytes:
                    live_bytes[seg_id] += rec_len
                    live_allocs.setdefault(seg_id, []).append(key)

            reclaimed = 0
            victims = []
            for seg_id, live in live_bytes.items():
                size = self._segs[seg_id].stat().st_size
                if size == 0 or live == 0 or live <= size // 2:
                    victims.append((seg_id, size))
            moved = 0
            for seg_id, size in sorted(victims):
                for key in live_reqs.get(seg_id, []):
                    old_seg, data_off, data_len, _ = self._requests[key]
                    data = self._read(old_seg, data_off, data_len)
                    payload, doff = _encode_request(
                        RequestAck(client_id=key[0], req_no=key[1], digest=key[2]),
                        data,
                    )
                    new_seg, payload_off, rec_len = self._append(TAG_REQUEST, payload)
                    self._requests[key] = (
                        new_seg, payload_off + doff, data_len, rec_len,
                    )
                    moved += rec_len
                for key in live_allocs.get(seg_id, []):
                    digest, _, _ = self._allocations[key]
                    payload = _encode_allocation(key[0], key[1], digest)
                    new_seg, _, rec_len = self._append(TAG_ALLOCATION, payload)
                    self._allocations[key] = (digest, new_seg, rec_len)
                    moved += rec_len
            if not victims:
                return 0
            # Rewritten entries must be durable before the originals
            # vanish, and the unlinks must be durable before we report
            # the space reclaimed.
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            self._durable_seq = self._seq
            for seg_id, size in victims:
                self._segs[seg_id].unlink()
                del self._segs[seg_id]
                reclaimed += size
            fsync_dir(self.dir)
            reclaimed -= moved
            if reclaimed > 0:
                self._reclaimed.inc(reclaimed)
            return max(reclaimed, 0)
