"""Multi-chip sharding of the crypto workload.

Consensus messages are small and point-to-point (they ride the ``Link``
abstraction over DCN); what scales with replica count and load is the crypto
batch — digests and signature verifications.  This package shards that batch
dimension over a ``jax.sharding.Mesh`` so one hash/verify dispatch spans all
local chips, with XLA collectives (psum) aggregating verification verdicts
over ICI.
"""

from .mesh import (
    distributed_verify_step,
    sharded_ed25519_verify,
    make_mesh,
    sharded_sha256,
)

__all__ = [
    "distributed_verify_step",
    "make_mesh",
    "sharded_ed25519_verify",
    "sharded_sha256",
]
