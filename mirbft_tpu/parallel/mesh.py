"""Device-mesh sharding of the batched crypto kernels.

The design follows the standard JAX recipe: pick a mesh, annotate shardings,
let XLA insert the collectives.  The crypto batch is pure data parallelism —
each row (message) is independent — so the batch dimension shards over the
``"batch"`` axis and digests come back sharded the same way.  The
distributed verify step adds the one genuine collective of the workload: a
``psum`` over per-shard verification verdicts, so every chip learns the
global "all batches verified" outcome without the host gathering digests.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    _shard_map = jax.shard_map
except AttributeError:  # pre-0.5 jax: experimental module, check_rep kwarg

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
        )

from ..ops.sha256 import _sha256_padded

BATCH_AXIS = "batch"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the local devices; the crypto batch shards across it."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def _sha256_rows(blocks: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """Local (per-shard) batched SHA-256: [b, L, 16] x [b] -> [b, 8]."""
    return jax.vmap(_sha256_padded)(blocks, n_blocks)


def sharded_sha256(mesh: Mesh):
    """A jitted batched-SHA-256 whose batch dimension is sharded over the
    mesh.  Inputs [B, L, 16] / [B]; B must divide by the mesh size."""
    spec = P(BATCH_AXIS)
    return jax.jit(
        _sha256_rows,
        in_shardings=(
            NamedSharding(mesh, P(BATCH_AXIS, None, None)),
            NamedSharding(mesh, spec),
        ),
        out_shardings=NamedSharding(mesh, P(BATCH_AXIS, None)),
    )


def distributed_verify_step(mesh: Mesh):
    """The full distributed crypto step: hash every (padded) message shard-
    locally, compare against expected digests, and ``psum`` the mismatch
    count over ICI so every chip holds the global verdict.

    This is the multi-chip shape of the epoch-change / forwarded-batch
    verification flow (``VerifyBatchOrigin``): digests stay on-device; only
    the 1-word verdict is exchanged.
    """

    def step(blocks, n_blocks, expected_words):
        # blocks [b, L, 16], n_blocks [b], expected_words [b, 8] (per shard)
        digests = _sha256_rows(blocks, n_blocks)
        mismatches = jnp.sum(
            jnp.any(digests != expected_words, axis=-1).astype(jnp.uint32)
        )
        total_mismatches = jax.lax.psum(mismatches, BATCH_AXIS)
        return digests, total_mismatches

    mapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS, None, None), P(BATCH_AXIS), P(BATCH_AXIS, None)),
        out_specs=(P(BATCH_AXIS, None), P()),
        # The SHA-256 scan carries start from unvarying constants (_H0);
        # varying-manual-axis checking would require pvary-ing every carry.
        check_vma=False,
    )
    return jax.jit(mapped)


def sharded_ed25519_verify(mesh: Mesh, kernel: str = "vpu"):
    """Batched Ed25519 verification with the batch dimension sharded over
    the mesh, plus the byzantine-signer collective: every shard verifies its
    rows locally and a ``psum`` over ICI gives every chip the global count
    of invalid signatures among the REAL rows (the f-byzantine-signers
    detection of BASELINE config 5 at multi-chip scale).

    Inputs: the packed kernel arrays from
    ``Ed25519BatchVerifier.pack_inputs`` plus ``real`` — a [B] bool mask of
    rows that carry actual signatures (padding rows are False and are
    excluded from the count; a real row whose signature is structurally
    invalid — ``valid`` False — counts as invalid).  The mesh size must
    divide the batch.  ``kernel`` picks the field-multiply backend
    ("vpu" default, as for ``Ed25519BatchVerifier``).
    """
    from ..ops.ed25519 import _mul_mxu, _mul_vpu, _verify_kernel_body

    if kernel not in ("mxu", "vpu"):
        raise ValueError(f"unknown ed25519 kernel backend {kernel!r}")
    mul = _mul_mxu if kernel == "mxu" else _mul_vpu

    def step(ax, ay, r_bytes, s_bits, h_bits, valid, real):
        ok = _verify_kernel_body(ax, ay, r_bytes, s_bits, h_bits, mul)
        ok = jnp.logical_and(ok, valid)
        invalid = jax.lax.psum(
            jnp.sum(
                jnp.logical_and(real, jnp.logical_not(ok)).astype(jnp.uint32)
            ),
            BATCH_AXIS,
        )
        return ok, invalid

    row = P(BATCH_AXIS, None)
    mapped = _shard_map(
        step,
        mesh=mesh,
        in_specs=(row, row, row, row, row, P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=(P(BATCH_AXIS), P()),
        # Same rationale as distributed_verify_step: the ladder scan carries
        # start from unvarying curve constants.
        check_vma=False,
    )
    return jax.jit(mapped)
