"""Flight-recorder journal: segmented, CRC-framed, bounded event logs.

The always-on successor to the single-gzip-stream ``record.Recorder``
(docs/OBSERVABILITY.md "Flight recorder").  Each boot of a node appends
``seg-<boot>-<index>.evj`` files under ``<node_dir>/journal/``, framed with
the storage engine's CRC record shape (``storage/segments.py``), so a
SIGKILL mid-write costs exactly the torn tail (``cut_torn_tail``) and a
flipped bit is caught by the CRC, never decoded.

Record tags inside a segment::

    TAG_BOOT        uvarint(node_id) || uvarint(boot) || uvarint(seg_index)
    TAG_EVENT       wire.encode(RecordedEvent)
    TAG_TRACE       uvarint(trace_id)   -- annotates the NEXT TAG_EVENT
    TAG_CHECKPOINT  uvarint(seq_no)     -- stable checkpoint / state transfer
    TAG_GAP         uvarint(count)      -- events dropped under overflow

``RecordedEvent``'s wire shape is frozen (append-only registry), so the
fleet trace-id annotation lives in the journal framing — a ``TAG_TRACE``
record ahead of the event — not inside the event itself.

Bounding is two-fold, mirroring ``logstore.py`` GC:

* **Rotation** by bytes: a segment past ``rotate_bytes`` is sealed
  (fsync + close) and a fresh one opened.
* **Retention** keyed to stable checkpoints: sealed segments strictly
  older than the segment holding the ``retain_checkpoints``-th most
  recent ``TAG_CHECKPOINT`` marker are deleted, and boots older than the
  ``retain_boots`` most recent are pruned at startup.  A reader sees a
  pruned head as ``pruned`` (partial history), never as divergence.

Overflow never blocks consensus: :class:`JournalRecorder.intercept` is a
``put_nowait`` and, on a full queue, drops the *oldest* buffered record
(``eventlog_dropped_events_total``) so the journal keeps the most recent
window; the writer thread inserts a ``TAG_GAP`` marker so replay tooling
knows the boot is gapped instead of silently divergent.
"""

from __future__ import annotations

import os
import queue
import threading
import time as _time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from .. import metrics as metrics_mod
from .. import state as st
from .. import wire
from ..messages import RequestAck
from ..storage import segments
from .record import _strip_request_data, read_event_log

JOURNAL_DIRNAME = "journal"
SEGMENT_EXT = ".evj"

TAG_BOOT = 1
TAG_EVENT = 2
TAG_TRACE = 3
TAG_CHECKPOINT = 4
TAG_GAP = 5
# Observer plane: an applied committed-batch journal line (observers have
# no state machine, so their flight record is the applied stream itself):
# uvarint(seq_no) || utf-8 commit line.
TAG_APPLY = 6

DEFAULT_ROTATE_BYTES = 512 * 1024
DEFAULT_RETAIN_CHECKPOINTS = 3
DEFAULT_RETAIN_BOOTS = 3


def _uvarint(value: int) -> bytes:
    buf = bytearray()
    wire.write_uvarint(buf, value)
    return bytes(buf)


def _read_uvarint(payload: bytes) -> int:
    value, _ = wire.read_uvarint(memoryview(payload), 0)
    return value


def _segment_name(boot: int, index: int) -> str:
    return f"seg-{boot:03d}-{index:06d}{SEGMENT_EXT}"


def _segment_files(dir_path: Path) -> List[Tuple[int, int, Path]]:
    """Sorted ``(boot, index, path)`` for every journal segment file."""
    out: List[Tuple[int, int, Path]] = []
    if not dir_path.is_dir():
        return out
    for path in sorted(dir_path.glob(f"seg-*{SEGMENT_EXT}")):
        parts = path.name[: -len(SEGMENT_EXT)].split("-")
        if len(parts) != 3:
            continue
        try:
            out.append((int(parts[1]), int(parts[2]), path))
        except ValueError:
            continue
    out.sort(key=lambda t: (t[0], t[1]))
    return out


class SegmentSink:
    """Synchronous segmented record sink: rotation by bytes, retention
    keyed to checkpoint markers.  Single-writer by contract (the
    recorder's writer thread, or the observer's apply loop), so it needs
    no lock."""

    def __init__(
        self,
        dir_path: Path,
        node_id: int,
        *,
        boot: Optional[int] = None,
        rotate_bytes: int = DEFAULT_ROTATE_BYTES,
        retain_checkpoints: int = DEFAULT_RETAIN_CHECKPOINTS,
        retain_boots: int = DEFAULT_RETAIN_BOOTS,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.dir = Path(dir_path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.node_id = node_id
        self.rotate_bytes = rotate_bytes
        self.retain_checkpoints = retain_checkpoints
        self.retain_boots = retain_boots
        registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self._bytes_total = registry.counter(
            "eventlog_bytes_total", labels={"node": str(node_id)}
        )

        existing = _segment_files(self.dir)
        prior_boots = sorted({b for b, _, _ in existing})
        if boot is None:
            boot = prior_boots[-1] + 1 if prior_boots else 0
        self.boot = boot
        # A crash can only tear the last segment of the last prior boot;
        # cutting it here means every later reader scans a clean file.
        if prior_boots:
            last_boot_files = [p for b, _, p in existing if b == prior_boots[-1]]
            try:
                segments.cut_torn_tail(last_boot_files[-1])
            except OSError:
                pass  # read-only media: readers still stop at the tear
        # Boot retention: keep the newest (retain_boots - 1) prior boots.
        keep_from = (
            prior_boots[-(self.retain_boots - 1)]
            if self.retain_boots > 1 and len(prior_boots) >= self.retain_boots
            else (boot if self.retain_boots <= 1 else -1)
        )
        pruned_any = False
        for b, _, path in existing:
            if b < keep_from:
                try:
                    path.unlink()
                    pruned_any = True
                except OSError:
                    pass
        if pruned_any:
            segments.fsync_dir(self.dir)

        self._seg_index = 0
        self._seg_bytes = 0
        self._file = None
        # (seq_no, seg_index) of recent checkpoint markers; retention floor.
        self._checkpoint_marks: List[Tuple[int, int]] = []
        self._open_segment()

    # -- segment lifecycle --------------------------------------------------

    def _open_segment(self) -> None:
        path = self.dir / _segment_name(self.boot, self._seg_index)
        self._file = open(path, "ab")
        self._seg_bytes = 0
        header = (
            _uvarint(self.node_id)
            + _uvarint(self.boot)
            + _uvarint(self._seg_index)
        )
        self._write(TAG_BOOT, header)

    def _seal_segment(self) -> None:
        self._file.flush()
        try:
            os.fsync(self._file.fileno())
        except OSError:
            pass
        self._file.close()

    def _rotate(self) -> None:
        self._seal_segment()
        self._seg_index += 1
        self._open_segment()
        segments.fsync_dir(self.dir)

    def _write(self, tag: int, payload: bytes) -> None:
        record = segments.encode_record(tag, payload)
        self._file.write(record)
        self._seg_bytes += len(record)
        self._bytes_total.inc(len(record))

    # -- public api ---------------------------------------------------------

    def append(self, tag: int, payload: bytes) -> None:
        self._write(tag, payload)
        if self._seg_bytes >= self.rotate_bytes:
            self._rotate()

    def note_checkpoint(self, seq_no: int) -> None:
        """Record a stable-checkpoint marker and apply retention: sealed
        segments strictly older than the ``retain_checkpoints``-th most
        recent marker's segment are history the checkpoint already
        covers."""
        self.append(TAG_CHECKPOINT, _uvarint(seq_no))
        self._checkpoint_marks.append((seq_no, self._seg_index))
        if len(self._checkpoint_marks) < self.retain_checkpoints:
            return
        self._checkpoint_marks = self._checkpoint_marks[
            -self.retain_checkpoints :
        ]
        floor_seg = self._checkpoint_marks[0][1]
        removed = False
        for b, index, path in _segment_files(self.dir):
            if b == self.boot and index < floor_seg:
                try:
                    path.unlink()
                    removed = True
                except OSError:
                    pass
        if removed:
            segments.fsync_dir(self.dir)

    def flush(self) -> None:
        self._file.flush()

    def close(self) -> None:
        self._seal_segment()


class JournalRecorder:
    """Async flight recorder implementing the processor's
    ``EventInterceptor`` protocol over a :class:`SegmentSink`.

    The hot-path ``intercept`` is a non-blocking enqueue: on overflow the
    oldest buffered record is dropped (counted in
    ``eventlog_dropped_events_total``) and the writer inserts a TAG_GAP
    marker, so a slow disk degrades the journal, never consensus.  When a
    ``trace_lookup`` callable is bound (``Node`` binds its trace-binding
    LRU automatically), recorded ``EventStep``s that name a request carry
    the request's fleet trace id as a TAG_TRACE annotation.
    """

    def __init__(
        self,
        node_dir,
        node_id: int,
        *,
        time_source: Optional[Callable[[], int]] = None,
        retain_request_data: bool = True,
        buffer_size: int = 5000,
        rotate_bytes: int = DEFAULT_ROTATE_BYTES,
        retain_checkpoints: int = DEFAULT_RETAIN_CHECKPOINTS,
        retain_boots: int = DEFAULT_RETAIN_BOOTS,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.node_id = node_id
        # Default wall-clock ms mirrors record.Recorder; deployments pass a
        # monotonic source so the doctor's replay clock is restart-safe.
        # mirlint: allow(wall-clock) — timestamp metadata, never ordering
        self.time_source = time_source or (lambda: int(_time.time() * 1000))
        self.retain_request_data = retain_request_data
        # None is reserved as the shutdown sentinel; Node.__init__ binds
        # its (client_id, req_no) -> trace id LRU here when it sees the
        # attribute (docs/OBSERVABILITY.md "Fleet plane").
        self.trace_lookup: Optional[Callable[[int, int], Optional[int]]] = None
        self.dropped_events = 0  # producer-side ledger (tests, reports)
        registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self._dropped = registry.counter(
            "eventlog_dropped_events_total", labels={"node": str(node_id)}
        )
        self._sink = SegmentSink(
            Path(node_dir) / JOURNAL_DIRNAME,
            node_id,
            rotate_bytes=rotate_bytes,
            retain_checkpoints=retain_checkpoints,
            retain_boots=retain_boots,
            registry=registry,
        )
        self.boot = self._sink.boot
        self._queue: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        # Overflow accounting without a lock: _gap_noted is written only by
        # the producer (intercept), _gap_acked only by the writer thread.
        self._gap_noted = 0
        self._gap_acked = 0
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- hot path -----------------------------------------------------------

    def _trace_of(self, event: st.Event) -> int:
        lookup = self.trace_lookup
        if lookup is None or not isinstance(event, st.EventStep):
            return 0
        msg = event.msg
        ack = getattr(msg, "request_ack", None)
        if ack is None and isinstance(msg, RequestAck):
            ack = msg
        if ack is None:
            return 0
        try:
            return lookup(ack.client_id, ack.req_no) or 0
        except Exception:
            return 0  # a racing LRU eviction only costs the annotation

    def intercept(self, event: st.Event) -> None:
        if self._error is not None:
            raise RuntimeError("event recorder failed") from self._error
        if self._done.is_set() or self._stopped:
            raise RuntimeError("event recorder already stopped")
        if not self.retain_request_data:
            event = _strip_request_data(event)
        item = (
            st.RecordedEvent(
                node_id=self.node_id,
                time=self.time_source(),
                state_event=event,
            ),
            self._trace_of(event),
        )
        try:
            self._queue.put_nowait(item)
            return
        except queue.Full:
            pass
        # Overflow: evict the oldest buffered record to keep the most
        # recent window — the hot path must never wait on the writer.
        try:
            victim = self._queue.get_nowait()
            if victim is None:
                # Never swallow the shutdown sentinel (stop() race).
                try:
                    self._queue.put_nowait(None)
                except queue.Full:
                    pass
            else:
                self._gap_noted += 1
                self.dropped_events += 1
                self._dropped.inc()
        except queue.Empty:
            pass
        try:
            self._queue.put_nowait(item)
        except queue.Full:  # lost the race for the freed slot: drop new
            self._gap_noted += 1
            self.dropped_events += 1
            self._dropped.inc()

    # -- writer thread ------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                item = self._queue.get()
                if item is None:
                    break
                gap = self._gap_noted - self._gap_acked
                if gap > 0:
                    self._gap_acked += gap
                    self._sink.append(TAG_GAP, _uvarint(gap))
                record, trace_id = item
                if trace_id:
                    self._sink.append(TAG_TRACE, _uvarint(trace_id))
                self._sink.append(TAG_EVENT, wire.encode(record))
                event = record.state_event
                if isinstance(event, st.EventCheckpointResult):
                    self._sink.note_checkpoint(event.seq_no)
                elif isinstance(event, st.EventStateTransferComplete):
                    # Journal hand-off on snapshot state transfer: the jump
                    # target is a retention anchor and tells the audit the
                    # replay baseline moved (no divergence across the gap).
                    self._sink.note_checkpoint(event.seq_no)
        except BaseException as e:  # surfaced on next intercept/stop
            self._error = e
        finally:
            try:
                self._sink.close()
            except BaseException as e:
                if self._error is None:
                    self._error = e
            self._done.set()

    def stop(self) -> None:
        """Flush and seal; the recorder cannot be used afterwards."""
        self._stopped = True
        while not self._done.is_set():
            try:
                self._queue.put(None, timeout=0.1)
                break
            except queue.Full:
                continue  # writer died or is draining; re-check _done
        self._done.wait()
        if self._error is not None:
            raise RuntimeError("event recorder failed") from self._error


# ---------------------------------------------------------------------------
# Readers
# ---------------------------------------------------------------------------


@dataclass
class BootLog:
    """One boot's worth of journal evidence, decoded and classified."""

    boot: int
    source: str  # "journal" | "legacy"
    paths: List[Path] = field(default_factory=list)
    # (record, trace_id) in append order; trace_id 0 when unannotated.
    records: List[Tuple[st.RecordedEvent, int]] = field(default_factory=list)
    # Observer journals: (seq_no, commit line) applied-batch stream.
    applies: List[Tuple[int, str]] = field(default_factory=list)
    checkpoints: List[int] = field(default_factory=list)
    dropped: int = 0  # events lost to overflow (TAG_GAP sums)
    torn: bool = False  # a tail was cut short by a crash
    crc_damage: bool = False  # a record failed its checksum
    pruned: bool = False  # retention removed the head of this boot
    error: Optional[str] = None


def _read_journal_boot(boot: int, files: List[Tuple[int, Path]]) -> BootLog:
    log = BootLog(boot=boot, source="journal")
    first_index: Optional[int] = None
    for index, path in files:
        log.paths.append(path)
        if first_index is None:
            first_index = index
        try:
            data = path.read_bytes()
        except OSError as exc:
            log.error = f"{path}: {exc}"
            continue
        _, reason = segments.valid_prefix(data)
        if reason == segments.SCAN_TORN:
            log.torn = True
        elif reason == segments.SCAN_CRC:
            log.crc_damage = True
        pending_trace = 0
        for tag, payload, _, _ in segments.iter_records(data):
            if tag == TAG_EVENT:
                try:
                    record = wire.decode(payload)
                except ValueError as exc:
                    log.error = f"{path}: {exc}"
                    pending_trace = 0
                    continue
                if isinstance(record, st.RecordedEvent):
                    log.records.append((record, pending_trace))
                pending_trace = 0
            elif tag == TAG_TRACE:
                pending_trace = _read_uvarint(payload)
            elif tag == TAG_APPLY:
                view = memoryview(payload)
                seq, pos = wire.read_uvarint(view, 0)
                log.applies.append((seq, bytes(view[pos:]).decode()))
            elif tag == TAG_CHECKPOINT:
                log.checkpoints.append(_read_uvarint(payload))
            elif tag == TAG_GAP:
                log.dropped += _read_uvarint(payload)
            # TAG_BOOT is self-describing; unknown tags skip forward-compat.
    log.pruned = bool(first_index)
    return log


def _read_legacy_boot(boot: int, path: Path) -> BootLog:
    log = BootLog(boot=boot, source="legacy", paths=[path])
    try:
        with open(path, "rb") as f:
            for record in read_event_log(f):
                log.records.append((record, 0))
    except Exception as exc:  # torn gzip / partial frame after SIGKILL
        log.torn = True
        log.error = f"{path}: {exc!r}"
    return log


def load_boots(node_dir) -> List[BootLog]:
    """Every boot's journal under ``node_dir``, oldest first.

    Reads both layouts: legacy ``events-<boot>.gz`` single-stream logs and
    the segmented ``journal/`` directory.  Torn tails come back clean-cut
    (``torn=True``, nothing decoded past the tear) — a crash is evidence,
    never an error."""
    node_dir = Path(node_dir)
    out: List[BootLog] = []
    for path in sorted(node_dir.glob("events-*.gz")):
        try:
            boot = int(path.name[len("events-") : -len(".gz")])
        except ValueError:
            boot = len(out)
        out.append(_read_legacy_boot(boot, path))
    by_boot: dict = {}
    for boot, index, path in _segment_files(node_dir / JOURNAL_DIRNAME):
        by_boot.setdefault(boot, []).append((index, path))
    for boot in sorted(by_boot):
        out.append(_read_journal_boot(boot, sorted(by_boot[boot])))
    return out


def journal_bytes(node_dir) -> int:
    """Total on-disk journal footprint for one node directory."""
    total = 0
    for _, _, path in _segment_files(Path(node_dir) / JOURNAL_DIRNAME):
        try:
            total += path.stat().st_size
        except OSError:
            pass
    return total
