"""Event-log recorder/reader.

Rebuild of reference ``pkg/eventlog/interceptor.go``: an asynchronous,
buffered, gzip-compressed stream of length-prefixed ``RecordedEvent``s.  The
writer thread drains a bounded queue so the interceptor call on the hot path
is a cheap enqueue (the reference's default buffer is 5000 events); options
mirror the reference's (time source, request-data retention, compression
level, buffer size).
"""

from __future__ import annotations

import gzip
import queue
import threading
import time as _time
from typing import BinaryIO, Callable, Iterator, Optional

from .. import state as st
from .. import wire
from ..messages import ForwardRequest


def write_recorded_event(stream: BinaryIO, record: st.RecordedEvent) -> None:
    wire.write_framed(stream, record)


def _strip_request_data(event: st.Event) -> st.Event:
    """Drop request payloads from recorded events (they can dominate log
    size; reference interceptor.go retain-request-data option)."""
    if isinstance(event, st.EventStep) and isinstance(event.msg, ForwardRequest):
        return st.EventStep(
            source=event.source,
            msg=ForwardRequest(
                request_ack=event.msg.request_ack, request_data=b""
            ),
        )
    return event


class Recorder:
    """Async buffered gzip event recorder implementing the processor's
    ``EventInterceptor`` protocol (reference interceptor.go:84-233)."""

    def __init__(
        self,
        node_id: int,
        dest: BinaryIO,
        time_source: Optional[Callable[[], int]] = None,
        retain_request_data: bool = False,
        compression_level: int = 6,
        buffer_size: int = 5000,
    ):
        self.node_id = node_id
        # Wall-clock default matches the reference; it is timestamp metadata
        # on the record, never replay ordering.
        # mirlint: allow(wall-clock)
        self.time_source = time_source or (lambda: int(_time.time() * 1000))
        self.dropped_events = 0
        self.retain_request_data = retain_request_data
        self._queue: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self._gzip = gzip.GzipFile(
            fileobj=dest, mode="wb", compresslevel=compression_level
        )
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def intercept(self, event: st.Event) -> None:
        if self._error is not None:
            raise RuntimeError("event recorder failed") from self._error
        if self._done.is_set() or self._stopped:
            raise RuntimeError("event recorder already stopped")
        if not self.retain_request_data:
            event = _strip_request_data(event)
        record = st.RecordedEvent(
            node_id=self.node_id, time=self.time_source(), state_event=event
        )
        # Non-blocking overflow (flight-recorder policy, see journal.py):
        # the old 0.1 s retry loop could stall consensus indefinitely behind
        # an alive-but-slow writer.  On a full queue, evict the oldest
        # buffered record so the log keeps the most recent window and the
        # hot path never waits.
        try:
            self._queue.put_nowait(record)
            return
        except queue.Full:
            pass
        try:
            victim = self._queue.get_nowait()
            if victim is None:
                # Never swallow the shutdown sentinel (stop() race).
                try:
                    self._queue.put_nowait(None)
                except queue.Full:
                    pass
            else:
                self.dropped_events += 1
        except queue.Empty:
            pass
        try:
            self._queue.put_nowait(record)
        except queue.Full:  # lost the race for the freed slot: drop new
            self.dropped_events += 1

    def _run(self) -> None:
        try:
            while True:
                record = self._queue.get()
                if record is None:
                    break
                write_recorded_event(self._gzip, record)
        except BaseException as e:  # surfaced on next intercept/stop
            self._error = e
        finally:
            try:
                self._gzip.close()
            except BaseException as e:
                if self._error is None:
                    self._error = e
            self._done.set()

    def stop(self) -> None:
        """Flush and close; the recorder cannot be used afterwards."""
        self._stopped = True
        while not self._done.is_set():
            try:
                self._queue.put(None, timeout=0.1)
                break
            except queue.Full:
                continue  # writer died or is draining; re-check _done
        self._done.wait()
        if self._error is not None:
            raise RuntimeError("event recorder failed") from self._error


def read_event_log(stream: BinaryIO) -> Iterator[st.RecordedEvent]:
    """Stream records from a gzip event log (reference interceptor.go:235-289)."""
    with gzip.GzipFile(fileobj=stream, mode="rb") as gz:
        while True:
            record = wire.read_framed(gz)
            if record is None:
                return
            if not isinstance(record, st.RecordedEvent):
                raise ValueError(
                    f"event log contains non-record type {type(record).__name__}"
                )
            yield record
