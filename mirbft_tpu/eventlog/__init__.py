"""Event-log recording and replay (L5 observability).

Rebuild of reference ``pkg/eventlog``: every event entering a state machine
is tapped through an ``EventInterceptor`` and recorded — with node id and
fake/wall time — as canonical records, enabling byte-exact deterministic
replay (``mirbft_tpu.tools.mircat``).  Two recorders exist:

* :class:`Recorder` — the reference-shaped single gzip stream (testengine,
  legacy deployments).
* :class:`JournalRecorder` — the always-on flight recorder: segmented,
  CRC-framed, checkpoint-retained journal files with non-blocking overflow
  and trace-id annotation (``journal.py``), plus the incident capture /
  replay plane (``incident.py``).
"""

from .journal import (
    BootLog,
    JournalRecorder,
    SegmentSink,
    journal_bytes,
    load_boots,
)
from .record import Recorder, read_event_log, write_recorded_event

__all__ = [
    "BootLog",
    "JournalRecorder",
    "Recorder",
    "SegmentSink",
    "journal_bytes",
    "load_boots",
    "read_event_log",
    "write_recorded_event",
]
