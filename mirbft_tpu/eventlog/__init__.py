"""Event-log recording and replay (L5 observability).

Rebuild of reference ``pkg/eventlog``: every event entering a state machine
is tapped through an ``EventInterceptor`` and appended — with node id and
fake/wall time — to a gzip-compressed stream of length-prefixed canonical
records, enabling byte-exact deterministic replay (``mirbft_tpu.tools.mircat``).
"""

from .record import Recorder, read_event_log, write_recorded_event

__all__ = ["Recorder", "read_event_log", "write_recorded_event"]
