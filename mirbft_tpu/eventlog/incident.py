"""Incident bundles: cross-node journal slices + deterministic replay.

The diagnosis path of the flight recorder (docs/OBSERVABILITY.md "Flight
recorder"): :func:`capture_incident` cuts a self-contained
``incident-<id>/`` bundle out of a live or finished deployment — every
node's latest-boot journal, its final metrics snapshot, the commit /
checkpoint ground-truth logs, the merged fleet trace when a collector
ran, and a ``manifest.json`` naming the window, the trace id, the health
thresholds, and the fleet clock offsets.  :func:`replay_incident` then
replays the bundled journals through fresh state machines and health
monitors and reconstructs the causal commit / view-change timeline inside
the window — deterministically, so two replays of one bundle are
byte-identical and a bundle is a complete bug report.

``HealthMonitor.capture_hook`` auto-captures via :class:`AnomalyCapture`
(one bundle per anomaly kind per node, ``flight_recorder_captures_total``).

Clock domains: journal record times and anomaly times share the node
host's CLOCK_MONOTONIC (milliseconds / seconds), so ``window_ms`` is in
monotonic milliseconds and slices journals directly.  Fleet spans live in
aligned wall microseconds; the per-endpoint ``clock_offsets_us`` from the
collector ride in the manifest so span tooling can align them, and the
bundled trace is copied whole (it is already ring-bounded).
"""

from __future__ import annotations

import json
import shutil
import threading
import time as _time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics as metrics_mod
from .. import state as st
from .. import status as status_mod
from ..health import Anomaly, HealthMonitor, HealthThresholds
from ..statemachine.machine import MachineState, StateMachine
from . import journal as journal_mod

# The manifest schema, in lockstep with every reader below and enforced
# by mirlint's wire-schema pass (check_incident_manifest): adding a key
# here without teaching the readers — or vice versa — fails lint.
MANIFEST_KEYS = (
    "clock_offsets_us",
    "created_ms",
    "incident_id",
    "nodes",
    "reason",
    "source_root",
    "thresholds",
    "trace_id",
    "window_ms",
)

# Replay-derived stall threshold: an inter-commit gap longer than this
# inside the window counts as a stall finding.
STALL_GAP_MS = 1000.0

_COPY_FILES = ("metrics.prom", "commits.log", "checkpoints.log")


def sample_manifest() -> dict:
    """A fully-populated example manifest (mirlint round-trips this
    against :data:`MANIFEST_KEYS`; tests use it as a fixture)."""
    return {
        "clock_offsets_us": {"g0n0": 0.0, "g0n1": -12.5},
        "created_ms": 1700000000000,
        "incident_id": "n3-watermark_stall",
        "nodes": ["n0", "n1", "n2", "n3"],
        "reason": "watermark_stall",
        "source_root": "/tmp/mirnet-xyz",
        "thresholds": {"stall_observations": 150},
        "trace_id": "00000000000012ab",
        "window_ms": [1000.0, 64000.0],
    }


def _node_label_dirs(root: Path) -> List[Tuple[str, Path]]:
    """``(label, dir)`` for every journaled runtime under a deployment
    dir: nodes as ``n<i>`` (``g<g>n<i>`` inside a group dir) and
    observers as ``obs<i>`` — the labels the fleet plane uses."""
    root = Path(root)
    group_id: Optional[int] = None
    cluster_path = root / "cluster.json"
    if cluster_path.exists():
        try:
            group_id = json.loads(cluster_path.read_text()).get("group_id")
        except ValueError:
            group_id = None
    prefix = f"g{group_id}" if group_id is not None else ""
    out: List[Tuple[str, Path]] = []
    for node_dir in sorted(root.glob("node-*")):
        try:
            node_id = int(node_dir.name.split("-", 1)[1])
        except ValueError:
            continue
        out.append((f"{prefix}n{node_id}", node_dir))
    for obs_dir in sorted(root.glob("observer-*")):
        try:
            obs_idx = int(obs_dir.name.split("-", 1)[1])
        except ValueError:
            continue
        out.append((f"{prefix}obs{obs_idx}", obs_dir))
    return out


def _fleet_clock_offsets(root: Path) -> Dict[str, float]:
    """Per-node ``offset_us`` from the fleet collector's ``latest.json``
    (beside or above the deployment dir); empty when no collector ran."""
    for candidate in (root / "fleet", root.parent / "fleet"):
        latest = candidate / "latest.json"
        if not latest.exists():
            continue
        try:
            doc = json.loads(latest.read_text())
        except ValueError:
            continue
        offsets: Dict[str, float] = {}
        for label in sorted(doc.get("nodes") or {}):
            entry = (doc["nodes"] or {}).get(label) or {}
            if "offset_us" in entry:
                offsets[label] = float(entry["offset_us"])
        return offsets
    return {}


def _copy_latest_boot_journal(node_dir: Path, dest: Path) -> int:
    """Copy the newest boot's journal evidence (segments, or the legacy
    gzip stream) into ``dest``; returns the number of files copied."""
    copied = 0
    segs = journal_mod._segment_files(node_dir / journal_mod.JOURNAL_DIRNAME)
    if segs:
        latest_boot = segs[-1][0]
        jdir = dest / journal_mod.JOURNAL_DIRNAME
        jdir.mkdir(parents=True, exist_ok=True)
        for boot, _, path in segs:
            if boot != latest_boot:
                continue
            try:
                shutil.copy2(path, jdir / path.name)
                copied += 1
            except OSError:
                pass
        return copied
    legacy = sorted(node_dir.glob("events-*.gz"))
    if legacy:
        dest.mkdir(parents=True, exist_ok=True)
        try:
            shutil.copy2(legacy[-1], dest / legacy[-1].name)
            copied += 1
        except OSError:
            pass
    return copied


def capture_incident(
    root,
    window_ms: Tuple[float, float],
    *,
    trace_id: Optional[str] = None,
    reason: str = "manual",
    incident_id: Optional[str] = None,
    out_dir=None,
    registry: Optional[metrics_mod.Registry] = None,
) -> Path:
    """Cut an ``incident-<id>/`` bundle from deployment dir ``root``.

    Copies every node's latest-boot journal plus its metrics / commit /
    checkpoint evidence and the merged fleet trace, then writes
    ``manifest.json`` **last** — its presence is the completeness marker,
    which also makes capture idempotent (an existing complete bundle is
    returned untouched, so concurrent hooks cannot double-capture)."""
    root = Path(root)
    if incident_id is None:
        if trace_id:
            incident_id = f"trace-{trace_id}"
        else:
            incident_id = f"w{int(window_ms[0])}-{int(window_ms[1])}"
    base = Path(out_dir) if out_dir is not None else root / "incidents"
    bundle = base / f"incident-{incident_id}"
    manifest_path = bundle / "manifest.json"
    if manifest_path.exists():
        return bundle
    bundle.mkdir(parents=True, exist_ok=True)

    labels: List[str] = []
    for label, node_dir in _node_label_dirs(root):
        dest = bundle / label
        copied = _copy_latest_boot_journal(node_dir, dest)
        for name in _COPY_FILES:
            src = node_dir / name
            if src.exists():
                dest.mkdir(parents=True, exist_ok=True)
                try:
                    shutil.copy2(src, dest / name)
                    copied += 1
                except OSError:
                    pass
        if copied:
            labels.append(label)

    for candidate in (root / "fleet", root.parent / "fleet"):
        trace_path = candidate / "trace.json"
        if trace_path.exists():
            try:
                shutil.copy2(trace_path, bundle / "trace.json")
            except OSError:
                pass
            break

    thresholds = None
    cluster_path = root / "cluster.json"
    if cluster_path.exists():
        try:
            thresholds = json.loads(cluster_path.read_text()).get("thresholds")
        except ValueError:
            thresholds = None

    manifest = {
        "clock_offsets_us": _fleet_clock_offsets(root),
        # Wall-clock creation stamp: provenance metadata for humans, no
        # replay decision ever reads it.
        # mirlint: allow(wall-clock)
        "created_ms": int(_time.time() * 1000),
        "incident_id": incident_id,
        "nodes": labels,
        "reason": reason,
        "source_root": str(root),
        "thresholds": thresholds,
        "trace_id": trace_id,
        "window_ms": [float(window_ms[0]), float(window_ms[1])],
    }
    manifest_path.write_text(json.dumps(manifest, indent=2, sort_keys=True))
    reg = registry if registry is not None else metrics_mod.default_registry
    reg.counter("flight_recorder_captures_total").inc()
    return bundle


class AnomalyCapture:
    """``HealthMonitor.capture_hook`` implementation: auto-capture one
    incident bundle per anomaly kind (first occurrence wins), windowed
    around the anomaly with lead-in context, after a short settle delay
    so the journal tail past the anomaly lands in the copy.

    Runs in the node process; capture happens on a daemon thread so the
    monitor's emission path never blocks on file copies."""

    def __init__(
        self,
        root,
        node_label: str,
        *,
        max_captures: int = 4,
        settle_s: float = 1.0,
        pre_window_s: float = 15.0,
        post_window_s: float = 2.0,
        registry: Optional[metrics_mod.Registry] = None,
        time_source: Optional[Callable[[], float]] = None,
    ):
        self.root = Path(root)
        self.node_label = node_label
        self.max_captures = max_captures
        self.settle_s = settle_s
        self.pre_window_s = pre_window_s
        self.post_window_s = post_window_s
        self.registry = registry
        # Window timestamps must share the journal's clock domain.  The
        # monitor clock and the JournalRecorder time_source are wired to
        # the same clock (monotonic in mirnet deployments), with the
        # monitor in seconds and the journal in ms — so the anomaly's own
        # time/since values translate directly.  ``time_source`` overrides
        # that assumption when the two domains differ.
        self.time_source = time_source
        self.captured: List[str] = []  # kinds, in emission order

    def __call__(self, anomaly: Anomaly) -> None:
        if anomaly.kind in self.captured:
            return
        if len(self.captured) >= self.max_captures:
            return
        self.captured.append(anomaly.kind)
        if self.time_source is not None:
            # The hook fires at detection, so "now" in the monitor clock
            # is anomaly.time; carry the lead over into the journal
            # domain anchored at the override clock's current value.
            now_ms = float(self.time_source())
            lead_s = max(0.0, float(anomaly.time) - float(anomaly.since))
            window = (
                now_ms - (lead_s + self.pre_window_s) * 1000.0,
                now_ms + self.post_window_s * 1000.0,
            )
        else:
            window = (
                (float(anomaly.since) - self.pre_window_s) * 1000.0,
                (float(anomaly.time) + self.post_window_s) * 1000.0,
            )
        thread = threading.Thread(
            target=self._capture, args=(anomaly.kind, window), daemon=True
        )
        thread.start()

    def _capture(self, kind: str, window: Tuple[float, float]) -> None:
        try:
            if self.settle_s > 0:
                _time.sleep(self.settle_s)
            capture_incident(
                self.root,
                window,
                reason=kind,
                incident_id=f"{self.node_label}-{kind}",
                registry=self.registry,
            )
        except Exception:
            pass  # capture is evidence, never a failure mode


# ---------------------------------------------------------------------------
# Deterministic bundle replay
# ---------------------------------------------------------------------------


def _commit_line(batch) -> str:
    reqs = ",".join(f"{r.client_id}:{r.req_no}" for r in batch.requests)
    return f"{batch.seq_no} {batch.digest.hex()} {reqs}"


def _replay_node(label: str, node_dir: Path, thresholds) -> dict:
    """Replay one bundled node's newest boot: full-boot state machine +
    health monitor run (determinism needs the boot from its first event),
    returning commits, epoch changes, anomalies, and the boot envelope."""
    boots = journal_mod.load_boots(node_dir)
    out = {
        "label": label,
        "commits": [],  # (time_ms, seq, line)
        "epochs": [],  # (time_ms, epoch)
        "anomalies": [],
        "anomaly_kinds": [],
        "dropped": 0,
        "torn": False,
        "last_event_ms": 0.0,
        "error": None,
    }
    if not boots:
        return out
    boot = boots[-1]
    out["dropped"] = boot.dropped
    out["torn"] = boot.torn
    clock = {"t": 0.0}
    monitor = HealthMonitor(
        0,
        registry=metrics_mod.Registry(),
        clock=lambda: clock["t"],
        thresholds=thresholds,
    )
    sm = StateMachine()
    try:
        for record, _trace in boot.records:
            clock["t"] = float(record.time)
            out["last_event_ms"] = float(record.time)
            actions = sm.apply_event(record.state_event)
            monitor.observe_events((record.state_event,), actions)
            for action in actions:
                if isinstance(action, st.ActionCommit):
                    out["commits"].append(
                        (
                            float(record.time),
                            action.batch.seq_no,
                            _commit_line(action.batch),
                        )
                    )
            if sm.state == MachineState.INITIALIZED:
                epoch = sm.epoch_tracker.current_epoch.number
                if not out["epochs"] or out["epochs"][-1][1] != epoch:
                    out["epochs"].append((float(record.time), epoch))
            if isinstance(record.state_event, st.EventTickElapsed):
                monitor.observe_snapshot(
                    status_mod.snapshot(sm), now=float(record.time)
                )
    except Exception as exc:
        out["error"] = f"{type(exc).__name__}: {exc}"
    out["anomalies"] = [
        {
            "kind": a.kind,
            "time_ms": float(a.time),
            "since_ms": float(a.since),
            "peer": a.peer,
        }
        for a in monitor.anomalies
    ]
    out["anomaly_kinds"] = sorted({a.kind for a in monitor.anomalies})
    return out


def _stall_gaps(
    commits: List[Tuple[float, int, str]],
    last_event_ms: float,
    window: Tuple[float, float],
    gap_ms: float,
) -> List[dict]:
    """Inter-commit gaps (including the tail gap to the last recorded
    event) longer than ``gap_ms`` that overlap the window."""
    out: List[dict] = []
    times = [t for t, _, _ in commits]
    edges = list(zip(times, times[1:]))
    if times and last_event_ms > times[-1]:
        edges.append((times[-1], last_event_ms))
    for since, until in edges:
        gap = until - since
        if gap <= gap_ms:
            continue
        if until < window[0] or since > window[1]:
            continue
        out.append({"since_ms": since, "until_ms": until, "gap_ms": gap})
    return out


def replay_incident(bundle, stall_gap_ms: float = STALL_GAP_MS) -> dict:
    """Deterministically replay a captured bundle (module docstring).

    Every bundled node's newest boot replays in full — determinism needs
    the boot from its first event — and only the *reported* timeline is
    filtered to the manifest window.  The result is pure data (print it
    with :func:`format_replay`); two replays of one bundle are identical.
    """
    bundle = Path(bundle)
    manifest = json.loads((bundle / "manifest.json").read_text())
    window = tuple(manifest["window_ms"])
    thresholds = (
        HealthThresholds.from_dict(manifest["thresholds"])
        if manifest.get("thresholds")
        else None
    )

    per_node = []
    for label in manifest["nodes"]:
        node_dir = bundle / label
        if not node_dir.is_dir():
            continue
        per_node.append(_replay_node(label, node_dir, thresholds))

    timeline: List[dict] = []
    stalls: List[dict] = []
    anomaly_kinds: set = set()
    for node in per_node:
        label = node["label"]
        for time_ms, seq, line in node["commits"]:
            if window[0] <= time_ms <= window[1]:
                timeline.append(
                    {
                        "time_ms": time_ms,
                        "node": label,
                        "kind": "commit",
                        "seq": seq,
                        "detail": line,
                    }
                )
        for time_ms, epoch in node["epochs"]:
            if window[0] <= time_ms <= window[1]:
                timeline.append(
                    {
                        "time_ms": time_ms,
                        "node": label,
                        "kind": "epoch",
                        "seq": epoch,
                        "detail": f"epoch {epoch}",
                    }
                )
        for anomaly in node["anomalies"]:
            if window[0] <= anomaly["time_ms"] <= window[1]:
                timeline.append(
                    {
                        "time_ms": anomaly["time_ms"],
                        "node": label,
                        "kind": "anomaly",
                        "seq": 0,
                        "detail": anomaly["kind"],
                    }
                )
        anomaly_kinds.update(node["anomaly_kinds"])
        for stall in _stall_gaps(
            node["commits"], node["last_event_ms"], window, stall_gap_ms
        ):
            stalls.append(dict(stall, node=label))
    timeline.sort(key=lambda e: (e["time_ms"], e["node"], e["kind"], e["seq"]))
    stalls.sort(key=lambda s: (s["since_ms"], s["node"]))

    return {
        "incident_id": manifest["incident_id"],
        "reason": manifest["reason"],
        "trace_id": manifest.get("trace_id"),
        "window_ms": [float(window[0]), float(window[1])],
        "nodes": [
            {
                "label": n["label"],
                "commits": len(n["commits"]),
                "anomaly_kinds": n["anomaly_kinds"],
                "dropped": n["dropped"],
                "torn": n["torn"],
                "error": n["error"],
            }
            for n in per_node
        ],
        "timeline": timeline,
        "stalls": stalls,
        "anomaly_kinds": sorted(anomaly_kinds),
    }


def format_replay(report: dict) -> str:
    """Human-readable rendering of a :func:`replay_incident` result."""
    lines = [
        f"incident {report['incident_id']} "
        f"(reason={report['reason']}, "
        f"window={report['window_ms'][0]:.0f}..{report['window_ms'][1]:.0f}ms)"
    ]
    for node in report["nodes"]:
        extras = []
        if node["dropped"]:
            extras.append(f"dropped={node['dropped']}")
        if node["torn"]:
            extras.append("torn-tail")
        if node["error"]:
            extras.append(f"error={node['error']}")
        suffix = f" [{', '.join(extras)}]" if extras else ""
        lines.append(
            f"  {node['label']}: {node['commits']} commits replayed, "
            f"anomalies={node['anomaly_kinds'] or '-'}{suffix}"
        )
    for event in report["timeline"]:
        lines.append(
            f"  {event['time_ms']:>12.1f}ms {event['node']:>8} "
            f"{event['kind']:>7} {event['detail']}"
        )
    for stall in report["stalls"]:
        lines.append(
            f"  stall: {stall['node']} "
            f"{stall['since_ms']:.1f}..{stall['until_ms']:.1f}ms "
            f"({stall['gap_ms']:.0f}ms without a commit)"
        )
    if not report["timeline"]:
        lines.append("  (no events inside the window)")
    return "\n".join(lines)
