"""Epoch lifecycle: the 11-state target machine driving view change.

Rebuild of reference ``pkg/statemachine/epoch_target.go``: collects epoch
changes + ACKs into strong certs, the primary constructs the NewEpoch,
validation reconstructs the config and compares (:168-212), the fetch phase
retrieves missing batches/requests referenced by the new epoch (:214-397),
and a Bracha reliable broadcast carries the config — Echo doubles as the PBFT
Prepare for carried-over sequences, Ready doubles as Commit (:632-775).
Epoch-change digests and fetched-batch verification hashes are computed by
the TPU batcher.
"""

from __future__ import annotations

import enum
from typing import Dict, Optional, Set, Tuple

from .. import state as st
from ..messages import (
    ECEntry,
    EpochChange,
    EpochChangeAck,
    Msg,
    NEntry,
    NetworkConfig,
    NewEpoch,
    NewEpochConfig,
    NewEpochEcho,
    NewEpochReady,
    PEntry,
    QEntry,
    RemoteEpochChange,
    Suspect,
)
from ..state import EventInitialParameters
from .actions import EMPTY_ACTIONS, Actions
from .batch_tracker import BatchTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .disseminator import ClientHashDisseminator
from .epoch_active import ActiveEpoch
from .epoch_change import EpochChangeVotes, ParsedEpochChange
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import PersistedLog
from .stateless import (
    construct_new_epoch_config,
    epoch_change_hash_data,
    intersection_quorum,
    some_correct_quorum,
)


class EpochTargetState(enum.IntEnum):
    PREPENDING = 0   # sent an epoch-change; waiting for a quorum
    PENDING = 1      # quorum of epoch-changes; waiting on new-epoch
    VERIFYING = 2    # have a NewEpoch but cannot verify its references yet
    FETCHING = 3     # verified NewEpoch; fetching missing state
    ECHOING = 4      # echoed; waiting for echo quorum
    READYING = 5     # echo quorum; waiting for ready quorum
    RESUMING = 6     # crashed during this epoch; waiting to resume
    READY = 7        # new epoch ready to begin
    IN_PROGRESS = 8  # no pending change
    ENDING = 9       # committed all it can; stable checkpoint reached
    DONE = 10        # epoch over for us (epoch change sent)


class EpochTarget:
    """Reference epoch_target.go:39-118."""

    __slots__ = (
        "state",
        "commit_state",
        "state_ticks",
        "number",
        "starting_seq_no",
        "changes",
        "strong_changes",
        "echos",
        "readies",
        "active_epoch",
        "suspicions",
        "my_new_epoch",
        "my_epoch_change",
        "my_leader_choice",
        "leader_new_epoch",
        "network_new_epoch",
        "resume_epoch_config",
        "is_primary",
        "prestart_buffers",
        "persisted",
        "node_buffers",
        "client_tracker",
        "client_hash_disseminator",
        "batch_tracker",
        "network_config",
        "my_config",
        "logger",
        "_ec_digests",
        "_ec_keys",
        "_ne_construct_key",
        "_ne_verify_key",
    )

    def __init__(
        self,
        number: int,
        persisted: PersistedLog,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        client_tracker: ClientTracker,
        client_hash_disseminator: ClientHashDisseminator,
        batch_tracker: BatchTracker,
        network_config: NetworkConfig,
        my_config: EventInitialParameters,
        logger=None,
    ):
        self.state = EpochTargetState.PREPENDING
        self.number = number
        self.commit_state = commit_state
        self.state_ticks = 0
        self.starting_seq_no = 0
        self.changes: Dict[int, EpochChangeVotes] = {}
        self.strong_changes: Dict[int, ParsedEpochChange] = {}
        self.echos: Dict[NewEpochConfig, Set[int]] = {}
        self.readies: Dict[NewEpochConfig, Set[int]] = {}
        self.active_epoch: Optional[ActiveEpoch] = None
        self.suspicions: Set[int] = set()
        self.my_new_epoch: Optional[NewEpoch] = None
        self.my_epoch_change: Optional[ParsedEpochChange] = None
        self.my_leader_choice: Optional[Tuple[int, ...]] = None
        self.leader_new_epoch: Optional[NewEpoch] = None
        self.network_new_epoch: Optional[NewEpochConfig] = None
        # Set on the crash-recovery resume path (no Bracha broadcast ran):
        # the epoch config from the last NEntry, used to rebuild the active
        # epoch at READY.  (The reference nil-derefs in this situation when
        # no state transfer is needed, epoch_target.go:813.)
        self.resume_epoch_config = None
        self.is_primary = number % len(network_config.nodes) == my_config.id
        self.prestart_buffers = {
            node: MsgBuffer(
                f"epoch-{number}-prestart", node_buffers.node_buffer(node)
            )
            for node in network_config.nodes
        }
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.client_tracker = client_tracker
        self.client_hash_disseminator = client_hash_disseminator
        self.batch_tracker = batch_tracker
        self.network_config = network_config
        self.my_config = my_config
        self.logger = logger
        # Digest memo for epoch-change ack hashing: every ack carrying the
        # same EpochChange content hashes to the same digest, so only the
        # first ack per distinct content pays the hash-action round-trip
        # (the reference hashes every ack, epoch_target.go:514-528 — O(N³)
        # cluster-wide per epoch change).  The memo is keyed by CONTENT
        # (the flattened hash-data tuple) so behavior is a deterministic
        # function of the event stream — a serialized replay reproduces the
        # exact same state even though it sees fresh message objects.
        # content_key -> (digest, waiters): digest None while the hash
        # action is in flight, with (source, origin) pairs queued to apply
        # when the result lands.
        self._ec_digests: Dict[tuple, list] = {}
        # In-process transports hand all N acks the same message OBJECT, so
        # an identity side-table skips re-flattening per ack (values pin the
        # msg so ids stay stable); replay simply misses here and re-flattens.
        self._ec_keys: Dict[int, tuple] = {}
        # NewEpoch-construction/validation memos.  construct_new_epoch_config
        # is the expensive derivation of the view change (~1.3M cycles/msg at
        # 128 nodes per the profiler), and both its call sites re-run with
        # unchanged inputs on almost every event while the epoch change is
        # in flight: check_epoch_quorum per advance_state pass once quorum
        # is reached, verify_new_epoch_state per rebroadcast NewEpoch (the
        # primary re-sends every 2 ticks).  Each memo records the input
        # fingerprint of the last attempt that did NOT advance (None config
        # / failed validation) and skips re-derivation until the cert set —
        # monotone: entries are added once and never replaced — or the
        # leader's message changes.  Pure functions of the event stream,
        # like _ec_digests, so replay is unaffected.
        self._ne_construct_key: Optional[tuple] = None
        self._ne_verify_key: Optional[tuple] = None

    # --- three-phase traffic routing (reference :120-131) ---

    def step(self, source: int, msg: Msg) -> Actions:
        if self.state < EpochTargetState.IN_PROGRESS:
            self.prestart_buffers[source].store(msg)
            return Actions()
        if self.state == EpochTargetState.DONE:
            return Actions()
        return self.active_epoch.step(source, msg)

    # --- NewEpoch construction / verification ---

    def construct_new_epoch(
        self, new_leaders: Tuple[int, ...], nc: NetworkConfig
    ) -> Optional[NewEpoch]:
        """Reference :138-168."""
        if len(self.strong_changes) < intersection_quorum(nc):
            raise AssertionError(
                f"need {intersection_quorum(nc)} acked epoch changes, have "
                f"{len(self.strong_changes)}"
            )
        new_config = construct_new_epoch_config(nc, new_leaders, self.strong_changes)
        if new_config is None:
            return None

        remote_changes = tuple(
            RemoteEpochChange(
                node_id=node, digest=self.changes[node].strong_cert
            )
            for node in self.network_config.nodes  # deterministic order
            if node in self.strong_changes
        )
        return NewEpoch(new_config=new_config, epoch_changes=remote_changes)

    def verify_new_epoch_state(self) -> None:
        """Validate the primary's NewEpoch against locally-acked epoch
        changes and the deterministic reconstruction (reference :173-225).

        Memoized: validation is a pure function of (the leader's NewEpoch,
        which referenced certs are locally acked past the weak quorum), so
        a failed attempt is only retried when one of those inputs changes —
        not per rebroadcast/advance_state pass (see _ne_verify_key)."""
        key = (self.leader_new_epoch, self._verify_fingerprint())
        if key == self._ne_verify_key:
            return  # identical inputs already failed validation
        if self._validate_leader_new_epoch():
            self._ne_verify_key = None
            self.state = EpochTargetState.FETCHING
        else:
            self._ne_verify_key = key

    def _verify_fingerprint(self) -> tuple:
        """The cert-set inputs validation depends on, per referenced cert:
        is a parse for (node, digest) locally known and weakly acked?  The
        parsed *content* for a digest is fixed (it hashes to the digest),
        so the boolean crossing is the only thing that can change."""
        quorum = some_correct_quorum(self.network_config)
        fingerprint = []
        for remote in self.leader_new_epoch.epoch_changes:
            votes = self.changes.get(remote.node_id)
            parsed = (
                None if votes is None
                else votes.parsed_by_digest.get(remote.digest)
            )
            fingerprint.append(
                (
                    remote.node_id,
                    remote.digest,
                    parsed is not None and len(parsed.acks) >= quorum,
                )
            )
        return tuple(fingerprint)

    def _validate_leader_new_epoch(self) -> bool:
        epoch_changes: Dict[int, ParsedEpochChange] = {}
        for remote in self.leader_new_epoch.epoch_changes:
            if remote.node_id in epoch_changes:
                return False  # duplicate reference: malformed
            votes = self.changes.get(remote.node_id)
            if votes is None:
                return False  # primary lying, or we lack information
            parsed = votes.parsed_by_digest.get(remote.digest)
            if parsed is None or len(parsed.acks) < some_correct_quorum(
                self.network_config
            ):
                return False
            epoch_changes[remote.node_id] = parsed

        reconstructed = construct_new_epoch_config(
            self.network_config,
            self.leader_new_epoch.new_config.config.leaders,
            epoch_changes,
        )
        return reconstructed == self.leader_new_epoch.new_config

    def fetch_new_epoch_state(self) -> Actions:
        """Retrieve batches/requests the new epoch references that we lack
        (reference :228-397)."""
        new_epoch_config = self.leader_new_epoch.new_config

        if self.commit_state.transferring:
            return Actions()  # wait for state transfer first

        if new_epoch_config.starting_checkpoint.seq_no > self.commit_state.highest_commit:
            return self.commit_state.transfer_to(
                new_epoch_config.starting_checkpoint.seq_no,
                new_epoch_config.starting_checkpoint.value,
            )

        actions = Actions()
        fetch_pending = False

        for i, digest in enumerate(new_epoch_config.final_preprepares):
            if not digest:
                continue  # null request
            seq_no = i + new_epoch_config.starting_checkpoint.seq_no + 1
            if seq_no <= self.commit_state.highest_commit:
                continue  # already committed

            # Nodes whose Q-sets attest to this batch digest.
            sources = []
            for remote in self.leader_new_epoch.epoch_changes:
                parsed = self.changes[remote.node_id].parsed_by_digest[remote.digest]
                if digest in parsed.q_set.get(seq_no, {}).values():
                    sources.append(remote.node_id)
            if len(sources) < some_correct_quorum(self.network_config):
                raise AssertionError(
                    f"only {len(sources)} sources for seq {seq_no}; the "
                    "verified new-epoch config guarantees a weak quorum"
                )

            batch = self.batch_tracker.get_batch(digest)
            if batch is None:
                actions.concat(
                    self.batch_tracker.fetch_batch(seq_no, digest, tuple(sources))
                )
                fetch_pending = True
                continue

            batch.observed_for.add(seq_no)

            # Make sure every request in the batch is locally available,
            # crediting the attesting sources as acks for it.
            for request_ack in batch.request_acks:
                cr = None
                for node in sources:
                    i_actions, cr = self.client_hash_disseminator.ack(
                        node, request_ack, force=True
                    )
                    actions.concat(i_actions)
                if cr.stored:
                    continue
                fetch_pending = True
                actions.concat(cr.fetch())
                self.client_hash_disseminator.note_fetching(request_ack)

        if fetch_pending:
            return actions

        if new_epoch_config.starting_checkpoint.seq_no > self.commit_state.low_watermark:
            # Committed through this checkpoint but its result is still being
            # computed; wait before echoing.
            return actions

        self.state = EpochTargetState.ECHOING

        if (
            new_epoch_config.starting_checkpoint.seq_no == self.commit_state.stop_at_seq_no
            and new_epoch_config.final_preprepares
        ):
            # A verified NewEpoch carrying batches past a halted boundary is
            # unreachable for this machine; the reference leaves the spot
            # unresolved (panic "deal with this", epoch_target.go:333), but
            # the condition is provably vacuous among correct nodes:
            #
            # 1. Window extension is capped at stop_at_seq_no
            #    (epoch_active.advance), so no correct node ever persists a
            #    P/QEntry for a sequence past a halted checkpoint — halting
            #    only happens at a reconfiguration's applying checkpoint.
            # 2. construct_new_epoch_config emits a non-empty
            #    final_preprepares only if some digest past the starting
            #    checkpoint satisfies condition A2 — a weak quorum (f+1) of
            #    epoch changes carrying that Q-entry.  By (1) at most the f
            #    byzantine nodes can attest such entries: A2 cannot pass.
            # 3. A byzantine primary cannot fabricate the carryover either:
            #    verify_new_epoch_state re-runs construct_new_epoch_config
            #    over our locally-acked epoch changes, so a NewEpoch that
            #    violates (2) never reaches FETCHING.
            #
            # Reaching this point therefore means local state corruption —
            # fail loudly rather than order past a reconfiguration boundary
            # under the old configuration.  docs/Divergences.md #12.
            raise AssertionError(
                "verified NewEpoch carries batches past a reconfiguration "
                "boundary: impossible for <= f byzantine nodes (see proof "
                "in epoch_target.fetch_new_epoch_state)"
            )

        actions.concat(
            self.persisted.add_n_entry(
                NEntry(
                    seq_no=new_epoch_config.starting_checkpoint.seq_no + 1,
                    epoch_config=new_epoch_config.config,
                )
            )
        )

        for i, digest in enumerate(new_epoch_config.final_preprepares):
            seq_no = i + new_epoch_config.starting_checkpoint.seq_no + 1
            if not digest:
                actions.concat(
                    self.persisted.add_q_entry(
                        QEntry(seq_no=seq_no, digest=b"", requests=())
                    )
                )
                continue
            batch = self.batch_tracker.get_batch(digest)
            if batch is None:
                if seq_no <= self.commit_state.highest_commit:
                    # Already committed, so the fetch loop above skipped it
                    # — and a checkpoint reached meanwhile (commits or
                    # state transfer racing a slow epoch change) may have
                    # truncated it from the tracker.  Its QEntry is
                    # already in the log from the original commit; nothing
                    # to re-persist.
                    continue
                raise AssertionError("batch verified above is now missing")
            actions.concat(
                self.persisted.add_q_entry(
                    QEntry(
                        seq_no=seq_no,
                        digest=digest,
                        requests=tuple(batch.request_acks),
                    )
                )
            )
            if (
                seq_no % self.network_config.checkpoint_interval == 0
                and seq_no < self.commit_state.stop_at_seq_no
            ):
                actions.concat(
                    self.persisted.add_n_entry(
                        NEntry(
                            seq_no=seq_no + 1,
                            epoch_config=new_epoch_config.config,
                        )
                    )
                )

        self.starting_seq_no = (
            new_epoch_config.starting_checkpoint.seq_no
            + len(new_epoch_config.final_preprepares)
            + 1
        )

        # Bracha echo — which is simultaneously the PBFT Prepare for all the
        # carried-over sequences.
        return actions.send(
            self.network_config.nodes,
            NewEpochEcho(config=self.leader_new_epoch.new_config),
        )

    # --- ticks (reference :399-481) ---

    def tick(self) -> Actions:
        self.state_ticks += 1
        if self.state == EpochTargetState.PREPENDING:
            return self._tick_prepending()
        if self.state <= EpochTargetState.RESUMING:
            return self._tick_pending()
        if self.state <= EpochTargetState.IN_PROGRESS:
            return self.active_epoch.tick()
        return Actions()

    def repeat_epoch_change_broadcast(self) -> Actions:
        return Actions().send(
            self.network_config.nodes, self.my_epoch_change.underlying
        )

    def _tick_prepending(self) -> Actions:
        if self.my_new_epoch is None:
            half = self.my_config.new_epoch_timeout_ticks // 2
            if half and self.state_ticks % half == 0 and self.my_epoch_change is not None:
                return self.repeat_epoch_change_broadcast()
            return Actions()
        if self.is_primary:
            return Actions().send(self.network_config.nodes, self.my_new_epoch)
        return Actions()

    def _tick_pending(self) -> Actions:
        if self.my_new_epoch is None or self.my_epoch_change is None:
            # Crash-recovery RESUMING path: we never produced an epoch change
            # or new-epoch for this target; there is nothing to rebroadcast.
            # (The reference nil-derefs here, epoch_target.go:449-481.)
            return Actions()
        pending_ticks = self.state_ticks % self.my_config.new_epoch_timeout_ticks
        if self.is_primary:
            if pending_ticks % 2 == 0:
                return Actions().send(self.network_config.nodes, self.my_new_epoch)
        else:
            if pending_ticks == 0:
                # New-epoch timeout: suspect the target epoch itself.
                suspect = Suspect(epoch=self.my_new_epoch.new_config.config.number)
                return (
                    Actions()
                    .send(self.network_config.nodes, suspect)
                    .concat(self.persisted.add_suspect(suspect))
                )
            if pending_ticks % 2 == 0:
                return self.repeat_epoch_change_broadcast()
        return Actions()

    # --- epoch change / ack flow (reference :484-560) ---

    def apply_epoch_change_msg(self, source: int, msg: EpochChange) -> Actions:
        actions = Actions()
        if source != self.my_config.id:
            # Don't echo our own (we already broadcast/rebroadcast it).
            actions.send(
                self.network_config.nodes,
                EpochChangeAck(originator=source, epoch_change=msg),
            )
        return actions.concat(self.apply_epoch_change_ack_msg(source, source, msg))

    def apply_epoch_change_ack_msg(
        self, source: int, origin: int, msg: EpochChange
    ) -> Actions:
        """Hash the acked epoch change (on the TPU batcher); processing
        resumes in apply_epoch_change_digest (reference :514-528).

        Digest memo: the reference hashes every ack separately — O(N²) per
        node, O(N³) cluster-wide per epoch change.  Acks referencing epoch-
        change content this node has already hashed (or has in flight) skip
        the round-trip: a known digest applies synchronously, an in-flight
        one queues the (source, origin) pair for when the result lands."""
        key = self._ec_key(msg)
        entry = self._ec_digests.get(key)
        if entry is not None:
            if entry[0] is not None:
                return self._apply_ec_digest(source, origin, msg, entry[0])
            entry[1].append((source, origin))
            return Actions()
        self._ec_digests[key] = [None, []]
        return Actions().hash(
            list(key),
            st.EpochChangeOrigin(source=source, origin=origin, epoch_change=msg),
        )

    def _ec_key(self, msg: EpochChange) -> tuple:
        """Content key for the digest memo.  The identity side-table entry
        stores the msg itself, pinning the id for the table's lifetime."""
        # mirlint: allow(id-ordering) — identity side-table lookup; the
        # entry pins msg and is is-checked, never ordered or hashed.
        entry = self._ec_keys.get(id(msg))
        if entry is not None and entry[0] is msg:
            return entry[1]
        key = tuple(epoch_change_hash_data(msg))
        # mirlint: allow(id-ordering) — same identity side-table insert.
        self._ec_keys[id(msg)] = (msg, key)
        return key

    def apply_epoch_change_digest(
        self, origin: st.EpochChangeOrigin, digest: bytes
    ) -> Actions:
        """Reference :534-560, plus draining the digest-memo waiters."""
        msg = origin.epoch_change
        key = self._ec_key(msg)
        entry = self._ec_digests.get(key)
        waiters: list = []
        if entry is not None and entry[0] is None:
            waiters = entry[1]
        self._ec_digests[key] = [digest, []]
        actions = self._apply_ec_digest(origin.source, origin.origin, msg, digest)
        for source, origin_node in waiters:
            actions.concat(
                self._apply_ec_digest(source, origin_node, msg, digest)
            )
        return actions

    def _apply_ec_digest(
        self, source_node: int, origin_node: int, msg: EpochChange, digest: bytes
    ) -> Actions:
        """One ack's digest application (reference :534-560)."""
        votes = self.changes.get(origin_node)
        if votes is None:
            votes = EpochChangeVotes(self.network_config)
            self.changes[origin_node] = votes
        votes.add_ack(source_node, msg, digest)
        if votes.strong_cert is not None and origin_node not in self.strong_changes:
            self.strong_changes[origin_node] = votes.parsed_by_digest[
                votes.strong_cert
            ]
            return self.advance_state()
        return Actions()

    def check_epoch_quorum(self) -> Actions:
        """Reference :564-593.

        Memoized on (leader choice, strong-cert set): entries are added to
        ``strong_changes`` at most once per node (``:561``) and never
        replaced, so the sorted key tuple fingerprints the whole input of
        ``construct_new_epoch`` — a failed construction is not re-derived
        until another strong cert lands (see _ne_construct_key)."""
        if (
            len(self.strong_changes) < intersection_quorum(self.network_config)
            or self.my_epoch_change is None
        ):
            return Actions()
        key = (self.my_leader_choice, tuple(sorted(self.strong_changes)))
        if key == self._ne_construct_key:
            return Actions()
        self.my_new_epoch = self.construct_new_epoch(
            self.my_leader_choice, self.network_config
        )
        if self.my_new_epoch is None:
            self._ne_construct_key = key
            return Actions()
        self.state_ticks = 0
        self.state = EpochTargetState.PENDING
        if self.is_primary:
            return Actions().send(self.network_config.nodes, self.my_new_epoch)
        return Actions()

    def apply_new_epoch_msg(self, msg: NewEpoch) -> Actions:
        self.leader_new_epoch = msg
        return self.advance_state()

    # --- Bracha echo / ready (reference :601-775) ---

    def apply_new_epoch_echo_msg(self, source: int, config: NewEpochConfig) -> Actions:
        self.echos.setdefault(config, set()).add(source)
        return self.advance_state()

    def check_new_epoch_echo_quorum(self) -> Actions:
        """Echo quorum → persist PEntries (the implicit Prepares) + send
        Ready (reference :632-671)."""
        actions = Actions()
        for config, echo_sources in self.echos.items():
            if len(echo_sources) < intersection_quorum(self.network_config):
                continue
            self.state = EpochTargetState.READYING
            for i, digest in enumerate(config.final_preprepares):
                seq_no = i + config.starting_checkpoint.seq_no + 1
                actions.concat(
                    self.persisted.add_p_entry(
                        PEntry(seq_no=seq_no, digest=digest)
                    )
                )
            return actions.send(
                self.network_config.nodes, NewEpochReady(config=config)
            )
        return actions

    def apply_new_epoch_ready_msg(self, source: int, config: NewEpochConfig) -> Actions:
        """Reference :676-738."""
        if self.state > EpochTargetState.READYING:
            return Actions()  # already accepted the config

        readies = self.readies.setdefault(config, set())
        readies.add(source)

        if len(readies) < some_correct_quorum(self.network_config):
            return Actions()

        if self.state < EpochTargetState.ECHOING:
            return self.advance_state()

        if self.state < EpochTargetState.READYING:
            # Weak quorum of readies before a strong quorum of echos
            # (standard Bracha amplification).
            self.state = EpochTargetState.READYING
            return Actions().send(
                self.network_config.nodes, NewEpochReady(config=config)
            )

        return self.advance_state()

    def check_new_epoch_ready_quorum(self) -> None:
        """Ready quorum → accept the config; replay own-epoch-change-window
        QEntries into the commit state (reference :743-775)."""
        for config, readies in self.readies.items():
            if len(readies) < intersection_quorum(self.network_config):
                continue
            self.state = EpochTargetState.RESUMING
            self.network_new_epoch = config

            current_epoch = False
            for _, entry in self.persisted.entries:
                if isinstance(entry, QEntry):
                    if current_epoch:
                        self.commit_state.commit(entry)
                elif isinstance(entry, ECEntry):
                    if entry.epoch_number < config.config.number:
                        continue
                    if config.config.number < entry.epoch_number:
                        raise AssertionError(
                            "epoch change entries cannot exceed the target epoch"
                        )
                    current_epoch = True

    def check_epoch_resumed(self) -> None:
        """Reference :777-792."""
        if self.commit_state.stop_at_seq_no < self.starting_seq_no:
            return  # waiting for the outstanding checkpoint to commit
        if self.commit_state.low_watermark + 1 != self.starting_seq_no:
            return  # waiting for state transfer to initiate/complete
        self.state = EpochTargetState.READY

    # --- driver (reference :797-851) ---

    def advance_state(self) -> Actions:
        # Fast path for the per-event fixpoint: a steady-state epoch with no
        # pending available requests and no window work allocates nothing.
        if self.state == EpochTargetState.IN_PROGRESS:
            ae = self.active_epoch
            if (
                not ae.outstanding_reqs.available_iterator.has_next()
                and not ae.needs_advance()
            ):
                return EMPTY_ACTIONS
        actions = Actions()
        while True:
            old_state = self.state
            if self.state == EpochTargetState.PREPENDING:
                actions.concat(self.check_epoch_quorum())
            elif self.state == EpochTargetState.PENDING:
                if self.leader_new_epoch is None:
                    return actions
                self.state = EpochTargetState.VERIFYING
            elif self.state == EpochTargetState.VERIFYING:
                self.verify_new_epoch_state()
            elif self.state == EpochTargetState.FETCHING:
                actions.concat(self.fetch_new_epoch_state())
            elif self.state == EpochTargetState.ECHOING:
                actions.concat(self.check_new_epoch_echo_quorum())
            elif self.state == EpochTargetState.READYING:
                self.check_new_epoch_ready_quorum()
            elif self.state == EpochTargetState.RESUMING:
                self.check_epoch_resumed()
            elif self.state == EpochTargetState.READY:
                epoch_config = (
                    self.network_new_epoch.config
                    if self.network_new_epoch is not None
                    else self.resume_epoch_config
                )
                if (
                    self.commit_state.low_watermark
                    >= epoch_config.planned_expiration
                ):
                    # The epoch expired while we were down or state
                    # transferring past it: there is no window left to
                    # resume (activating would assert in advance()).  End
                    # it so the tracker rolls to an epoch change — which
                    # targets max_correct_epoch, rejoining the cluster's
                    # current epoch instead of replaying the dead one.
                    self.state = EpochTargetState.DONE
                    continue
                self.active_epoch = ActiveEpoch(
                    epoch_config,
                    self.persisted,
                    self.node_buffers,
                    self.commit_state,
                    self.client_tracker,
                    self.my_config,
                    self.logger,
                )
                actions.concat(self.active_epoch.advance())
                self.state = EpochTargetState.IN_PROGRESS
                for node in self.network_config.nodes:
                    self.prestart_buffers[node].iterate(
                        lambda _nid, _msg: Applyable.CURRENT,  # drain all
                        lambda nid, msg: actions.concat(
                            self.active_epoch.step(nid, msg)
                        ),
                    )
                actions.concat(self.active_epoch.drain_buffers())
            elif self.state == EpochTargetState.IN_PROGRESS:
                # This arm runs in the per-event fixpoint; both calls are
                # no-ops almost always, so gate them on cheap predicates.
                ae = self.active_epoch
                if ae.outstanding_reqs.available_iterator.has_next():
                    actions.concat(ae.outstanding_reqs.advance_requests())
                if ae.needs_advance():
                    actions.concat(ae.advance())
            # ENDING / DONE: nothing to do here
            if self.state == old_state:
                return actions

    def move_low_watermark(self, seq_no: int) -> Actions:
        """Reference :853-865."""
        if self.state != EpochTargetState.IN_PROGRESS:
            return Actions()
        actions, done = self.active_epoch.move_low_watermark(seq_no)
        if done:
            self.state = EpochTargetState.DONE
        return actions

    def apply_suspect_msg(self, source: int) -> None:
        """Suspicion quorum ends the epoch (reference :867-874)."""
        self.suspicions.add(source)
        if len(self.suspicions) >= intersection_quorum(self.network_config):
            self.state = EpochTargetState.DONE
