"""Outstanding-request bookkeeping for leader Preprepare validation.

Rebuild of reference ``pkg/statemachine/outstanding.go``: enforces per-bucket,
per-client in-order request-number consumption when validating a leader's
batch (``apply_acks``, reference :120-151), and matches arriving "available"
requests (stored + correct) to sequences waiting on them
(``advance_requests``, reference :101-117).

``RequestAck`` is frozen/hashable, so it serves directly as the reference's
``ackKey``.
"""

from __future__ import annotations

from typing import Dict, List, Set, TYPE_CHECKING

from ..messages import ClientState, NetworkState, RequestAck
from .actions import Actions
from .stateless import client_req_to_bucket, is_committed

if TYPE_CHECKING:
    from .client_tracker import AvailableList
    from .sequence import Sequence


class ClientOutstandingReqs:
    """Next expected req_no for one client within one bucket
    (reference outstanding.go:88-104)."""

    __slots__ = ("next_req_no", "num_buckets", "client")

    def __init__(self, next_req_no: int, num_buckets: int, client: ClientState):
        self.next_req_no = next_req_no
        self.num_buckets = num_buckets
        self.client = client

    def skip_previously_committed(self) -> None:
        while is_committed(self.next_req_no, self.client):
            self.next_req_no += self.num_buckets


class AllOutstandingReqs:
    """Reference outstanding.go:28-86."""

    __slots__ = (
        "buckets",
        "available_iterator",
        "correct_requests",
        "outstanding_requests",
    )

    def __init__(
        self,
        available_list: "AvailableList",
        network_state: NetworkState,
        logger=None,
    ):
        available_list.reset_iterator()
        self.available_iterator = available_list
        self.correct_requests: Dict[RequestAck, RequestAck] = {}
        self.outstanding_requests: Dict[RequestAck, "Sequence"] = {}
        self.buckets: Dict[int, Dict[int, ClientOutstandingReqs]] = {}

        num_buckets = network_state.config.number_of_buckets
        for bucket in range(num_buckets):
            clients: Dict[int, ClientOutstandingReqs] = {}
            self.buckets[bucket] = clients
            for client in network_state.clients:
                # First req_no ≥ low_watermark mapping into this bucket:
                # solve (client_id + req_no) ≡ bucket (mod num_buckets).
                lw = client.low_watermark
                first_uncommitted = lw + (bucket - client.id - lw) % num_buckets
                cors = ClientOutstandingReqs(
                    next_req_no=first_uncommitted,
                    num_buckets=num_buckets,
                    client=client,
                )
                cors.skip_previously_committed()
                clients[client.id] = cors

        self.advance_requests()  # no sequences allocated yet → no actions

    def advance_requests(self) -> Actions:
        """Drain newly-available requests: satisfy waiting sequences, or
        record them as correct-and-present (reference outstanding.go:101-117)."""
        actions = Actions()
        while self.available_iterator.has_next():
            ack = self.available_iterator.next()
            seq = self.outstanding_requests.pop(ack, None)
            if seq is not None:
                actions.concat(seq.satisfy_outstanding(ack))
                continue
            self.correct_requests[ack] = ack
        return actions

    def apply_acks(
        self, bucket: int, seq: "Sequence", batch: List[RequestAck]
    ) -> Actions:
        """Validate a leader's batch against in-order per-client consumption
        and allocate the sequence (reference outstanding.go:120-151).

        Raises ValueError for protocol-invalid batches (unknown client,
        out-of-order req_no) — the caller treats that as a byzantine leader
        and emits a Suspect (epoch_active.apply_preprepare_msg).  Validation
        runs as a separate pass over simulated cursors so a rejected batch
        leaves the bookkeeping untouched: the node keeps running on exactly
        the state it had before the bad Preprepare arrived.
        """
        clients = self.buckets.get(bucket)
        if clients is None:
            raise AssertionError(f"no such bucket {bucket}")

        # Validate pass: no mutation.  Simulated per-client cursors advance
        # the same way the apply pass does (+num_buckets, then skip
        # already-committed req_nos).
        cursors: Dict[int, int] = {}
        for req in batch:
            co = clients.get(req.client_id)
            if co is None:
                raise ValueError(f"no such client {req.client_id}")
            expected = cursors.get(req.client_id, co.next_req_no)
            if expected != req.req_no:
                raise ValueError(
                    f"expected client {req.client_id} next request for bucket "
                    f"{bucket} to have req_no {expected} but got "
                    f"{req.req_no}"
                )
            nxt = expected + co.num_buckets
            while is_committed(nxt, co.client):
                nxt += co.num_buckets
            cursors[req.client_id] = nxt

        # Apply pass: cannot fail.
        outstanding: Set[RequestAck] = set()
        for req in batch:
            co = clients[req.client_id]
            if req in self.correct_requests:
                del self.correct_requests[req]
            else:
                self.outstanding_requests[req] = seq
                outstanding.add(req)
            co.next_req_no += co.num_buckets
            co.skip_previously_committed()

        return seq.allocate(batch, outstanding)
