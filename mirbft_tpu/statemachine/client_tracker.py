"""Ready/available request lists with single-consumer resettable iterators.

Rebuild of reference ``pkg/statemachine/client_tracker.go``: the ``appendList``
structure (pending/consumed split, iterator reset on epoch change, GC from
either side, reference :64-119), specialized as the *available* list (requests
with f+1 acks and locally-stored data) and the *ready* list (strong-cert
requests eligible for proposal).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Dict, TYPE_CHECKING

from ..messages import ClientState, NetworkState, RequestAck
from ..state import EventInitialParameters
from .stateless import is_committed

if TYPE_CHECKING:
    from .disseminator import ClientReqNo


class AppendList:
    """Single-consumer iterator over pending items; consumed items are
    retained (for iterator reset) until garbage collected
    (reference client_tracker.go:56-119)."""

    __slots__ = ("consumed", "pending")

    def __init__(self):
        self.consumed: Deque = deque()
        self.pending: Deque = deque()

    def reset_iterator(self) -> None:
        self.consumed.extend(self.pending)
        self.pending = self.consumed
        self.consumed = deque()

    def has_next(self) -> bool:
        return bool(self.pending)

    def next(self):
        value = self.pending.popleft()
        self.consumed.append(value)
        return value

    def push_back(self, value) -> None:
        self.pending.append(value)

    def garbage_collect(self, should_remove: Callable) -> None:
        self.consumed = deque(v for v in self.consumed if not should_remove(v))
        self.pending = deque(v for v in self.pending if not should_remove(v))


class ReadyList:
    """Strong-certified requests awaiting proposal."""

    __slots__ = ("_list",)

    def __init__(self):
        self._list = AppendList()

    def reset_iterator(self) -> None:
        self._list.reset_iterator()

    def has_next(self) -> bool:
        return self._list.has_next()

    def next(self) -> "ClientReqNo":
        return self._list.next()

    def push_back(self, crn: "ClientReqNo") -> None:
        self._list.push_back(crn)

    def garbage_collect(self, client_states: Dict[int, ClientState]) -> None:
        def should_remove(crn: "ClientReqNo") -> bool:
            state = client_states.get(crn.client_id)
            if state is None:
                raise AssertionError("client removal not yet supported")
            return is_committed(crn.req_no, state)

        self._list.garbage_collect(should_remove)


class AvailableList:
    """Requests with a weak quorum of acks whose data we hold locally."""

    __slots__ = ("_list",)

    def __init__(self):
        self._list = AppendList()

    def reset_iterator(self) -> None:
        self._list.reset_iterator()

    def has_next(self) -> bool:
        return self._list.has_next()

    def next(self) -> RequestAck:
        return self._list.next()

    def push_back(self, ack: RequestAck) -> None:
        self._list.push_back(ack)

    def garbage_collect(self, client_states: Dict[int, ClientState]) -> None:
        def should_remove(ack: RequestAck) -> bool:
            state = client_states.get(ack.client_id)
            if state is None:
                raise AssertionError(
                    "any available client req must have its client in config"
                )
            return is_committed(ack.req_no, state)

        self._list.garbage_collect(should_remove)


class ClientTracker:
    """Reference client_tracker.go:16-54."""

    __slots__ = (
        "my_config",
        "logger",
        "network_config",
        "ready_list",
        "available_list",
        "client_states",
    )

    def __init__(self, my_config: EventInitialParameters, logger=None):
        self.my_config = my_config
        self.logger = logger
        self.network_config = None
        self.ready_list = ReadyList()
        self.available_list = AvailableList()
        self.client_states = ()

    def reinitialize(self, network_state: NetworkState) -> None:
        self.network_config = network_state.config
        self.client_states = network_state.clients
        self.available_list = AvailableList()
        self.ready_list = ReadyList()

    def add_ready(self, crn: "ClientReqNo") -> None:
        self.ready_list.push_back(crn)

    def add_available(self, ack: RequestAck) -> None:
        self.available_list.push_back(ack)

    def allocate(self, seq_no: int, state: NetworkState) -> None:
        """GC both lists against the post-checkpoint client states
        (reference client_tracker.go:46-54)."""
        state_map = {client.id: client for client in state.clients}
        self.available_list.garbage_collect(state_map)
        self.ready_list.garbage_collect(state_map)
