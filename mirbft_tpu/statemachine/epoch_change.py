"""Epoch-change message parsing and ACK accumulation.

Rebuild of reference ``pkg/statemachine/epoch_change.go``: ``ParsedEpochChange``
validates + indexes the P/Q sets (:71-124); ``EpochChangeVotes`` (the
reference's ``epochChange``) accumulates per-digest ACKs until a strong cert
forms (:38-60).  Digests here are computed by the TPU hash batcher over
``epoch_change_hash_data`` flattenings.
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..messages import EpochChange, EpochChangeSetEntry, NetworkConfig
from .stateless import intersection_quorum


class ParsedEpochChange:
    """Validated, indexed view of one EpochChange message
    (reference epoch_change.go:63-124)."""

    __slots__ = ("underlying", "p_set", "q_set", "low_watermark", "acks")

    def __init__(self, underlying: EpochChange):
        if not underlying.checkpoints:
            raise ValueError("epoch change did not contain any checkpoints")

        low_watermark = underlying.checkpoints[0].seq_no
        seen_cp = set()
        for cp in underlying.checkpoints:
            low_watermark = min(low_watermark, cp.seq_no)
            if cp.seq_no in seen_cp:
                raise ValueError(
                    f"epoch change checkpoints duplicated seq_no {cp.seq_no}"
                )
            seen_cp.add(cp.seq_no)

        p_set: Dict[int, EpochChangeSetEntry] = {}
        for entry in underlying.p_set:
            if entry.seq_no in p_set:
                raise ValueError(
                    f"epoch change p_set duplicated seq_no {entry.seq_no}"
                )
            p_set[entry.seq_no] = entry

        q_set: Dict[int, Dict[int, bytes]] = {}
        for entry in underlying.q_set:
            views = q_set.setdefault(entry.seq_no, {})
            if entry.epoch in views:
                raise ValueError(
                    f"epoch change q_set duplicated seq_no={entry.seq_no} "
                    f"epoch={entry.epoch}"
                )
            views[entry.epoch] = entry.digest

        self.underlying = underlying
        self.low_watermark = low_watermark
        self.p_set = p_set
        self.q_set = q_set
        self.acks: Set[int] = set()


def try_parse_epoch_change(underlying: EpochChange) -> Optional[ParsedEpochChange]:
    try:
        return ParsedEpochChange(underlying)
    except ValueError:
        return None


class EpochChangeVotes:
    """Per-origin ACK accumulation keyed by epoch-change digest
    (reference epoch_change.go:18-60)."""

    __slots__ = ("network_config", "parsed_by_digest", "strong_cert")

    def __init__(self, network_config: NetworkConfig):
        self.network_config = network_config
        self.parsed_by_digest: Dict[bytes, ParsedEpochChange] = {}
        # digest of the EpochChange with a strong quorum of acks, if any
        self.strong_cert: Optional[bytes] = None

    def add_ack(self, source: int, msg: EpochChange, digest: bytes) -> None:
        parsed = self.parsed_by_digest.get(digest)
        if parsed is None:
            parsed = try_parse_epoch_change(msg)
            if parsed is None:
                return  # malformed; drop
            self.parsed_by_digest[digest] = parsed
        parsed.acks.add(source)
        if (
            self.strong_cert is None
            and len(parsed.acks) >= intersection_quorum(self.network_config)
        ):
            self.strong_cert = digest
