"""Per-sequence-number three-phase-commit state machine.

Rebuild of reference ``pkg/statemachine/sequence.go``: the lifecycle
``UNINITIALIZED → ALLOCATED → PENDING_REQUESTS → READY → PREPREPARED →
PREPARED → COMMITTED`` (sequence.go:18-26), batch-digest hashing on
allocation (:142-177) — the hash request is the unit of work the TPU batcher
aggregates — QEntry-persist-then-send on preprepare (:203-255), and the
intersection-quorum prepare/commit rules (:276-355).

Vote accumulation (the O(N²) Prepare/Commit hot path) runs in the native
sequence-vote plane when available (``voteplane.py`` / ackplane.cpp); the
dict-based path below is the pure-Python semantic reference.  Both paths
share the lifecycle/transition code: quorum checks read counts through
``_counts()``, which consults whichever store is live.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Set

from .. import state as st
from ..messages import (
    Commit,
    NetworkConfig,
    PEntry,
    Preprepare,
    Prepare,
    QEntry,
    RequestAck,
)
from .actions import EMPTY_ACTIONS, Actions
from .persisted import PersistedLog
from .stateless import intersection_quorum


class SeqState(enum.IntEnum):
    UNINITIALIZED = 0
    ALLOCATED = 1
    PENDING_REQUESTS = 2
    READY = 3
    PREPREPARED = 4
    PREPARED = 5
    COMMITTED = 6


class Sequence:
    """One in-flight sequence number within an active epoch."""

    __slots__ = (
        "owner",
        "seq_no",
        "epoch",
        "my_id",
        "network_config",
        "persisted",
        "_state",
        "plane",
        "q_entry",
        "client_requests",
        "batch",
        "outstanding_reqs",
        "digest",
        "prep_mask",
        "commit_mask",
        "my_prepare_digest",
        "prepares",
        "commits",
        "_iq",
    )

    def __init__(
        self,
        owner: int,
        epoch: int,
        seq_no: int,
        persisted: PersistedLog,
        network_config: NetworkConfig,
        my_id: int,
        plane=None,
    ):
        self.owner = owner
        self.seq_no = seq_no
        self.epoch = epoch
        self.my_id = my_id
        self.network_config = network_config
        self.persisted = persisted
        # Bypass the property: the plane window may not cover this seq yet
        # (slots default to UNINITIALIZED natively too).
        self._state = SeqState.UNINITIALIZED
        self.plane = plane
        self.q_entry: Optional[QEntry] = None
        self.client_requests: List = []  # ClientRequest-like (has .ack, .agreements)
        self.batch: List[RequestAck] = []
        self.outstanding_reqs: Optional[Set[RequestAck]] = None
        self.digest: Optional[bytes] = None
        # The digest carried by our own prepare — the only per-node digest
        # the quorum checks ever read back.
        self.my_prepare_digest: Optional[bytes] = None
        self._iq = intersection_quorum(network_config)
        if plane is None:
            # Pure-Python vote store: per-node bitmasks (a node's "seq choice
            # state" in the reference is derivable: prepare recorded ⇔ bit in
            # prep_mask|commit_mask; commit recorded ⇔ bit in commit_mask)
            # plus per-digest counts.
            self.prep_mask = 0
            self.commit_mask = 0
            self.prepares: Dict[bytes, int] = {}
            self.commits: Dict[bytes, int] = {}

    # --- state, mirrored into the native plane ---

    @property
    def state(self) -> SeqState:
        return self._state

    @state.setter
    def state(self, value: SeqState) -> None:
        self._state = value
        if self.plane is not None:
            self.plane.set_phase(self.seq_no, int(value))

    # --- driver ---

    def advance_state(self) -> Actions:
        """Iterate phase transitions to fixpoint (reference sequence.go:101-125)."""
        actions = Actions()
        while True:
            old_state = self._state
            if self._state == SeqState.PENDING_REQUESTS:
                self._check_requests()
            elif self._state == SeqState.READY:
                if self.digest is not None or not self.batch:
                    actions.concat(self._prepare())
            elif self._state == SeqState.PREPREPARED:
                actions.concat(self._check_prepare_quorum())
            elif self._state == SeqState.PREPARED:
                self._check_commit_quorum()
            if self._state == old_state:
                return actions

    # --- allocation ---

    def allocate_as_owner(self, client_requests: List) -> Actions:
        """Owner-side allocation from proposer-selected client requests
        (reference sequence.go:127-137)."""
        self.client_requests = client_requests
        return self.allocate([cr.ack for cr in client_requests], None)

    def allocate(
        self,
        request_acks: List[RequestAck],
        outstanding_reqs: Optional[Set[RequestAck]],
    ) -> Actions:
        """Reserve this sequence for a batch; emits the batch-digest hash
        request (the TPU hot-path action) unless the batch is empty
        (reference sequence.go:139-177)."""
        if self._state != SeqState.UNINITIALIZED:
            raise AssertionError(
                f"seq_no={self.seq_no} must be uninitialized to allocate, "
                f"was {self._state.name}"
            )
        self.state = SeqState.ALLOCATED
        self.batch = request_acks
        self.outstanding_reqs = outstanding_reqs

        if not request_acks:
            # Null batch: no digest to compute.
            self.state = SeqState.READY
            return self.apply_batch_hash_result(None)

        actions = Actions().hash(
            [ack.digest for ack in request_acks],
            st.BatchOrigin(
                source=self.owner,
                epoch=self.epoch,
                seq_no=self.seq_no,
                request_acks=tuple(request_acks),
            ),
        )
        self.state = SeqState.PENDING_REQUESTS
        return actions.concat(self.advance_state())

    def satisfy_outstanding(self, ack: RequestAck) -> Actions:
        """A request this sequence was waiting on became locally available
        (reference sequence.go:179-188)."""
        if self.outstanding_reqs is None or ack not in self.outstanding_reqs:
            raise AssertionError(
                f"told request {ack.digest.hex()} was ready but we weren't "
                "waiting for it"
            )
        self.outstanding_reqs.discard(ack)
        return self.advance_state()

    def _check_requests(self) -> None:
        if self.outstanding_reqs:
            return
        self.state = SeqState.READY

    # --- three-phase commit ---

    def apply_batch_hash_result(self, digest: Optional[bytes]) -> Actions:
        """Record the batch digest (computed on TPU) and treat it as the
        owner's implicit prepare (reference sequence.go:190-194)."""
        self.digest = digest
        if self.plane is not None:
            self.plane.set_expected(
                self.seq_no, digest if digest is not None else b""
            )
        return self.apply_prepare_msg(self.owner, digest)

    def _prepare(self) -> Actions:
        """Persist the QEntry, then send Preprepare (owner) or Prepare
        (follower) — WAL-before-send (reference sequence.go:196-255)."""
        self.q_entry = QEntry(
            seq_no=self.seq_no,
            digest=self.digest if self.digest is not None else b"",
            requests=tuple(self.batch),
        )
        self.state = SeqState.PREPREPARED

        actions = self.persisted.add_q_entry(self.q_entry)

        if self.owner == self.my_id:
            # Forward each request to nodes that have not acked it, so
            # followers can satisfy their outstanding-request checks.
            for cr in self.client_requests:
                # refresh(): the live agreement mask may be accumulating in
                # the native ack plane (disseminator.ClientRequest.refresh).
                agreements = cr.refresh()
                missing = [
                    node
                    for node in self.network_config.nodes
                    if not (agreements >> node) & 1
                ]
                if missing:
                    actions.forward_request(missing, cr.ack)
            actions.send(
                self.network_config.nodes,
                Preprepare(
                    seq_no=self.seq_no, epoch=self.epoch, batch=tuple(self.batch)
                ),
            )
        else:
            actions.send(
                self.network_config.nodes,
                Prepare(
                    seq_no=self.seq_no,
                    epoch=self.epoch,
                    digest=self.digest if self.digest is not None else b"",
                ),
            )
        return actions

    def apply_prepare_msg(self, source: int, digest: Optional[bytes]) -> Actions:
        """Reference sequence.go:257-274, with one deviation: duplicate
        prepares are dropped for the owner too.  In the reference, the owner's
        artificial prepare (from the batch hash result) and its own Preprepare
        loopback BOTH increment the prepare count (its dup-check is
        ``source != owner`` only), letting a leader count itself twice toward
        the 2f+1 prepare certificate.  We count each node at most once."""
        if self.plane is not None:
            count = self.plane.apply_vote(
                0, self.seq_no, digest if digest is not None else b"", source
            )
            if count is None:
                return EMPTY_ACTIONS  # duplicate
            if source == self.my_id:
                self.my_prepare_digest = digest
        else:
            bit = 1 << source
            if (self.prep_mask | self.commit_mask) & bit:
                return EMPTY_ACTIONS
            self.prep_mask |= bit
            if source == self.my_id:
                self.my_prepare_digest = digest
            key = digest if digest is not None else b""
            count = self.prepares.get(key, 0) + 1
            self.prepares[key] = count
        # advance_state can only do work here when the prepare quorum on the
        # incremented digest is reachable (PREPREPARED) or when this is the
        # digest-arrival path (READY/PENDING_REQUESTS); every other state's
        # transitions do not read prepare votes, so skip the fixpoint walk.
        state = self._state
        if state is SeqState.PREPREPARED:
            if count >= self._iq:
                return self.advance_state()
            return Actions()
        if state is SeqState.READY or state is SeqState.PENDING_REQUESTS:
            return self.advance_state()
        return EMPTY_ACTIONS

    def _check_prepare_quorum(self) -> Actions:
        """2f+1 prepares (leader's preprepare counts) + own prepare persisted
        → persist PEntry, send Commit (reference sequence.go:276-318)."""
        my_key = self.digest if self.digest is not None else b""
        if self.plane is not None:
            prep_count, _, self_pc, _, my_matches = self.plane.query(self.seq_no)
            if not self_pc:
                # Have not sent our own prepare → QEntry may not be persisted.
                return EMPTY_ACTIONS
            if not my_matches:
                # Network's correct digest differs from ours; do not prepare.
                return EMPTY_ACTIONS
            if prep_count < self._iq:
                return EMPTY_ACTIONS
        else:
            agreements = self.prepares.get(my_key, 0)
            if not ((self.prep_mask | self.commit_mask) >> self.my_id) & 1:
                return EMPTY_ACTIONS
            my_digest = (
                self.my_prepare_digest
                if self.my_prepare_digest is not None
                else b""
            )
            if my_digest != my_key:
                return EMPTY_ACTIONS
            if agreements < self._iq:
                return EMPTY_ACTIONS

        self.state = SeqState.PREPARED
        p_entry = PEntry(seq_no=self.seq_no, digest=my_key)
        return self.persisted.add_p_entry(p_entry).send(
            self.network_config.nodes,
            Commit(seq_no=self.seq_no, epoch=self.epoch, digest=my_key),
        )

    def apply_commit_msg(self, source: int, digest: Optional[bytes]) -> Actions:
        """Reference sequence.go:320-337."""
        if self.plane is not None:
            count = self.plane.apply_vote(
                1, self.seq_no, digest if digest is not None else b"", source
            )
            if count is None:
                return EMPTY_ACTIONS  # duplicate commit
        else:
            bit = 1 << source
            if self.commit_mask & bit:
                return EMPTY_ACTIONS  # duplicate commit
            self.commit_mask |= bit
            key = digest if digest is not None else b""
            count = self.commits.get(key, 0) + 1
            self.commits[key] = count
        # Only a PREPARED sequence with a reachable commit quorum can
        # transition on a commit vote (commit emission itself is action-free).
        if self._state is SeqState.PREPARED and count >= self._iq:
            self._check_commit_quorum()
        return EMPTY_ACTIONS

    def _check_commit_quorum(self) -> None:
        """Reference sequence.go:339-355."""
        if self.plane is not None:
            _, commit_count, _, self_c, _ = self.plane.query(self.seq_no)
            if not self_c:
                return  # our own Commit (and thus PEntry persist) not sent yet
            if commit_count < self._iq:
                return
        else:
            my_key = self.digest if self.digest is not None else b""
            agreements = self.commits.get(my_key, 0)
            if not (self.commit_mask >> self.my_id) & 1:
                return
            if agreements < self._iq:
                return
        self.state = SeqState.COMMITTED
