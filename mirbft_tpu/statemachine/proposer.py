"""Batch proposer: pulls ready requests into owned buckets and cuts batches.

Rebuild of reference ``pkg/statemachine/proposer.go``: per-owned-bucket
proposal queues with next-checkpoint gating (``valid_after_seq_no``), full
batches via ``has_pending`` and partial heartbeat batches via
``has_outstanding`` (reference :77-161).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, TYPE_CHECKING

from ..messages import NetworkConfig
from ..state import EventInitialParameters
from .stateless import client_req_to_bucket

if TYPE_CHECKING:
    from .client_tracker import ReadyList
    from .disseminator import ClientRequest


class ProposalBucket:
    """Reference proposer.go:30-52."""

    __slots__ = (
        "request_count",
        "pending",
        "bucket_id",
        "checkpoint_interval",
        "current_checkpoint",
        "ready_list",
        "next_ready_list",
    )

    def __init__(
        self,
        bucket_id: int,
        base_checkpoint: int,
        checkpoint_interval: int,
        request_count: int,
    ):
        self.bucket_id = bucket_id
        self.current_checkpoint = base_checkpoint
        self.checkpoint_interval = checkpoint_interval
        self.request_count = request_count
        self.pending: List["ClientRequest"] = []
        # requests valid at/before the current checkpoint window
        self.ready_list: Deque["ClientRequest"] = deque()
        # requests valid only after the next checkpoint
        self.next_ready_list: Deque["ClientRequest"] = deque()

    def queue_request(self, valid_after_seq_no: int, cr: "ClientRequest") -> None:
        if self.current_checkpoint >= valid_after_seq_no:
            self.ready_list.append(cr)
        else:
            if valid_after_seq_no != self.current_checkpoint + self.checkpoint_interval:
                raise AssertionError(
                    "requests should never become ready beyond the next "
                    "checkpoint interval"
                )
            self.next_ready_list.append(cr)

    def advance(self, to_seq_no: int) -> None:
        if to_seq_no >= self.current_checkpoint + self.checkpoint_interval:
            self.current_checkpoint += self.checkpoint_interval
            self.ready_list.extend(self.next_ready_list)
            self.next_ready_list = deque()
        while len(self.pending) < self.request_count and self.ready_list:
            self.pending.append(self.ready_list.popleft())

    def has_outstanding(self, for_seq_no: int) -> bool:
        """Anything at all to propose (heartbeat / partial batch)."""
        self.advance(for_seq_no)
        return len(self.pending) > 0

    def has_pending(self, for_seq_no: int) -> bool:
        """A full batch to propose."""
        self.advance(for_seq_no)
        return 0 < len(self.pending) == self.request_count

    def next(self) -> List["ClientRequest"]:
        result = self.pending
        self.pending = []
        return result


class Proposer:
    """Reference proposer.go:54-113."""

    __slots__ = (
        "my_config",
        "network_config",
        "proposal_buckets",
        "ready_iterator",
    )

    def __init__(
        self,
        base_checkpoint: int,
        checkpoint_interval: int,
        my_config: EventInitialParameters,
        ready_list: "ReadyList",
        buckets: Dict[int, int],  # bucket_id -> leader node_id
        network_config: NetworkConfig,
    ):
        self.my_config = my_config
        self.network_config = network_config
        self.proposal_buckets: Dict[int, ProposalBucket] = {
            bucket_id: ProposalBucket(
                bucket_id=bucket_id,
                base_checkpoint=base_checkpoint,
                checkpoint_interval=checkpoint_interval,
                request_count=my_config.batch_size,
            )
            for bucket_id, leader in buckets.items()
            if leader == my_config.id
        }
        ready_list.reset_iterator()
        self.ready_iterator = ready_list

    def advance(self, to_seq_no: int) -> None:
        """Pull newly-ready requests into owned proposal buckets
        (reference proposer.go:85-123)."""
        while self.ready_iterator.has_next():
            crn = self.ready_iterator.next()
            if crn.committed:
                # Possible if committed in a previous view but not yet GC'd.
                continue

            bucket_id = client_req_to_bucket(
                crn.client_id, crn.req_no, self.network_config
            )
            bucket = self.proposal_buckets.get(bucket_id)
            if bucket is None:
                continue  # not ours

            bucket.advance(to_seq_no)

            if len(crn.strong_requests) > 1:
                # Conflicting strong certs: one must be the null request;
                # prefer it (byzantine-client handling).
                null_req = crn.strong_requests.get(b"")
                if null_req is None:
                    raise AssertionError(
                        "if multiple requests have quorum, one must be null"
                    )
                bucket.queue_request(crn.valid_after_seq_no, null_req)
            else:
                if len(crn.strong_requests) != 1:
                    raise AssertionError("exactly one strong request must exist")
                bucket.queue_request(
                    crn.valid_after_seq_no,
                    next(iter(crn.strong_requests.values())),
                )

    def proposal_bucket(self, bucket_id: int) -> ProposalBucket:
        return self.proposal_buckets.get(bucket_id)
