"""Root deterministic state machine: event dispatch and the fixpoint loop.

Rebuild of reference ``pkg/statemachine/state_machine.go``: the 3-phase
lifecycle (UNINITIALIZED → LOADING_PERSISTED → INITIALIZED, :90-94), event
dispatch (:173-231), message routing by type (:310-349), hash-result demux by
origin (:351-371) — the return path of every TPU hash dispatch — checkpoint
results (:373-401), and the post-event loop: garbage-collect watermarks, then
iterate ``commit_state.drain()`` + ``epoch_tracker.advance_state()`` to
fixpoint (:239-267).

The machine is single-threaded and deterministic by construction: same event
sequence in, same action sequence out, on every replica and on every replay.
"""

from __future__ import annotations

import enum
from typing import Optional

from .. import state as st
from ..messages import (
    AckBatch,
    AckMsg,
    MsgBatch,
    CEntry,
    CheckpointMsg,
    Commit,
    EpochChange,
    EpochChangeAck,
    FEntry,
    FetchBatch,
    FetchRequest,
    ForwardBatch,
    ForwardRequest,
    Msg,
    NEntry,
    NewEpoch,
    NewEpochEcho,
    NewEpochReady,
    Preprepare,
    Prepare,
    Suspect,
)
from .actions import Actions
from .batch_tracker import BatchTracker
from .checkpoints import CheckpointState, CheckpointTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .disseminator import ClientHashDisseminator
from .epoch_target import EpochTargetState
from .epoch_tracker import EpochTracker
from .msgbuffers import NodeBuffers
from .persisted import PersistedLog
from .voteplane import split_votes

_ET_IN_PROGRESS = EpochTargetState.IN_PROGRESS


class MachineState(enum.IntEnum):
    UNINITIALIZED = 0
    LOADING_PERSISTED = 1
    INITIALIZED = 2


class StateMachine:
    """Reference state_machine.go:96-170."""

    __slots__ = (
        "logger",
        "state",
        "my_config",
        "commit_state",
        "client_tracker",
        "client_hash_disseminator",
        "node_buffers",
        "batch_tracker",
        "checkpoint_tracker",
        "epoch_tracker",
        "persisted",
    )

    def __init__(self, logger=None):
        self.logger = logger
        self.state = MachineState.UNINITIALIZED
        self.my_config: Optional[st.EventInitialParameters] = None
        self.commit_state: Optional[CommitState] = None
        self.client_tracker: Optional[ClientTracker] = None
        self.client_hash_disseminator: Optional[ClientHashDisseminator] = None
        self.node_buffers: Optional[NodeBuffers] = None
        self.batch_tracker: Optional[BatchTracker] = None
        self.checkpoint_tracker: Optional[CheckpointTracker] = None
        self.epoch_tracker: Optional[EpochTracker] = None
        self.persisted: Optional[PersistedLog] = None

    # --- lifecycle ---

    def _initialize(self, parameters: st.EventInitialParameters) -> None:
        if self.state != MachineState.UNINITIALIZED:
            raise AssertionError("state machine has already been initialized")
        self.my_config = parameters
        self.state = MachineState.LOADING_PERSISTED
        self.persisted = PersistedLog(self.logger)
        self.node_buffers = NodeBuffers(parameters, self.logger)
        self.checkpoint_tracker = CheckpointTracker(
            self.persisted, self.node_buffers, parameters, self.logger
        )
        self.client_tracker = ClientTracker(parameters, self.logger)
        self.commit_state = CommitState(self.persisted, self.logger)
        self.client_hash_disseminator = ClientHashDisseminator(
            self.node_buffers, parameters, self.client_tracker, self.logger
        )
        self.batch_tracker = BatchTracker(self.persisted)
        self.epoch_tracker = EpochTracker(
            self.persisted,
            self.node_buffers,
            self.commit_state,
            parameters,
            self.batch_tracker,
            self.client_tracker,
            self.client_hash_disseminator,
            self.logger,
        )

    def _apply_persisted(self, index: int, entry) -> None:
        if self.state != MachineState.LOADING_PERSISTED:
            raise AssertionError("not in the loading-persisted phase")
        self.persisted.append_initial_load(index, entry)

    def _complete_initialization(self) -> Actions:
        if self.state != MachineState.LOADING_PERSISTED:
            raise AssertionError("not in the loading-persisted phase")
        self.state = MachineState.INITIALIZED
        return self._reinitialize()

    def _reinitialize(self) -> Actions:
        """Shared by start, state transfer, and reconfiguration
        (reference state_machine.go:272-287)."""
        actions = self._complete_pending_reconfiguration()
        actions.concat(self._recover_log())
        actions.concat(self.commit_state.reinitialize())
        self.client_tracker.reinitialize(self.commit_state.active_state)
        actions.concat(
            self.client_hash_disseminator.reinitialize(
                self.commit_state.low_watermark, self.commit_state.active_state
            )
        )
        self.checkpoint_tracker.reinitialize()
        self.batch_tracker.reinitialize()
        return actions.concat(self.epoch_tracker.reinitialize())

    def _complete_pending_reconfiguration(self) -> Actions:
        """Close the epoch at a reconfiguration boundary.

        When the checkpoint that APPLIES a pending reconfiguration has been
        persisted (its predecessor CEntry still carries the pending list) but
        no FEntry follows it yet, append the FEntry ending the current epoch
        config.  The subsequent log recovery truncates through the new CEntry
        and every tracker reinitializes under the post-reconfiguration
        network state; the next epoch then starts via the graceful
        epoch-change path.

        The reference never implemented this step (its reconfiguration
        "does not entirely work", reference README.md:35, and the in-epoch
        variant dead-ends at epoch_target.go:333's panic); this follows the
        flow reference docs/LogMovement.md describes.  Running it inside
        ``_reinitialize`` makes the normal path and the
        crashed-between-CEntry-and-FEntry recovery path identical.
        """
        prev_c = last_c = None
        last_epoch_config = None
        f_after_last_c = False
        for _, entry in self.persisted.entries:
            if isinstance(entry, CEntry):
                prev_c, last_c = last_c, entry
                f_after_last_c = False
            elif isinstance(entry, FEntry):
                f_after_last_c = True
                last_epoch_config = entry.ends_epoch_config
            elif isinstance(entry, NEntry):
                last_epoch_config = entry.epoch_config
        if (
            last_c is None
            or prev_c is None
            or f_after_last_c
            or not prev_c.network_state.pending_reconfigurations
        ):
            return Actions()
        if last_epoch_config is None:
            raise AssertionError(
                "reconfiguration completed with no epoch config in the log"
            )
        return self.persisted.add_f_entry(
            FEntry(ends_epoch_config=last_epoch_config)
        )

    def _recover_log(self) -> Actions:
        """Truncate the WAL through the last CEntry preceding each FEntry
        (reference state_machine.go:290-308)."""
        actions = Actions()
        last_c: Optional[CEntry] = None
        for _, entry in list(self.persisted.entries):
            if isinstance(entry, CEntry):
                last_c = entry
            elif isinstance(entry, FEntry):
                if last_c is None:
                    raise AssertionError(
                        "FEntry without corresponding CEntry; corrupt log"
                    )
                actions.concat(self.persisted.truncate(last_c.seq_no))
        if last_c is None:
            raise AssertionError("found no checkpoints in the log")
        return actions

    # --- event dispatch (reference state_machine.go:168-270) ---

    def apply_event(self, event: st.Event) -> Actions:
        cls = event.__class__

        if cls is st.EventInitialParameters:
            self._initialize(event)
            return Actions()
        if cls is st.EventLoadPersistedEntry:
            self._apply_persisted(event.index, event.entry)
            return Actions()

        actions = Actions()
        if cls is st.EventLoadCompleted:
            actions = self._complete_initialization()
        elif cls is st.EventActionsReceived:
            # Marker correlating action batches to their events in the
            # recorded stream — and the batch boundary at which deferred
            # ack broadcasts flush (one AckBatch per client per batch).
            if self.state == MachineState.INITIALIZED:
                return self.client_hash_disseminator.flush_acks()
            return actions
        else:
            if self.state != MachineState.INITIALIZED:
                raise AssertionError(
                    "cannot apply events to an uninitialized state machine"
                )
            # Ordered by hot-path frequency: Step dominates, then the
            # hash/persist round-trips, then ticks.
            if cls is st.EventStep:
                actions.concat(self.step(event.source, event.msg))
            elif cls is st.EventRequestPersisted:
                actions.concat(
                    self.client_hash_disseminator.apply_new_request(
                        event.request_ack
                    )
                )
            elif cls is st.EventHashResult:
                actions.concat(self._process_hash_result(event))
            elif cls is st.EventCheckpointResult:
                actions.concat(self._process_checkpoint_result(event))
            elif cls is st.EventTickElapsed:
                actions.concat(self.client_hash_disseminator.tick())
                actions.concat(self.epoch_tracker.tick())
                actions.concat(self.commit_state.tick())
            elif cls is st.EventStateTransferFailed:
                # The reference leaves this edge unresolved
                # (state_machine.go:210-212 ``panic("XXX handle state
                # transfer failure")``); we complete it: the transfer is
                # re-issued after a deterministic tick backoff so the app
                # can retry (against an alternate snapshot source if it has
                # one).  docs/Divergences.md #8.
                actions.concat(
                    self.commit_state.apply_transfer_failed(
                        event.seq_no, event.checkpoint_value
                    )
                )
            elif cls is st.EventStateTransferComplete:
                if not self.commit_state.transferring:
                    raise AssertionError(
                        "state transfer completed but none was requested"
                    )
                actions.concat(
                    self.persisted.add_c_entry(
                        CEntry(
                            seq_no=event.seq_no,
                            checkpoint_value=event.checkpoint_value,
                            network_state=event.network_state,
                        )
                    )
                )
                actions.concat(self._reinitialize())
            else:
                raise AssertionError(f"unknown event type {type(event).__name__}")

        # At most one watermark movement is possible per event (a second
        # would need a fresh checkpoint result from ourselves).
        if self.checkpoint_tracker.state == CheckpointState.GARBAGE_COLLECTABLE:
            new_low = self.checkpoint_tracker.garbage_collect()
            # Deviation from the reference, which drops the Truncate action
            # returned here (state_machine.go:243), leaving the durable WAL
            # to grow until recovery: we emit it so the WAL stays bounded.
            actions.concat(self.persisted.truncate(new_low))
            ci = self.checkpoint_tracker.network_config.checkpoint_interval
            if new_low > ci:
                # Keep one extra checkpoint interval of batches for epoch change.
                self.batch_tracker.truncate(new_low - ci)
            actions.concat(self.epoch_tracker.move_low_watermark(new_low))

        # Mid-epoch catch-up (docs/Divergences.md #13): when a weak quorum
        # attests a checkpoint beyond our tracker windows, transfer to it.
        # The reference strands a replica the cluster outruns within one
        # epoch (state transfer only arms via epoch changes); this
        # completes that path the same way Divergences #8 completed
        # transfer failure.
        target = self.checkpoint_tracker.catch_up_target
        if target is not None:
            seq_no, value = target
            if seq_no <= self.commit_state.highest_commit:
                self.checkpoint_tracker.catch_up_target = None  # stale
            elif not self.commit_state.transferring:
                self.checkpoint_tracker.catch_up_target = None
                actions.concat(self.commit_state.transfer_to(seq_no, value))
            # else: a transfer is in flight — keep the target armed
            # (checkpoint messages are sent once; dropping it here could
            # strand the replica if the cluster quiesces before anything
            # else re-arms it).

        # Fixpoint: drain commits and advance the epoch until quiescent.
        while True:
            actions.concat(self.commit_state.drain())
            loop_actions = self.epoch_tracker.advance_state()
            if not loop_actions:
                break
            actions.concat(loop_actions)

        return actions

    # --- message routing (reference state_machine.go:310-349) ---

    def step(self, source: int, msg: Msg) -> Actions:
        t = msg.__class__
        if t is Prepare or t is Commit:
            # Hot path: three-phase-commit traffic for the current in-progress
            # epoch goes straight to the active epoch, skipping the
            # tracker/target routing hops (same classification outcome).
            target = self.epoch_tracker.current_epoch
            if (
                msg.epoch == target.number
                and target.state is _ET_IN_PROGRESS
            ):
                return target.active_epoch.step(source, msg)
            return self.epoch_tracker.step(source, msg)
        if t is AckBatch or t is AckMsg or t is FetchRequest or t is ForwardRequest:
            return self.client_hash_disseminator.step(source, msg)
        if t is MsgBatch:
            # Transport envelope: one event for the whole envelope (the
            # post-event fixpoint in apply_event runs once), and — when the
            # native vote plane is live — the envelope's Prepare/Commit
            # votes are applied with a single native call on a packed
            # representation shared by every receiver (voteplane.py).
            target = self.epoch_tracker.current_epoch
            if target.state is _ET_IN_PROGRESS:
                plane = target.active_epoch.seq_plane
                if plane is not None:
                    packed, vote_msgs, rest = split_votes(msg)
                    if vote_msgs:
                        actions = target.active_epoch.apply_envelope_votes(
                            packed, vote_msgs, source, self.step
                        )
                        for inner in rest:
                            actions.concat(self.step(source, inner))
                        return actions
            actions = Actions()
            for inner in msg.msgs:
                actions.concat(self.step(source, inner))
            return actions
        if t is CheckpointMsg:
            self.checkpoint_tracker.step(source, msg)
            return Actions()
        if t is FetchBatch or t is ForwardBatch:
            return self.batch_tracker.step(source, msg)
        if isinstance(
            msg,
            (
                Suspect,
                EpochChange,
                EpochChangeAck,
                NewEpoch,
                NewEpochEcho,
                NewEpochReady,
                Preprepare,
            ),
        ):
            return self.epoch_tracker.step(source, msg)
        raise AssertionError(f"unexpected message type {type(msg).__name__}")

    # --- hash results: the TPU return path (reference :351-371) ---

    def _process_hash_result(self, event: st.EventHashResult) -> Actions:
        origin = event.origin
        if isinstance(origin, st.BatchOrigin):
            self.batch_tracker.add_batch(
                origin.seq_no, event.digest, origin.request_acks
            )
            return self.epoch_tracker.apply_batch_hash_result(
                origin.epoch, origin.seq_no, event.digest
            )
        if isinstance(origin, st.EpochChangeOrigin):
            return self.epoch_tracker.apply_epoch_change_digest(
                origin, event.digest
            )
        if isinstance(origin, st.VerifyBatchOrigin):
            actions = Actions()
            self.batch_tracker.apply_verify_batch_hash_result(event.digest, origin)
            if (
                not self.batch_tracker.has_fetch_in_flight()
                and self.epoch_tracker.current_epoch.state
                == EpochTargetState.FETCHING
            ):
                actions.concat(
                    self.epoch_tracker.current_epoch.fetch_new_epoch_state()
                )
            return actions
        raise AssertionError("no hash origin type set")

    # --- checkpoint results (reference :373-401) ---

    def _process_checkpoint_result(self, result: st.EventCheckpointResult) -> Actions:
        actions = Actions()
        if result.seq_no < self.commit_state.low_watermark:
            return actions  # stale result after state transfer

        expected = (
            self.commit_state.low_watermark
            + self.commit_state.active_state.config.checkpoint_interval
        )
        if expected != result.seq_no:
            raise AssertionError(
                "checkpoint results must be exactly one interval after the last"
            )

        completing_reconfiguration = bool(
            self.commit_state.active_state.pending_reconfigurations
        )
        prev_stop = self.commit_state.stop_at_seq_no
        actions.concat(self.commit_state.apply_checkpoint_result(result))
        if completing_reconfiguration and not self.commit_state.transferring:
            # This checkpoint applied a reconfiguration: the epoch ends here.
            # _reinitialize appends the FEntry, truncates the log through the
            # new CEntry, and restarts every tracker under the new network
            # state (see _complete_pending_reconfiguration).
            return actions.concat(self._reinitialize())
        if prev_stop < self.commit_state.stop_at_seq_no:
            self.client_tracker.allocate(result.seq_no, result.network_state)
            actions.concat(
                self.client_hash_disseminator.allocate(
                    result.seq_no, result.network_state
                )
            )
        return actions
