"""In-memory mirror of the durable write-ahead log.

Rebuild of reference ``pkg/statemachine/persisted.go``.  Every append emits a
``Persist`` action mirroring the entry to disk; ``truncate`` computes the cut
index and emits a ``Truncate`` action; and — the key trick of the protocol
(reference ``docs/LogMovement.md``) — ``construct_epoch_change`` derives the
PBFT view-change message (checkpoints / P-set / Q-set) purely from the log, so
crash recovery and view change share one code path.

The reference threads a callback-struct visitor (``logIterator``) over a
linked list; here the log is a Python list of (index, entry) pairs and callers
iterate directly — simpler and faster for the host-side hot path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..messages import (
    CEntry,
    CheckpointMsg,
    ECEntry,
    EpochChange,
    EpochChangeSetEntry,
    FEntry,
    NEntry,
    PEntry,
    Persistent,
    QEntry,
    Suspect,
    TEntry,
)
from .actions import Actions


class PersistedLog:
    """Append-only in-memory WAL mirror (reference persisted.go:36-43)."""

    __slots__ = ("next_index", "entries", "logger")

    def __init__(self, logger=None):
        self.next_index = 0
        # list of (index, entry); head is entries[0] after truncation
        self.entries: List[Tuple[int, Persistent]] = []
        self.logger = logger

    # --- loading (recovery path; no Persist actions) ---

    def append_initial_load(self, index: int, entry: Persistent) -> None:
        """Append an entry already read from durable storage
        (reference persisted.go:50-68)."""
        if self.entries:
            if self.next_index != index:
                raise AssertionError(
                    f"WAL indexes out of order: expected {self.next_index}, "
                    f"got {index} — corrupted WAL?"
                )
        else:
            self.next_index = index
        self.entries.append((index, entry))
        self.next_index = index + 1

    # --- appending (normal path; emits Persist) ---

    def append(self, entry: Persistent) -> Actions:
        """Append a new entry and emit the mirroring Persist action
        (reference persisted.go:70-83).  The log must be non-empty (a fresh
        node seeds genesis CEntry/FEntry via append_initial_load)."""
        if not self.entries:
            raise AssertionError(
                "appending to an unseeded log; initialize via append_initial_load"
            )
        index = self.next_index
        self.entries.append((index, entry))
        self.next_index += 1
        return Actions().persist(index, entry)

    # typed helpers mirroring addPEntry/addQEntry/... (persisted.go:85-160)
    def add_p_entry(self, entry: PEntry) -> Actions:
        return self.append(entry)

    def add_q_entry(self, entry: QEntry) -> Actions:
        return self.append(entry)

    def add_n_entry(self, entry: NEntry) -> Actions:
        return self.append(entry)

    def add_c_entry(self, entry: CEntry) -> Actions:
        if entry.network_state is None:
            raise AssertionError("CEntry network state must be set")
        return self.append(entry)

    def add_suspect(self, entry: Suspect) -> Actions:
        return self.append(entry)

    def add_ec_entry(self, entry: ECEntry) -> Actions:
        return self.append(entry)

    def add_f_entry(self, entry: FEntry) -> Actions:
        """Gracefully end the current epoch (reconfiguration boundary).  The
        reference only ever seeds an FEntry at genesis; our reconfiguration
        path appends one when the reconfiguring checkpoint lands, per
        reference docs/LogMovement.md's intended flow."""
        return self.append(entry)

    def add_t_entry(self, entry: TEntry) -> Actions:
        return self.append(entry)

    # --- truncation (reference persisted.go:162-190) ---

    def truncate(self, low_watermark: int) -> Actions:
        """Advance the log head to the first entry that anchors the current
        watermark (CEntry ≥ low_watermark or NEntry > low_watermark) and emit
        a Truncate action for the durable WAL, if the head moved."""
        for pos, (index, entry) in enumerate(self.entries):
            if isinstance(entry, CEntry):
                if entry.seq_no < low_watermark:
                    continue
            elif isinstance(entry, NEntry):
                if entry.seq_no <= low_watermark:
                    continue
            else:
                continue

            if self.logger is not None:
                self.logger.debug(
                    "truncating WAL", seq_no=low_watermark, index=index
                )
            if pos == 0:
                break
            del self.entries[:pos]
            return Actions().truncate(index)

        return Actions()

    # --- view-change derivation (reference persisted.go:245-318) ---

    def construct_epoch_change(self, new_epoch: int) -> EpochChange:
        """Deterministically derive the epoch-change message from the log.

        P-set: for each sequence, only the *latest* PEntry before the target
        epoch survives.  Q-set: every QEntry (per epoch it was logged under).
        Checkpoints: every CEntry still in the log.  Iteration stops once the
        log's epoch (tracked via N/F entries) reaches ``new_epoch``.
        """
        # Pass 1: count PEntries per sequence so only the last one is kept.
        p_counts: Dict[int, int] = {}
        log_epoch: Optional[int] = None
        for _, entry in self.entries:
            if log_epoch is not None and log_epoch >= new_epoch:
                break
            if isinstance(entry, PEntry):
                p_counts[entry.seq_no] = p_counts.get(entry.seq_no, 0) + 1
            elif isinstance(entry, NEntry):
                log_epoch = entry.epoch_config.number
            elif isinstance(entry, FEntry):
                log_epoch = entry.ends_epoch_config.number

        # Pass 2: collect checkpoints, final P entries, and all Q entries.
        checkpoints: List[CheckpointMsg] = []
        p_set: List[EpochChangeSetEntry] = []
        q_set: List[EpochChangeSetEntry] = []
        log_epoch = None
        for _, entry in self.entries:
            if log_epoch is not None and log_epoch >= new_epoch:
                break
            if isinstance(entry, PEntry):
                remaining = p_counts[entry.seq_no]
                if remaining != 1:
                    p_counts[entry.seq_no] = remaining - 1
                    continue
                p_set.append(
                    EpochChangeSetEntry(
                        epoch=log_epoch, seq_no=entry.seq_no, digest=entry.digest
                    )
                )
            elif isinstance(entry, QEntry):
                q_set.append(
                    EpochChangeSetEntry(
                        epoch=log_epoch, seq_no=entry.seq_no, digest=entry.digest
                    )
                )
            elif isinstance(entry, NEntry):
                log_epoch = entry.epoch_config.number
            elif isinstance(entry, FEntry):
                log_epoch = entry.ends_epoch_config.number
            elif isinstance(entry, CEntry):
                checkpoints.append(
                    CheckpointMsg(seq_no=entry.seq_no, value=entry.checkpoint_value)
                )

        return EpochChange(
            new_epoch=new_epoch,
            checkpoints=tuple(checkpoints),
            p_set=tuple(p_set),
            q_set=tuple(q_set),
        )
