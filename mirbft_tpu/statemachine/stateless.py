"""Stateless protocol math: quorums, bucket mapping, committed bitmask, and
the PBFT view-change decision function.

Rebuild of reference ``pkg/statemachine/stateless.go`` semantics:
quorum formulas (stateless.go:106-113), bucket mappings (:115-121), committed
bitmask (:32-100), ``constructNewEpochConfig`` (:123-321), and
``epochChangeHashData`` flattening (:323-352).  All functions are pure; they
run on host CPU — the only compute-heavy consumer (hashing the flattened
epoch-change data) is dispatched to the TPU batcher in ``mirbft_tpu.ops``.
"""

from __future__ import annotations

import bisect

from typing import Dict, List, Mapping, Optional, Protocol, Tuple

from ..messages import (
    CheckpointMsg,
    ClientState,
    EpochChange,
    EpochChangeSetEntry,
    EpochConfig,
    NetworkConfig,
    NewEpochConfig,
)

# ---------------------------------------------------------------------------
# Quorums (reference stateless.go:106-113).
# ---------------------------------------------------------------------------


def intersection_quorum(config: NetworkConfig) -> int:
    """Nodes required so any two such sets share a correct node:
    ceil((n+f+1)/2) == (n+f+2)//2 in truncating math."""
    return (len(config.nodes) + config.f + 2) // 2


def some_correct_quorum(config: NetworkConfig) -> int:
    """Nodes such that at least one is correct: f+1."""
    return config.f + 1


# ---------------------------------------------------------------------------
# Bucket mapping (reference stateless.go:115-121).  Buckets partition the
# request space across leaders — the protocol-level parallelism of Mir.
# ---------------------------------------------------------------------------


def client_req_to_bucket(client_id: int, req_no: int, config: NetworkConfig) -> int:
    return (client_id + req_no) % config.number_of_buckets


def seq_to_bucket(seq_no: int, config: NetworkConfig) -> int:
    return seq_no % config.number_of_buckets


# ---------------------------------------------------------------------------
# Committed bitmask (reference stateless.go:18-100).  MSB-first within each
# byte, matching the reference's wire-compatible committed_mask layout.
# ---------------------------------------------------------------------------


class Bitmask:
    """Mutable MSB-first bitmask over a byte buffer."""

    __slots__ = ("_buf",)

    def __init__(self, data: bytes = b"", nbits: Optional[int] = None):
        if nbits is not None:
            size = (nbits + 7) // 8
            self._buf = bytearray(size)
            # never let the seed data grow the buffer past the declared size
            # (e.g. shrinking a client window must truncate the old mask)
            self._buf[: min(len(data), size)] = data[:size]
        else:
            self._buf = bytearray(data)

    def bits(self) -> int:
        return 8 * len(self._buf)

    def is_bit_set(self, bit_index: int) -> bool:
        byte_index = bit_index // 8
        if byte_index >= len(self._buf):
            return False
        return bool(self._buf[byte_index] & (0x80 >> (bit_index % 8)))

    def set_bit(self, bit_index: int) -> None:
        byte_index = bit_index // 8
        if byte_index >= len(self._buf):
            raise IndexError(
                f"bit {bit_index} out of range for {len(self._buf)}-byte mask"
            )
        self._buf[byte_index] |= 0x80 >> (bit_index % 8)

    def to_bytes(self) -> bytes:
        return bytes(self._buf)


def is_committed(req_no: int, client_state: ClientState) -> bool:
    """Reference stateless.go:18-30, with the window bound made exclusive:
    the client window is exactly ``width`` slots [lw, lw+width-1].  The
    reference exposes width+1 slots here (``> lw+width``) while its
    committing-client bookkeeping tracks width slots, which overflows its
    fixed slice and trips its full-window assertions once a large batch
    commits an entire client window within one checkpoint interval."""
    offset = req_no - client_state.low_watermark
    if offset < 0:
        return True
    if offset >= client_state.width:
        return False
    # Allocation-free Bitmask(...).is_bit_set(offset): this runs on the
    # window-allocation and commit-drain hot paths.
    mask = client_state.committed_mask
    byte_index = offset >> 3
    if byte_index >= len(mask):
        return False  # short/empty mask: bit unset (Bitmask.is_bit_set)
    return bool(mask[byte_index] & (0x80 >> (offset & 7)))


# ---------------------------------------------------------------------------
# Epoch-change hash flattening (reference stateless.go:323-352).  The result
# feeds an ActionHashRequest, which the TPU batcher concatenates + pads into a
# fixed-shape SHA-256 dispatch.
# ---------------------------------------------------------------------------


def uint64_to_bytes(value: int) -> bytes:
    return value.to_bytes(8, "big")


def epoch_change_hash_data(epoch_change: EpochChange) -> List[bytes]:
    """Flatten an EpochChange into the canonical byte-slice list whose hash
    identifies it: [new_epoch, (seq,value)*, (epoch,seq,digest)* for P and Q]."""
    out: List[bytes] = [uint64_to_bytes(epoch_change.new_epoch)]
    for cp in epoch_change.checkpoints:
        out.append(uint64_to_bytes(cp.seq_no))
        out.append(cp.value)
    for entry in epoch_change.p_set:
        out.append(uint64_to_bytes(entry.epoch))
        out.append(uint64_to_bytes(entry.seq_no))
        out.append(entry.digest)
    for entry in epoch_change.q_set:
        out.append(uint64_to_bytes(entry.epoch))
        out.append(uint64_to_bytes(entry.seq_no))
        out.append(entry.digest)
    return out


# ---------------------------------------------------------------------------
# The PBFT view-change decision function (reference stateless.go:123-321).
# ---------------------------------------------------------------------------


class ParsedEpochChangeLike(Protocol):
    """Minimal view of epoch_change.ParsedEpochChange needed here."""

    underlying: EpochChange
    low_watermark: int
    p_set: Mapping[int, EpochChangeSetEntry]  # seq_no -> entry
    q_set: Mapping[int, Mapping[int, bytes]]  # seq_no -> {epoch -> digest}


def construct_new_epoch_config(
    config: NetworkConfig,
    new_leaders: Tuple[int, ...],
    epoch_changes: Mapping[int, "ParsedEpochChangeLike"],
) -> Optional[NewEpochConfig]:
    """Deterministically derive the new-epoch configuration from ≥2f+1 epoch
    changes, or return None if no decision is possible yet.

    Implements the classic PBFT new-view computation, multi-bucket flavored:
    1. Starting checkpoint: the max seq checkpoint supported by a weak quorum
       (value agreement) whose seq is covered by an intersection quorum of
       low-watermarks.
    2. Per sequence in the 2-checkpoint-interval window after it, select a
       P-set digest satisfying conditions A1 (intersection quorum saw nothing
       newer/conflicting) and A2 (weak quorum has it in Q-set), else require
       condition B (intersection quorum has no P-entry → null request), else
       no decision yet.
    """
    # --- starting checkpoint selection ---
    checkpoint_supporters: Dict[Tuple[int, bytes], List[int]] = {}
    new_epoch_number = 0
    # iterate in config.nodes order for determinism
    for node in config.nodes:
        ec = epoch_changes.get(node)
        if ec is None:
            continue
        new_epoch_number = ec.underlying.new_epoch
        # dedup per node: a byzantine node listing the same checkpoint twice
        # must not count twice toward the weak quorum
        seen = set()
        for cp in ec.underlying.checkpoints:
            key = (cp.seq_no, cp.value)
            if key in seen:
                continue
            seen.add(key)
            checkpoint_supporters.setdefault(key, []).append(node)

    max_checkpoint: Optional[Tuple[int, bytes]] = None
    for key, supporters in checkpoint_supporters.items():
        if len(supporters) < some_correct_quorum(config):
            continue
        lower_watermarks = sum(
            1 for ec in epoch_changes.values() if ec.low_watermark <= key[0]
        )
        if lower_watermarks < intersection_quorum(config):
            continue
        if max_checkpoint is None:
            max_checkpoint = key
            continue
        if max_checkpoint[0] > key[0]:
            continue
        if max_checkpoint[0] == key[0]:
            raise AssertionError(
                f"two correct quorums disagree on checkpoint value at seq "
                f"{key[0]}: {max_checkpoint[1].hex()} != {key[1].hex()}"
            )
        max_checkpoint = key

    if max_checkpoint is None:
        return None

    cp_seq, cp_value = max_checkpoint
    window = 2 * config.checkpoint_interval
    final_preprepares: List[bytes] = [b""] * window
    any_selected = False

    # Precomputation for the per-sequence scan: the window is 2 checkpoint
    # intervals wide and p-sets are sparse (empty on a graceful rotation),
    # so probing every (offset, node) pair costs O(window * n) dict lookups
    # for nothing.  One pass over the p-sets yields, per offset, the
    # candidate entries in config.nodes order (the A-scan's iteration
    # order) and the count of changes that admit the seq with no P-entry
    # (condition B's numerator, combined with the sorted-watermark count).
    candidates: List[List] = [[] for _ in range(window)]
    entry_counts = [0] * window  # changes with lw < seq AND a P-entry at seq
    for node in config.nodes:  # deterministic order
        node_ec = epoch_changes.get(node)
        if node_ec is None:
            continue
        lw = node_ec.low_watermark
        for p_seq, p_entry in node_ec.p_set.items():
            p_off = p_seq - cp_seq - 1
            if 0 <= p_off < window:
                candidates[p_off].append(p_entry)
                if lw < p_seq:
                    entry_counts[p_off] += 1
    sorted_lws = sorted(ec.low_watermark for ec in epoch_changes.values())

    for offset in range(window):
        seq_no = cp_seq + 1 + offset
        selected: Optional[EpochChangeSetEntry] = None

        for entry in candidates[offset]:

            # Condition A1: ≥ intersection quorum of nodes whose watermark
            # admits seq_no either saw nothing newer at seq_no, or agree.
            a1 = 0
            for other in epoch_changes.values():
                if other.low_watermark >= seq_no:
                    continue
                other_entry = other.p_set.get(seq_no)
                if other_entry is None or other_entry.epoch < entry.epoch:
                    a1 += 1
                    continue
                if other_entry.epoch > entry.epoch:
                    continue
                if other_entry.digest == entry.digest:
                    a1 += 1
            if a1 < intersection_quorum(config):
                continue

            # Condition A2: a weak quorum preprepared this digest at an epoch
            # ≥ entry.epoch (it survives in their Q-sets).
            a2 = 0
            for other in epoch_changes.values():
                epoch_digests = other.q_set.get(seq_no)
                if not epoch_digests:
                    continue
                if any(
                    epoch >= entry.epoch and digest == entry.digest
                    for epoch, digest in epoch_digests.items()
                ):
                    a2 += 1
            if a2 < some_correct_quorum(config):
                continue

            selected = entry
            break

        if selected is not None:
            final_preprepares[offset] = selected.digest
            any_selected = True
            continue

        # Condition B: an intersection quorum has no P-entry at seq_no
        # (→ safe to fill with a null request).  #changes with lw < seq_no
        # minus those that do have a P-entry there (precomputed above).
        b_count = (
            bisect.bisect_left(sorted_lws, seq_no) - entry_counts[offset]
        )
        if b_count < intersection_quorum(config):
            return None  # cannot satisfy A or B yet; wait for more changes

    return NewEpochConfig(
        config=EpochConfig(
            number=new_epoch_number,
            leaders=new_leaders,
            planned_expiration=cp_seq + config.max_epoch_length,
        ),
        starting_checkpoint=CheckpointMsg(seq_no=cp_seq, value=cp_value),
        final_preprepares=tuple(final_preprepares) if any_selected else (),
    )
