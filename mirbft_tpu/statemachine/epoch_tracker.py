"""Epoch tracker: owns the current epoch target and routes epoch traffic.

Rebuild of reference ``pkg/statemachine/epoch_tracker.go``: routes the 10
epoch-scoped message types by epoch number (past-drop / future-buffer /
current-apply, :313-332), recovery logic deciding resume vs epoch-change from
the last N/F/EC entries (:60-218), f+1 max-epoch jump on ticks (:376-406),
and rolling to the next epoch target when the current one is done (:220-273).
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import state as st
from ..messages import (
    CEntry,
    Commit,
    ECEntry,
    EpochChange,
    EpochChangeAck,
    FEntry,
    Msg,
    NEntry,
    NewEpoch,
    NewEpochEcho,
    NewEpochReady,
    Preprepare,
    Prepare,
    QEntry,
    Suspect,
)
from ..state import EventInitialParameters
from .actions import EMPTY_ACTIONS, Actions
from .batch_tracker import BatchTracker
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .disseminator import ClientHashDisseminator
from .epoch_change import ParsedEpochChange
from .epoch_target import EpochTarget, EpochTargetState
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import PersistedLog
from .stateless import some_correct_quorum

TICKS_OUT_OF_CORRECT_EPOCH_LIMIT = 10


def epoch_for_msg(msg: Msg) -> int:
    """Reference epoch_tracker.go:277-300."""
    if isinstance(msg, (Preprepare, Prepare, Commit, Suspect)):
        return msg.epoch
    if isinstance(msg, EpochChange):
        return msg.new_epoch
    if isinstance(msg, EpochChangeAck):
        return msg.epoch_change.new_epoch
    if isinstance(msg, NewEpoch):
        return msg.new_config.config.number
    if isinstance(msg, (NewEpochEcho, NewEpochReady)):
        return msg.config.config.number
    raise AssertionError(f"unexpected epoch message type {type(msg).__name__}")


class EpochTracker:
    """Reference epoch_tracker.go:17-41."""

    __slots__ = (
        "current_epoch",
        "persisted",
        "node_buffers",
        "commit_state",
        "network_config",
        "logger",
        "my_config",
        "batch_tracker",
        "client_tracker",
        "client_hash_disseminator",
        "future_msgs",
        "needs_state_transfer",
        "max_epochs",
        "max_correct_epoch",
        "ticks_out_of_correct_epoch",
    )

    def __init__(
        self,
        persisted: PersistedLog,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        my_config: EventInitialParameters,
        batch_tracker: BatchTracker,
        client_tracker: ClientTracker,
        client_hash_disseminator: ClientHashDisseminator,
        logger=None,
    ):
        self.current_epoch: Optional[EpochTarget] = None
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.commit_state = commit_state
        self.network_config = None
        self.my_config = my_config
        self.batch_tracker = batch_tracker
        self.client_tracker = client_tracker
        self.client_hash_disseminator = client_hash_disseminator
        self.logger = logger
        self.future_msgs: Dict[int, MsgBuffer] = {}
        self.needs_state_transfer = False
        self.max_epochs: Dict[int, int] = {}
        self.max_correct_epoch = 0
        self.ticks_out_of_correct_epoch = 0

    def _new_target(self, number: int) -> EpochTarget:
        return EpochTarget(
            number,
            self.persisted,
            self.node_buffers,
            self.commit_state,
            self.client_tracker,
            self.client_hash_disseminator,
            self.batch_tracker,
            self.network_config,
            self.my_config,
            self.logger,
        )

    # --- recovery (reference epoch_tracker.go:60-218) ---

    def reinitialize(self) -> Actions:
        self.network_config = self.commit_state.active_state.config

        new_future_msgs = {}
        for node in self.network_config.nodes:
            buf = self.future_msgs.get(node)
            if buf is None:
                buf = MsgBuffer("future-epochs", self.node_buffers.node_buffer(node))
            new_future_msgs[node] = buf
        self.future_msgs = new_future_msgs

        actions = Actions()
        last_n: Optional[NEntry] = None
        last_ec: Optional[ECEntry] = None
        last_f: Optional[FEntry] = None
        highest_preprepared = 0
        for _, entry in self.persisted.entries:
            if isinstance(entry, NEntry):
                last_n = entry
            elif isinstance(entry, FEntry):
                last_f = entry
            elif isinstance(entry, ECEntry):
                last_ec = entry
            elif isinstance(entry, QEntry):
                highest_preprepared = max(highest_preprepared, entry.seq_no)
            elif isinstance(entry, CEntry):
                # After state transfer we may have a CEntry with no QEntry.
                highest_preprepared = max(highest_preprepared, entry.seq_no)

        if last_n is None and last_f is None:
            raise AssertionError("no active epoch and no last epoch in log")
        if last_n is not None and last_f is not None:
            if last_n.epoch_config.number <= last_f.ends_epoch_config.number:
                raise AssertionError(
                    "new epoch number must exceed last terminated epoch"
                )

        if last_n is not None and (
            last_ec is None or last_ec.epoch_number <= last_n.epoch_config.number
        ):
            # Reinitializing mid-epoch: resume it (and suspect it, since we
            # may have missed traffic while down).
            self.current_epoch = self._new_target(last_n.epoch_config.number)
            starting_seq_no = highest_preprepared + 1
            ci = self.network_config.checkpoint_interval
            while starting_seq_no % ci != 1:
                # Advance to the first sequence after some checkpoint, so we
                # never re-consent on sequences we already consented on.
                starting_seq_no += 1
                self.needs_state_transfer = True
            self.current_epoch.starting_seq_no = starting_seq_no
            self.current_epoch.state = EpochTargetState.RESUMING
            self.current_epoch.resume_epoch_config = last_n.epoch_config
            suspect = Suspect(epoch=last_n.epoch_config.number)
            actions.concat(self.persisted.add_suspect(suspect))
            actions.send(self.network_config.nodes, suspect)
        else:
            if last_f is not None and (
                last_ec is None
                or last_ec.epoch_number <= last_f.ends_epoch_config.number
            ):
                # Graceful epoch end, epoch change not yet sent: create it.
                last_ec = ECEntry(
                    epoch_number=last_f.ends_epoch_config.number + 1
                )
                actions.concat(self.persisted.add_ec_entry(last_ec))

            assert last_ec is not None
            if (
                self.current_epoch is not None
                and self.current_epoch.number == last_ec.epoch_number
            ):
                # Reinitialized during an epoch change; keep going.
                return actions.concat(self.current_epoch.advance_state())

            epoch_change = self.persisted.construct_epoch_change(
                last_ec.epoch_number
            )
            parsed = ParsedEpochChange(epoch_change)
            self.current_epoch = self._new_target(epoch_change.new_epoch)
            self.current_epoch.my_epoch_change = parsed
            # Leader selection is a placeholder in the reference too
            # (epoch_tracker.go:202-205): all nodes lead.
            self.current_epoch.my_leader_choice = self.network_config.nodes

        for node in self.network_config.nodes:
            self.future_msgs[node].iterate(
                self.filter,
                lambda source, msg: actions.concat(self.apply_msg(source, msg)),
            )
        return actions

    # --- epoch rollover (reference epoch_tracker.go:220-273) ---

    def advance_state(self) -> Actions:
        if self.current_epoch.state < EpochTargetState.DONE:
            return self.current_epoch.advance_state()

        if self.commit_state.checkpoint_pending:
            # Wait for pending checkpoints before initiating epoch change.
            return EMPTY_ACTIONS

        new_epoch_number = self.current_epoch.number + 1
        if self.max_correct_epoch > new_epoch_number:
            new_epoch_number = self.max_correct_epoch
        epoch_change = self.persisted.construct_epoch_change(new_epoch_number)
        my_epoch_change = ParsedEpochChange(epoch_change)

        self.current_epoch = self._new_target(new_epoch_number)
        self.current_epoch.my_epoch_change = my_epoch_change
        self.current_epoch.my_leader_choice = (self.my_config.id,)
        if self.logger is not None:
            self.logger.info(
                "initiating epoch change", new_epoch=new_epoch_number
            )

        actions = self.persisted.add_ec_entry(
            ECEntry(epoch_number=new_epoch_number)
        ).send(self.network_config.nodes, epoch_change)

        for node in self.network_config.nodes:
            self.future_msgs[node].iterate(
                self.filter,
                lambda source, msg: actions.concat(self.apply_msg(source, msg)),
            )
        return actions

    # --- routing (reference epoch_tracker.go:302-372) ---

    def filter(self, _source: int, msg: Msg) -> Applyable:
        epoch_number = epoch_for_msg(msg)
        if epoch_number < self.current_epoch.number:
            return Applyable.PAST
        if epoch_number > self.current_epoch.number:
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step(self, source: int, msg: Msg) -> Actions:
        epoch_number = epoch_for_msg(msg)
        if epoch_number < self.current_epoch.number:
            return EMPTY_ACTIONS
        if epoch_number > self.current_epoch.number:
            if self.max_epochs.get(source, 0) < epoch_number:
                self.max_epochs[source] = epoch_number
            self.future_msgs[source].store(msg)
            return EMPTY_ACTIONS
        return self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: Msg) -> Actions:
        target = self.current_epoch
        if isinstance(msg, (Preprepare, Prepare, Commit)):
            return target.step(source, msg)
        if isinstance(msg, Suspect):
            target.apply_suspect_msg(source)
            return Actions()
        if isinstance(msg, EpochChange):
            return target.apply_epoch_change_msg(source, msg)
        if isinstance(msg, EpochChangeAck):
            return target.apply_epoch_change_ack_msg(
                source, msg.originator, msg.epoch_change
            )
        if isinstance(msg, NewEpoch):
            if msg.new_config.config.number % len(self.network_config.nodes) != source:
                return Actions()  # not from the epoch primary
            return target.apply_new_epoch_msg(msg)
        if isinstance(msg, NewEpochEcho):
            return target.apply_new_epoch_echo_msg(source, msg.config)
        if isinstance(msg, NewEpochReady):
            return target.apply_new_epoch_ready_msg(source, msg.config)
        raise AssertionError(f"unexpected epoch message type {type(msg).__name__}")

    def apply_batch_hash_result(
        self, epoch: int, seq_no: int, digest: bytes
    ) -> Actions:
        if (
            epoch != self.current_epoch.number
            or self.current_epoch.state != EpochTargetState.IN_PROGRESS
        ):
            return Actions()
        return self.current_epoch.active_epoch.apply_batch_hash_result(
            seq_no, digest
        )

    def apply_epoch_change_digest(
        self, origin: st.EpochChangeOrigin, digest: bytes
    ) -> Actions:
        target_number = origin.epoch_change.new_epoch
        if target_number < self.current_epoch.number:
            return Actions()  # old epoch we no longer care about
        if target_number > self.current_epoch.number:
            raise AssertionError(
                f"epoch change digest for future epoch {target_number} while "
                f"processing {self.current_epoch.number}"
            )
        return self.current_epoch.apply_epoch_change_digest(origin, digest)

    # --- ticks (reference epoch_tracker.go:376-406) ---

    def tick(self) -> Actions:
        for max_epoch in self.max_epochs.values():
            if max_epoch <= self.max_correct_epoch:
                continue
            # Count nodes reporting an epoch ≥ max_epoch.  (Deviation from
            # the reference, which seeds the count at 1 — effectively
            # counting ourselves as a supporter of an epoch we never saw,
            # letting a single byzantine report reach f+1 when f=1.)
            matches = sum(
                1 for reported in self.max_epochs.values() if reported >= max_epoch
            )
            if matches < some_correct_quorum(self.network_config):
                continue
            self.max_correct_epoch = max_epoch

        if self.max_correct_epoch > self.current_epoch.number:
            self.ticks_out_of_correct_epoch += 1
            if self.ticks_out_of_correct_epoch > TICKS_OUT_OF_CORRECT_EPOCH_LIMIT:
                self.current_epoch.state = EpochTargetState.DONE

        return self.current_epoch.tick()

    def move_low_watermark(self, seq_no: int) -> Actions:
        return self.current_epoch.move_low_watermark(seq_no)
