"""Checkpoint agreement tracking and watermark garbage collection.

Rebuild of reference ``pkg/statemachine/checkpoints.go``: per-seq checkpoint
value agreement (f+1 → committed value; self + intersection quorum → stable,
:270-305), ≥3 active checkpoint windows, highest-checkpoint tracking per node
for far-future GC (:199-241), and buffered checkpoint messages.

Deviation from the reference (hardening): ``Checkpoint.apply_checkpoint_msg``
dedups votes per source node — the reference counts a duplicate Checkpoint
message from the same node twice toward quorum (checkpoints.go:277-279).
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional

from ..messages import CEntry, CheckpointMsg, Msg, NetworkConfig
from ..state import EventInitialParameters
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .persisted import PersistedLog
from .stateless import intersection_quorum, some_correct_quorum


class CheckpointState(enum.IntEnum):
    IDLE = 0
    GARBAGE_COLLECTABLE = 1


class Checkpoint:
    """Agreement state for one checkpoint seq_no (reference checkpoints.go:247-305)."""

    __slots__ = (
        "seq_no",
        "my_id",
        "network_config",
        "logger",
        "values",
        "committed_value",
        "my_value",
        "stable",
    )

    def __init__(self, seq_no: int, network_config: NetworkConfig, my_id: int, logger=None):
        self.seq_no = seq_no
        self.my_id = my_id
        self.network_config = network_config
        self.logger = logger
        self.values: Dict[bytes, List[int]] = {}
        self.committed_value: Optional[bytes] = None
        self.my_value: Optional[bytes] = None
        self.stable = False

    def apply_checkpoint_msg(self, source: int, value: bytes) -> None:
        supporters = self.values.setdefault(value, [])
        if source in supporters:
            return  # dedup double-votes (hardening vs reference)
        supporters.append(source)
        agreements = len(supporters)

        if agreements == some_correct_quorum(self.network_config):
            self.committed_value = value
        if source == self.my_id:
            self.my_value = value

        if self.my_value is not None and self.committed_value is not None and not self.stable:
            if value != self.committed_value:
                # Byzantine-assumption violation; reference panics here too.
                raise AssertionError(
                    "my checkpoint disagrees with the committed network view"
                )
            # >= (not ==): our own agreement may arrive after 2f+1 others.
            if agreements >= intersection_quorum(self.network_config):
                self.stable = True
                if self.logger is not None:
                    self.logger.debug(
                        "checkpoint stable",
                        seq_no=self.seq_no,
                        agreements=agreements,
                    )


class CheckpointTracker:
    """Reference checkpoints.go:29-245."""

    __slots__ = (
        "state",
        "highest_checkpoints",
        "checkpoint_map",
        "active_checkpoints",
        "msg_buffers",
        "network_config",
        "persisted",
        "node_buffers",
        "my_config",
        "logger",
        "catch_up_target",
    )

    def __init__(
        self,
        persisted: PersistedLog,
        node_buffers: NodeBuffers,
        my_config: EventInitialParameters,
        logger=None,
    ):
        self.state = CheckpointState.IDLE
        self.persisted = persisted
        self.node_buffers = node_buffers
        self.my_config = my_config
        self.logger = logger
        self.highest_checkpoints: Dict[int, int] = {}
        self.checkpoint_map: Dict[int, Checkpoint] = {}
        self.active_checkpoints: List[Checkpoint] = []
        self.msg_buffers: Dict[int, MsgBuffer] = {}
        self.network_config: Optional[NetworkConfig] = None
        # (seq_no, value) of a weak-quorum-attested checkpoint beyond our
        # windows — the mid-epoch catch-up trigger (docs/Divergences.md
        # #13).  Consumed by the machine's post-event hook.
        self.catch_up_target: Optional[Tuple[int, bytes]] = None

    # --- (re)initialization (reference checkpoints.go:56-112) ---

    def reinitialize(self) -> None:
        old_checkpoint_map = self.checkpoint_map
        old_msg_buffers = self.msg_buffers

        self.highest_checkpoints = {}
        self.checkpoint_map = {}
        self.active_checkpoints = []
        self.msg_buffers = {}
        self.network_config = None
        self.catch_up_target = None

        for _, entry in self.persisted.entries:
            if not isinstance(entry, CEntry):
                continue
            if self.network_config is None:
                # Fixed until next reinitialize.
                self.network_config = entry.network_state.config
            cp = self.checkpoint(entry.seq_no)
            cp.apply_checkpoint_msg(self.my_config.id, entry.checkpoint_value)
            self.active_checkpoints.append(cp)

        assert self.active_checkpoints, "log must contain a CEntry"
        self.active_checkpoints[0].stable = True

        valid_nodes = set(self.network_config.nodes)
        for node in self.network_config.nodes:
            buffer = old_msg_buffers.get(node)
            if buffer is None:
                buffer = MsgBuffer(
                    "checkpoints", self.node_buffers.node_buffer(node)
                )
            self.msg_buffers[node] = buffer

        # Re-apply remembered agreements (commutative, order-independent).
        for seq_no, cp in old_checkpoint_map.items():
            if seq_no < self.low_watermark():
                continue
            for value, agreements in cp.values.items():
                for node in agreements:
                    if node in valid_nodes:
                        self.apply_checkpoint_msg(node, seq_no, value)

        self.garbage_collect()

    # --- message handling (reference checkpoints.go:114-152) ---

    def filter(self, _source: int, msg: Msg) -> Applyable:
        assert isinstance(msg, CheckpointMsg)
        if msg.seq_no < self.active_checkpoints[0].seq_no:
            return Applyable.PAST
        if msg.seq_no > self.high_watermark():
            return Applyable.FUTURE
        return Applyable.CURRENT

    def step(self, source: int, msg: Msg) -> None:
        verdict = self.filter(source, msg)
        if verdict == Applyable.PAST:
            return
        if verdict == Applyable.FUTURE:
            self.msg_buffers[source].store(msg)
        # FUTURE messages are both buffered and applied (they feed
        # highest-checkpoint tracking); CURRENT just applied.
        self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: Msg) -> None:
        assert isinstance(msg, CheckpointMsg)
        self.apply_checkpoint_msg(source, msg.seq_no, msg.value)

    # --- GC (reference checkpoints.go:154-180) ---

    def garbage_collect(self) -> int:
        """Drop all windows below the highest stable checkpoint, extend to ≥3
        active windows, re-drain buffers; returns the new low watermark."""
        highest_stable_idx = 0
        for i, cp in enumerate(self.active_checkpoints):
            if not cp.stable:
                break
            highest_stable_idx = i

        for cp in self.active_checkpoints[:highest_stable_idx]:
            self.checkpoint_map.pop(cp.seq_no, None)
        self.active_checkpoints = self.active_checkpoints[highest_stable_idx:]

        while len(self.active_checkpoints) < 3:
            next_seq = self.high_watermark() + self.network_config.checkpoint_interval
            self.active_checkpoints.append(self.checkpoint(next_seq))

        for node in self.network_config.nodes:
            self.msg_buffers[node].iterate(self.filter, self.apply_msg)

        self.state = CheckpointState.IDLE
        return self.active_checkpoints[0].seq_no

    # --- accessors ---

    def checkpoint(self, seq_no: int) -> Checkpoint:
        cp = self.checkpoint_map.get(seq_no)
        if cp is None:
            cp = Checkpoint(
                seq_no, self.network_config, self.my_config.id, self.logger
            )
            self.checkpoint_map[seq_no] = cp
        return cp

    def high_watermark(self) -> int:
        return self.active_checkpoints[-1].seq_no

    def low_watermark(self) -> int:
        return self.active_checkpoints[0].seq_no

    # --- agreement application (reference checkpoints.go:199-241) ---

    def apply_checkpoint_msg(self, source: int, seq_no: int, value: bytes) -> None:
        above_high = seq_no > self.high_watermark()
        if above_high:
            highest = self.highest_checkpoints.get(source)
            if highest is None or seq_no > highest:
                self.highest_checkpoints[source] = seq_no
            # Deliberate divergence from the reference (part of
            # Divergences.md #13): the reference drops a source's LATER
            # above-window checkpoints outright (checkpoints.go:199-241,
            # replace-only-if-greater with an early return), which was
            # harmless when the tracking only fed far-future GC — but the
            # catch-up trigger needs f+1 agreement on a VALUE, and
            # staggered first-reports (e.g. under drop manglers) would
            # otherwise never converge on any single seq_no.  Agreements
            # keep accumulating; the per-checkpoint dedup handles repeats.

        cp = self.checkpoint(seq_no)
        cp.apply_checkpoint_msg(source, value)

        if above_high and cp.committed_value is not None:
            # A weak quorum attests a checkpoint beyond every window we
            # track: the network has provably moved past anything our
            # commit window can reach.  Arm the mid-epoch catch-up
            # transfer (docs/Divergences.md #13) — the reference has no
            # such path and strands a replica that falls this far behind
            # inside one epoch (its harness only exercises catch-up
            # against a quiescent cluster).
            cur = self.catch_up_target
            if cur is None or seq_no > cur[0]:
                self.catch_up_target = (seq_no, cp.committed_value)

        if cp.stable and seq_no > self.low_watermark() and not above_high:
            self.state = CheckpointState.GARBAGE_COLLECTABLE
            return

        if not above_high:
            return

        # GC any above-window checkpoints no node claims as current anymore.
        referenced = {cp.seq_no for cp in self.active_checkpoints}
        referenced.update(self.highest_checkpoints.values())
        for seq in list(self.checkpoint_map):
            if seq not in referenced:
                del self.checkpoint_map[seq]
