"""Batch observation index and epoch-change-time batch fetching.

Rebuild of reference ``pkg/statemachine/batch_tracker.go``: indexes observed
batches by digest (from Preprepares and QEntry replay), and implements the
``FetchBatch`` → ``ForwardBatch`` → hash-verify (``VerifyBatchOrigin``) flow
used when a new-epoch config references batches we never saw (:109-218).
The verify hash runs on the TPU batcher alongside normal batch digests.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .. import state as st
from ..messages import FetchBatch, ForwardBatch, Msg, QEntry, RequestAck
from .actions import Actions
from .persisted import PersistedLog


class Batch:
    __slots__ = ("observed_for", "request_acks")

    def __init__(self, request_acks: Tuple[RequestAck, ...]):
        self.observed_for: Set[int] = set()
        self.request_acks = request_acks


class BatchTracker:
    """Reference batch_tracker.go:18-46."""

    __slots__ = ("batches_by_digest", "fetch_in_flight", "persisted")

    def __init__(self, persisted: PersistedLog):
        self.batches_by_digest: Dict[bytes, Batch] = {}
        # digest -> seq_nos being fetched (a list: identical digests may be
        # fetched for multiple seq_nos, e.g. empty batches)
        self.fetch_in_flight: Dict[bytes, List[int]] = {}
        self.persisted = persisted

    def reinitialize(self) -> None:
        self.batches_by_digest = {}
        self.fetch_in_flight = {}
        for _, entry in self.persisted.entries:
            if isinstance(entry, QEntry):
                self.add_batch(entry.seq_no, entry.digest, entry.requests)

    def step(self, source: int, msg: Msg) -> Actions:
        if isinstance(msg, FetchBatch):
            return self.reply_fetch_batch(source, msg.seq_no, msg.digest)
        if isinstance(msg, ForwardBatch):
            return self.apply_forward_batch_msg(
                source, msg.seq_no, msg.digest, msg.request_acks
            )
        raise AssertionError(f"unexpected batch message type {type(msg).__name__}")

    def truncate(self, seq_no: int) -> None:
        """Drop observations below seq_no (reference batch_tracker.go:69-80)."""
        for digest in list(self.batches_by_digest):
            batch = self.batches_by_digest[digest]
            batch.observed_for = {s for s in batch.observed_for if s >= seq_no}
            if not batch.observed_for:
                del self.batches_by_digest[digest]

    def add_batch(
        self, seq_no: int, digest: bytes, request_acks: Tuple[RequestAck, ...]
    ) -> None:
        """Reference batch_tracker.go:83-108."""
        b = self.batches_by_digest.get(digest)
        if b is None:
            b = Batch(request_acks)
            self.batches_by_digest[digest] = b
        b.observed_for.add(seq_no)

        in_flight = self.fetch_in_flight.pop(digest, None)
        if in_flight is not None:
            b.observed_for.update(in_flight)

    def fetch_batch(self, seq_no: int, digest: bytes, sources: Tuple[int, ...]) -> Actions:
        """Reference batch_tracker.go:110-140."""
        in_flight = self.fetch_in_flight.get(digest)
        if in_flight is not None and seq_no in in_flight:
            return Actions()
        self.fetch_in_flight.setdefault(digest, []).append(seq_no)
        return Actions().send(sources, FetchBatch(seq_no=seq_no, digest=digest))

    def reply_fetch_batch(self, source: int, seq_no: int, digest: bytes) -> Actions:
        batch = self.batches_by_digest.get(digest)
        if batch is None:
            return Actions()  # not necessarily byzantine; just don't have it
        return Actions().send(
            (source,),
            ForwardBatch(
                seq_no=seq_no, request_acks=batch.request_acks, digest=digest
            ),
        )

    def apply_forward_batch_msg(
        self,
        source: int,
        seq_no: int,
        digest: bytes,
        request_acks: Tuple[RequestAck, ...],
    ) -> Actions:
        """An unrequested forward is untrusted and discarded; a requested one
        is re-hashed (on TPU) to verify against the expected digest
        (reference batch_tracker.go:159-180)."""
        if digest not in self.fetch_in_flight:
            return Actions()
        return Actions().hash(
            [ack.digest for ack in request_acks],
            st.VerifyBatchOrigin(
                source=source,
                seq_no=seq_no,
                request_acks=tuple(request_acks),
                expected_digest=digest,
            ),
        )

    def apply_verify_batch_hash_result(
        self, digest: bytes, origin: st.VerifyBatchOrigin
    ) -> None:
        """Reference batch_tracker.go:182-210."""
        if origin.expected_digest != digest:
            raise AssertionError(
                "forwarded batch hash mismatch (byzantine forwarder)"
            )
        in_flight = self.fetch_in_flight.pop(digest, None)
        if in_flight is None:
            return  # duplicate response; already handled one
        b = self.batches_by_digest.get(digest)
        if b is None:
            b = Batch(origin.request_acks)
            self.batches_by_digest[digest] = b
        b.observed_for.update(in_flight)

    def has_fetch_in_flight(self) -> bool:
        return bool(self.fetch_in_flight)

    def get_batch(self, digest: bytes) -> Optional[Batch]:
        return self.batches_by_digest.get(digest)
