"""Commit ordering, checkpoint windows, and per-client committed bookkeeping.

Rebuild of reference ``pkg/statemachine/commitstate.go``: the two
checkpoint-interval halves of pending QEntries (:24-38), in-order ``drain``
emitting Commit actions plus a Checkpoint action at the interval boundary
(:228-269), checkpoint-result application with reconfiguration-aware
``stop_at_seq_no`` gating (:114-153), state-transfer initiation/resume
(:91-112), and the ``committingClient`` mask bookkeeping (:271-366).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..messages import (
    CEntry,
    CheckpointMsg,
    ClientState,
    NetworkConfig,
    NetworkState,
    QEntry,
    ReconfigNewClient,
    ReconfigNewConfig,
    ReconfigRemoveClient,
    ReconfigTransferClient,
    TEntry,
)
from ..state import EventCheckpointResult
from .actions import EMPTY_ACTIONS, Actions
from .persisted import PersistedLog
from .stateless import Bitmask


class CommittingClient:
    """Tracks which request numbers a client committed since the last
    checkpoint (reference commitstate.go:271-366)."""

    __slots__ = ("last_state", "committed_since_last_checkpoint")

    def __init__(self, seq_no: int, client_state: ClientState):
        committed: List[Optional[int]] = [None] * client_state.width
        mask = Bitmask(client_state.committed_mask)
        for i in range(mask.bits()):
            if mask.is_bit_set(i) and i < len(committed):
                committed[i] = seq_no
        self.last_state = client_state
        self.committed_since_last_checkpoint = committed

    def mark_committed(self, seq_no: int, req_no: int) -> None:
        if req_no < self.last_state.low_watermark:
            return
        offset = req_no - self.last_state.low_watermark
        committed = self.committed_since_last_checkpoint
        if offset >= len(committed):
            # Auto-grow up to the window bound.  The reference's fixed-width
            # slice panics here when a large batch commits a client's entire
            # remaining window within one checkpoint interval
            # (commitstate.go:292-298); the window invariant is the real
            # bound, not the slice length.
            if offset >= self.last_state.width:
                raise AssertionError(
                    f"commit for req_no {req_no} beyond client window "
                    f"[{self.last_state.low_watermark}, "
                    f"{self.last_state.low_watermark + self.last_state.width - 1}]"
                )
            committed.extend([None] * (offset + 1 - len(committed)))
        committed[offset] = seq_no

    def create_checkpoint_state(self) -> ClientState:
        """Roll the client window forward at a checkpoint boundary
        (reference commitstate.go:302-366)."""
        old = self.last_state
        committed = self.committed_since_last_checkpoint
        first_uncommitted: Optional[int] = None
        last_committed: Optional[int] = None
        # Scan the FULL window [lw, lw+width-1]: the tracking list may be
        # shorter than the window (it shrinks as checkpoints consume it, and
        # grows on demand); slots beyond it are uncommitted.  The reference
        # scans only its slice, wrongly concluding "all committed" when a
        # client stops submitting mid-window (commitstate.go:306-315).
        for i in range(old.width):
            seq = committed[i] if i < len(committed) else None
            req_no = old.low_watermark + i
            if seq is not None:
                last_committed = req_no
            elif first_uncommitted is None:
                first_uncommitted = req_no

        if last_committed is None:
            new_state = ClientState(
                id=old.id,
                width=old.width,
                width_consumed_last_checkpoint=0,
                low_watermark=old.low_watermark,
                committed_mask=b"",
            )
            self.last_state = new_state
            return new_state

        if first_uncommitted is None:
            # Whole window committed: the generic roll below handles it with
            # first_uncommitted one past the end.  (The reference special-
            # cases this with an assertion that mis-fires when the last
            # checkpoint's consumed slots commit within a later interval,
            # commitstate.go:306-315.)
            first_uncommitted = last_committed + 1

        width_consumed = first_uncommitted - old.low_watermark
        # Shift out the consumed prefix and cap at the window width — the
        # scan above only ever reads `width` slots, and the reference's
        # uncapped reshaping (old[c:] + width-c fresh slots) grows without
        # bound for a slow client (commitstate.go:334-336).
        self.committed_since_last_checkpoint = (
            self.committed_since_last_checkpoint[width_consumed:]
            + [None] * old.width
        )[: old.width]

        mask_bytes = b""
        if last_committed != first_uncommitted:
            mask = Bitmask(nbits=8 * ((last_committed - first_uncommitted) // 8 + 1))
            for i in range(last_committed - first_uncommitted + 1):
                if self.committed_since_last_checkpoint[i] is None:
                    continue
                if i == 0:
                    raise AssertionError(
                        "the first uncommitted request cannot be committed"
                    )
                mask.set_bit(i)
            mask_bytes = mask.to_bytes()

        new_state = ClientState(
            id=old.id,
            width=old.width,
            width_consumed_last_checkpoint=width_consumed,
            low_watermark=first_uncommitted,
            committed_mask=mask_bytes,
        )
        self.last_state = new_state
        return new_state


def next_network_config(
    starting_state: NetworkState,
    committing_clients: Dict[int, CommittingClient],
) -> Tuple[NetworkConfig, Tuple[ClientState, ...]]:
    """Compute the post-checkpoint network config, applying any pending
    reconfigurations (reference commitstate.go:188-225)."""
    next_config = starting_state.config
    next_clients: List[ClientState] = []
    for old_client in starting_state.clients:
        cc = committing_clients.get(old_client.id)
        if cc is None:
            raise AssertionError(
                f"no committing client instance for client {old_client.id}"
            )
        next_clients.append(cc.create_checkpoint_state())

    for reconfig in starting_state.pending_reconfigurations:
        if isinstance(reconfig, ReconfigNewClient):
            next_clients.append(
                ClientState(
                    id=reconfig.id,
                    width=reconfig.width,
                    width_consumed_last_checkpoint=0,
                    low_watermark=0,
                    committed_mask=b"",
                )
            )
        elif isinstance(reconfig, ReconfigRemoveClient):
            found = False
            for i, client in enumerate(next_clients):
                if client.id == reconfig.id:
                    del next_clients[i]
                    found = True
                    break
            if not found:
                raise AssertionError(
                    f"asked to remove client {reconfig.id} which doesn't exist"
                )
        elif isinstance(reconfig, ReconfigTransferClient):
            next_clients.append(
                ClientState(
                    id=reconfig.id,
                    width=reconfig.width,
                    width_consumed_last_checkpoint=0,
                    low_watermark=reconfig.low_watermark,
                    committed_mask=b"",
                )
            )
        elif isinstance(reconfig, ReconfigNewConfig):
            next_config = reconfig.config

    return next_config, tuple(next_clients)


class CommitState:
    """Reference commitstate.go:24-38.  Network state only changes at
    checkpoint boundaries; ``stop_at_seq_no`` pauses ordering past the next
    checkpoint while a reconfiguration is pending."""

    __slots__ = (
        "persisted",
        "committing_clients",
        "logger",
        "low_watermark",
        "last_applied_commit",
        "highest_commit",
        "stop_at_seq_no",
        "active_state",
        "lower_half_commits",
        "upper_half_commits",
        "checkpoint_pending",
        "transferring",
        "transfer_retry_in",
        "transfer_retry_backoff",
        "transfer_retry_target",
    )

    def __init__(self, persisted: PersistedLog, logger=None):
        self.persisted = persisted
        self.logger = logger
        self.committing_clients: Dict[int, CommittingClient] = {}
        self.low_watermark = 0
        self.last_applied_commit = 0
        self.highest_commit = 0
        self.stop_at_seq_no = 0
        self.active_state: Optional[NetworkState] = None
        self.lower_half_commits: List[Optional[QEntry]] = []
        self.upper_half_commits: List[Optional[QEntry]] = []
        self.checkpoint_pending = False
        self.transferring = False
        # Failed-transfer retry machinery (closes the reference's open edge,
        # state_machine.go:210-212 ``panic("XXX handle state transfer
        # failure")``; docs/Divergences.md #8): a failed attempt re-issues
        # the ActionStateTransfer after a deterministic tick backoff
        # (1, 2, 4, 8, 8, ... ticks), giving the app time to select an
        # alternate snapshot source between attempts.
        self.transfer_retry_in = 0
        self.transfer_retry_backoff = 0
        self.transfer_retry_target: Optional[TEntry] = None

    # --- (re)initialization from the log (reference commitstate.go:52-112) ---

    def reinitialize(self) -> Actions:
        last_c: Optional[CEntry] = None
        last_t: Optional[TEntry] = None
        for _, entry in self.persisted.entries:
            if isinstance(entry, CEntry):
                last_c = entry
            elif isinstance(entry, TEntry):
                last_t = entry

        assert last_c is not None, "log must contain a CEntry"

        # The machine's _complete_pending_reconfiguration guarantees that a
        # CEntry applying a reconfiguration is followed by an FEntry before
        # we get here, and log recovery then truncates its predecessors — so
        # the newest CEntry is always the state to restart from.
        self.active_state = last_c.network_state
        self.low_watermark = last_c.seq_no

        actions = Actions().state_applied(self.low_watermark, self.active_state)

        ci = self.active_state.config.checkpoint_interval
        if not self.active_state.pending_reconfigurations:
            self.stop_at_seq_no = last_c.seq_no + 2 * ci
        else:
            # Mid-reconfiguration: ordering halts at the next checkpoint,
            # which is where the pending reconfiguration will apply.
            self.stop_at_seq_no = self.low_watermark + ci

        self.last_applied_commit = last_c.seq_no
        self.highest_commit = last_c.seq_no
        self.lower_half_commits = [None] * ci
        self.upper_half_commits = [None] * ci
        self.checkpoint_pending = False

        self.committing_clients = {
            cs.id: CommittingClient(self.low_watermark, cs)
            for cs in self.active_state.clients
        }

        self.transfer_retry_in = 0
        self.transfer_retry_backoff = 0
        self.transfer_retry_target = None

        if last_t is None or last_c.seq_no >= last_t.seq_no:
            self.transferring = False
            return actions

        # We crashed mid-state-transfer: re-issue the transfer request.
        self.transferring = True
        return actions.state_transfer(last_t.seq_no, last_t.value)

    def transfer_to(self, seq_no: int, value: bytes) -> Actions:
        """Persist a TEntry and request app state transfer
        (reference commitstate.go:114-123)."""
        if self.transferring:
            raise AssertionError("concurrent state transfers are not supported")
        self.transferring = True
        if self.logger is not None:
            self.logger.info("initiating state transfer", seq_no=seq_no)
        return self.persisted.add_t_entry(
            TEntry(seq_no=seq_no, value=value)
        ).state_transfer(seq_no, value)

    # --- failed-transfer retry (no reference counterpart; the reference
    # panics here, state_machine.go:210-212) ---

    def apply_transfer_failed(self, seq_no: int, value: bytes) -> Actions:
        """Schedule a retry of a failed state transfer.

        The TEntry for the attempt is already persisted (transfer_to), so a
        crash between failure and retry recovers through the normal
        crashed-mid-transfer path.  Retry waits ``transfer_retry_backoff``
        ticks (doubling per consecutive failure, capped at 8) before
        re-emitting the ActionStateTransfer.
        """
        if not self.transferring:
            # Stale failure from before a reinitialization (e.g. a crash
            # recovered the transfer and it already completed) — ignore.
            return EMPTY_ACTIONS
        self.transfer_retry_backoff = (
            1 if self.transfer_retry_backoff == 0
            else min(self.transfer_retry_backoff * 2, 8)
        )
        self.transfer_retry_in = self.transfer_retry_backoff
        self.transfer_retry_target = TEntry(seq_no=seq_no, value=value)
        if self.logger is not None:
            self.logger.warn(
                "state transfer failed; retrying",
                seq_no=seq_no,
                backoff_ticks=self.transfer_retry_backoff,
            )
        return EMPTY_ACTIONS

    def tick(self) -> Actions:
        """Count down a pending transfer retry; re-issue when it expires."""
        if self.transfer_retry_target is None:
            return EMPTY_ACTIONS
        self.transfer_retry_in -= 1
        if self.transfer_retry_in > 0:
            return EMPTY_ACTIONS
        target = self.transfer_retry_target
        self.transfer_retry_target = None
        if self.logger is not None:
            self.logger.info(
                "re-issuing failed state transfer", seq_no=target.seq_no
            )
        return Actions().state_transfer(target.seq_no, target.value)

    # --- checkpoint results (reference commitstate.go:125-165) ---

    def apply_checkpoint_result(self, result: EventCheckpointResult) -> Actions:
        ci = self.active_state.config.checkpoint_interval

        if self.transferring:
            return Actions()

        if result.seq_no != self.low_watermark + ci:
            raise AssertionError(
                f"stale checkpoint result seq={result.seq_no}, expected "
                f"{self.low_watermark + ci}"
            )

        completing_reconfiguration = bool(
            self.active_state.pending_reconfigurations
        )
        if (
            not result.network_state.pending_reconfigurations
            and not completing_reconfiguration
        ):
            self.stop_at_seq_no = result.seq_no + 2 * ci
        # else: a reconfiguration is pending (don't order past the next
        # checkpoint) or this checkpoint just applied one (the epoch ends
        # here; the machine reinitializes under the new config).

        self.active_state = result.network_state
        self.lower_half_commits = self.upper_half_commits
        self.upper_half_commits = [None] * ci
        self.low_watermark = result.seq_no
        self.checkpoint_pending = False

        return (
            self.persisted.add_c_entry(
                CEntry(
                    seq_no=result.seq_no,
                    checkpoint_value=result.value,
                    network_state=result.network_state,
                )
            )
            .send(
                self.active_state.config.nodes,
                CheckpointMsg(seq_no=result.seq_no, value=result.value),
            )
            .state_applied(result.seq_no, result.network_state)
        )

    # --- commits (reference commitstate.go:167-186) ---

    def commit(self, q_entry: QEntry) -> None:
        if self.transferring:
            raise AssertionError("must never commit during state transfer")
        if q_entry.seq_no > self.stop_at_seq_no:
            raise AssertionError(
                f"commit seq {q_entry.seq_no} exceeds stop {self.stop_at_seq_no}"
            )
        if q_entry.seq_no <= self.low_watermark:
            # During epoch change we may re-commit already-committed seqnos.
            return

        if self.highest_commit < q_entry.seq_no:
            if self.highest_commit + 1 != q_entry.seq_no:
                raise AssertionError(
                    f"out-of-order commit: highest={self.highest_commit}, "
                    f"got {q_entry.seq_no}"
                )
            self.highest_commit = q_entry.seq_no

        ci = self.active_state.config.checkpoint_interval
        commits, offset = self._slot(q_entry.seq_no, ci)
        existing = commits[offset]
        if existing is not None:
            if existing.digest != q_entry.digest:
                raise AssertionError(
                    f"conflicting commit digests at seq {q_entry.seq_no}"
                )
        else:
            commits[offset] = q_entry

    def _slot(self, seq_no: int, ci: int):
        """(half-list, offset) holding the pending QEntry slot for seq_no —
        the single source of the two-half window arithmetic
        (reference commitstate.go:24-38)."""
        upper = seq_no - self.low_watermark > ci
        offset = (seq_no - (self.low_watermark + 1)) % ci
        return (
            self.upper_half_commits if upper else self.lower_half_commits,
            offset,
        )

    def drain(self) -> Actions:
        """Emit all in-order Commit actions plus the Checkpoint action at the
        interval boundary (reference commitstate.go:228-269)."""
        ci = self.active_state.config.checkpoint_interval

        # Fast path for the per-event fixpoint loop: nothing commits and no
        # checkpoint is due — the overwhelmingly common case.
        lac = self.last_applied_commit
        if lac < self.low_watermark + 2 * ci and not (
            lac == self.low_watermark + ci and not self.checkpoint_pending
        ):
            next_commit = lac + 1
            commits, offset = self._slot(next_commit, ci)
            if commits[offset] is None:
                return EMPTY_ACTIONS

        actions = Actions()
        while self.last_applied_commit < self.low_watermark + 2 * ci:
            if (
                self.last_applied_commit == self.low_watermark + ci
                and not self.checkpoint_pending
            ):
                network_config, client_configs = next_network_config(
                    self.active_state, self.committing_clients
                )
                actions.checkpoint(
                    self.last_applied_commit, network_config, client_configs
                )
                self.checkpoint_pending = True

            next_commit = self.last_applied_commit + 1
            commits, offset = self._slot(next_commit, ci)
            commit = commits[offset]
            if commit is None:
                break
            if commit.seq_no != next_commit:
                raise AssertionError(
                    f"attempted out-of-order commit: {commit.seq_no} != "
                    f"{next_commit}"
                )
            actions.commit(commit)
            for req in commit.requests:
                self.committing_clients[req.client_id].mark_committed(
                    commit.seq_no, req.req_no
                )
            self.last_applied_commit = next_commit

        return actions
