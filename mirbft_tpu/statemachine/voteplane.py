"""Glue for the native sequence-vote plane (see _native/ackplane.cpp).

The three-phase commit's Prepare/Commit traffic is O(N²) per sequence
cluster-wide (reference ``pkg/statemachine/sequence.go:257-355``) and
dominates wall-clock at 64+ replicas.  The native ``SeqPlane`` owns vote
accumulation (replica bitmasks + per-digest counts) while the sequence
lifecycle stays in Python; transport envelopes pack their votes ONCE
(cached on the shared ``MsgBatch`` object) and every receiver applies the
whole envelope with a single native call.

Pure-Python mode (no toolchain, or ``MIRBFT_TPU_NATIVE=0``) keeps the dict
path in ``sequence.py``; differential tests assert both modes converge to
identical state.
"""

from __future__ import annotations

import weakref
from typing import Optional, Tuple

from .. import _native
from ..messages import Commit, MsgBatch, Prepare

if _native.available:
    _native.core.register_vote_types(Prepare, Commit)


def make_seq_plane(n_nodes: int, my_id: int, iq: int):
    """A fresh native vote plane, or None when running pure-Python."""
    if not _native.available or n_nodes > 4096:
        return None
    return _native.core.SeqPlane(n_nodes, my_id, iq)


# One packed-vote split per envelope object: the in-process transports hand
# every receiver the same MsgBatch, so N replicas share one packing pass.
# Keyed by id() — a WeakKeyDictionary would re-hash the whole envelope (the
# frozen dataclass __hash__ walks every contained message) on each lookup,
# costing what the shared pack saves.  The weakref guards id reuse and its
# callback evicts the entry when the envelope is collected.
_split_cache: dict = {}  # id(envelope) -> (weakref, (packed, votes, rest))


def split_votes(envelope: MsgBatch) -> Tuple[bytes, list, list]:
    """(packed_votes, vote_msgs, rest) for an envelope, cached per object."""
    # mirlint: allow(id-ordering) — identity memo key; the cache entry
    # pins the object and is is-checked before use, never ordered.
    key = id(envelope)
    entry = _split_cache.get(key)
    if entry is not None and entry[0]() is envelope:
        return entry[1]
    result = _native.core.pack_votes(envelope.msgs)

    def _evict(ref, key=key):
        live = _split_cache.get(key)
        if live is not None and live[0] is ref:
            del _split_cache[key]

    _split_cache[key] = (weakref.ref(envelope, _evict), result)
    return result
