"""Deterministic consensus state machine (L1).

Pure, single-threaded, non-blocking: consumes ``state.Event``s, emits
``state.Action``s.  No I/O, clocks, or threads — everything blocking or
compute-heavy (hashing on TPU, disk, network, app commit) is delegated to the
processor layer (L2).  Mirrors the capability surface of the reference's
``pkg/statemachine`` while being written Python/TPU-first.
"""
