"""Fluent builders for Action and Event batches.

Rebuild of the reference's ``ActionList``/``EventList``
(``pkg/statemachine/actions.go``, ``events.go``).  The reference uses linked
lists of protobuf oneofs; here a batch is a thin wrapper over a Python list of
the frozen dataclasses from ``mirbft_tpu.state``, with the same fluent
constructor surface so state-machine code reads the same way.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Tuple

from .. import state as s
from ..messages import (
    ClientState,
    Msg,
    NetworkConfig,
    NetworkState,
    Persistent,
    QEntry,
    RequestAck,
)


class Actions:
    """An ordered batch of actions emitted by the state machine."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[List[s.Action]] = None):
        self.items = items if items is not None else []


    # --- composition ---

    def concat(self, other: "Actions") -> "Actions":
        if other.items:
            self.items.extend(other.items)
        return self

    def push_back(self, action: s.Action) -> "Actions":
        self.items.append(action)
        return self

    def __iter__(self) -> Iterator[s.Action]:
        return iter(self.items)

    def __len__(self) -> int:
        return len(self.items)

    def __bool__(self) -> bool:
        return bool(self.items)

    def __repr__(self) -> str:
        return f"Actions({self.items!r})"

    # --- fluent constructors (reference actions.go) ---

    def send(self, targets: Iterable[int], msg: Msg) -> "Actions":
        self.items.append(s.ActionSend(targets=tuple(targets), msg=msg))
        return self

    def hash(self, data: Iterable[bytes], origin: s.HashOrigin) -> "Actions":
        self.items.append(s.ActionHashRequest(data=tuple(data), origin=origin))
        return self

    def persist(self, index: int, entry: Persistent) -> "Actions":
        self.items.append(s.ActionPersist(index=index, entry=entry))
        return self

    def truncate(self, index: int) -> "Actions":
        self.items.append(s.ActionTruncate(index=index))
        return self

    def commit(self, qentry: QEntry) -> "Actions":
        self.items.append(s.ActionCommit(batch=qentry))
        return self

    def checkpoint(
        self,
        seq_no: int,
        network_config: NetworkConfig,
        client_states: Tuple[ClientState, ...],
    ) -> "Actions":
        self.items.append(
            s.ActionCheckpoint(
                seq_no=seq_no,
                network_config=network_config,
                client_states=client_states,
            )
        )
        return self

    def allocate_request(self, client_id: int, req_no: int) -> "Actions":
        self.items.append(
            s.ActionAllocatedRequest(client_id=client_id, req_no=req_no)
        )
        return self

    def correct_request(self, ack: RequestAck) -> "Actions":
        self.items.append(s.ActionCorrectRequest(ack=ack))
        return self

    def forward_request(self, targets: Iterable[int], ack: RequestAck) -> "Actions":
        self.items.append(
            s.ActionForwardRequest(targets=tuple(targets), ack=ack)
        )
        return self

    def state_applied(self, seq_no: int, ns: NetworkState) -> "Actions":
        self.items.append(s.ActionStateApplied(seq_no=seq_no, network_state=ns))
        return self

    def state_transfer(self, seq_no: int, value: bytes) -> "Actions":
        self.items.append(s.ActionStateTransfer(seq_no=seq_no, value=value))
        return self


class Events:
    """An ordered batch of events to feed the state machine."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[List[s.Event]] = None):
        self.items = items if items is not None else []

    def concat(self, other: "Events") -> "Events":
        self.items.extend(other.items)
        return self

    def push_back(self, event: s.Event) -> "Events":
        self.items.append(event)
        return self

    def __iter__(self) -> Iterator[s.Event]:
        return iter(self.items)

    def __len__(self) -> int:
        return len(self.items)

    def __bool__(self) -> bool:
        return bool(self.items)

    def __repr__(self) -> str:
        return f"Events({self.items!r})"

    # --- fluent constructors (reference events.go) ---

    def initialize(self, params: s.EventInitialParameters) -> "Events":
        self.items.append(params)
        return self

    def load_persisted_entry(self, index: int, entry: Persistent) -> "Events":
        self.items.append(s.EventLoadPersistedEntry(index=index, entry=entry))
        return self

    def complete_initialization(self) -> "Events":
        self.items.append(s.EventLoadCompleted())
        return self

    def hash_result(self, digest: bytes, origin: s.HashOrigin) -> "Events":
        self.items.append(s.EventHashResult(digest=digest, origin=origin))
        return self

    def checkpoint_result(
        self,
        seq_no: int,
        value: bytes,
        network_state: NetworkState,
        reconfigured: bool = False,
    ) -> "Events":
        self.items.append(
            s.EventCheckpointResult(
                seq_no=seq_no,
                value=value,
                network_state=network_state,
                reconfigured=reconfigured,
            )
        )
        return self

    def request_persisted(self, ack: RequestAck) -> "Events":
        self.items.append(s.EventRequestPersisted(request_ack=ack))
        return self

    def state_transfer_complete(
        self, seq_no: int, checkpoint_value: bytes, network_state: NetworkState
    ) -> "Events":
        self.items.append(
            s.EventStateTransferComplete(
                seq_no=seq_no,
                checkpoint_value=checkpoint_value,
                network_state=network_state,
            )
        )
        return self

    def state_transfer_failed(self, seq_no: int, checkpoint_value: bytes) -> "Events":
        self.items.append(
            s.EventStateTransferFailed(
                seq_no=seq_no, checkpoint_value=checkpoint_value
            )
        )
        return self

    def step(self, source: int, msg: Msg) -> "Events":
        self.items.append(s.EventStep(source=source, msg=msg))
        return self

    def tick_elapsed(self) -> "Events":
        self.items.append(s.EventTickElapsed())
        return self

    def actions_received(self) -> "Events":
        self.items.append(s.EventActionsReceived())
        return self


class _FrozenActions(Actions):
    """Immutable empty ActionList, returned by hot no-op paths to avoid
    allocating a fresh list per call.  Mutators raise so an accidental
    in-place use is caught immediately (``concat(EMPTY_ACTIONS)`` onto a
    live list is fine — it only reads)."""

    __slots__ = ()

    def _frozen(self, *_args, **_kw):
        raise AssertionError("EMPTY_ACTIONS is immutable; allocate Actions()")

    concat = _frozen
    push_back = _frozen
    send = _frozen
    hash = _frozen
    persist = _frozen
    truncate = _frozen
    commit = _frozen
    checkpoint = _frozen
    allocate_request = _frozen
    correct_request = _frozen
    forward_request = _frozen
    state_applied = _frozen
    state_transfer = _frozen


EMPTY_ACTIONS = _FrozenActions()
