"""Active epoch: the normal-case ordering machinery.

Rebuild of reference ``pkg/statemachine/epoch_active.go``: the watermark
window of sequences in checkpoint-interval chunks, bucket→leader assignment
(:61-70), per-bucket in-order preprepare buffers (:88-97), the
past/current/future/invalid message filter (:142-213), the commit cascade
into ``CommitState`` (:296-317), window advancement allocating new intervals
+ NEntries and pulling proposals for owned buckets (:368-423), and the tick
handler driving the progress watchdog (→ Suspect) and heartbeat (null /
partial batches) (:438-490).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..messages import (
    Commit,
    EpochConfig,
    Msg,
    NEntry,
    NetworkConfig,
    Preprepare,
    Prepare,
    RequestAck,
    Suspect,
)
from ..state import EventInitialParameters
from .actions import EMPTY_ACTIONS, Actions
from .client_tracker import ClientTracker
from .commitstate import CommitState
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .outstanding import AllOutstandingReqs
from .persisted import PersistedLog
from .proposer import Proposer
from .sequence import SeqState, Sequence
from .stateless import intersection_quorum, seq_to_bucket
from .voteplane import make_seq_plane


class PreprepareBuffer:
    __slots__ = ("next_seq_no", "buffer")

    def __init__(self, next_seq_no: int, buffer: MsgBuffer):
        self.next_seq_no = next_seq_no
        self.buffer = buffer


def assign_buckets(
    epoch_config: EpochConfig, network_config: NetworkConfig
) -> Dict[int, int]:
    """Bucket→leader assignment, rotating with the epoch number; buckets whose
    natural leader is not in the leader set overflow round-robin onto actual
    leaders (reference epoch_active.go:53-70)."""
    leaders = set(epoch_config.leaders)
    buckets: Dict[int, int] = {}
    overflow_index = 0
    nodes = network_config.nodes
    for i in range(network_config.number_of_buckets):
        natural = nodes[(i + epoch_config.number) % len(nodes)]
        if natural in leaders:
            buckets[i] = natural
        else:
            buckets[i] = epoch_config.leaders[
                overflow_index % len(epoch_config.leaders)
            ]
            overflow_index += 1
    return buckets


class ActiveEpoch:
    """Reference epoch_active.go:22-121."""

    __slots__ = (
        "epoch_config",
        "network_config",
        "my_config",
        "logger",
        "outstanding_reqs",
        "proposer",
        "persisted",
        "commit_state",
        "buckets",
        "sequences",
        "preprepare_buffers",
        "other_buffers",
        "lowest_uncommitted",
        "lowest_unallocated",
        "last_committed_at_tick",
        "ticks_since_progress",
        "_nb",
        "_ci",
        "_owned_buckets",
        "_buffered",
        "_drain_memo",
        "seq_plane",
    )

    def __init__(
        self,
        epoch_config: EpochConfig,
        persisted: PersistedLog,
        node_buffers: NodeBuffers,
        commit_state: CommitState,
        client_tracker: ClientTracker,
        my_config: EventInitialParameters,
        logger=None,
    ):
        network_config = commit_state.active_state.config
        starting_seq_no = commit_state.highest_commit

        self.epoch_config = epoch_config
        self.network_config = network_config
        self.my_config = my_config
        # Per-buffer no-op-scan memo: (filter fingerprint, buffer version)
        # recorded when a drain scan applied nothing, so unchanged buffers
        # are not re-filtered every fixpoint iteration (observably pure —
        # a no-op iterate leaves buffer and state untouched).  Keys:
        # ("pp", bucket) for in-order preprepare buffers, node id for the
        # per-peer other-message buffers.
        self._drain_memo = {}
        self.logger = logger
        self.persisted = persisted
        self.commit_state = commit_state

        self.outstanding_reqs = AllOutstandingReqs(
            client_tracker.available_list, commit_state.active_state, logger
        )
        self.buckets = assign_buckets(epoch_config, network_config)

        num_buckets = len(self.buckets)
        self._nb = num_buckets
        self._ci = network_config.checkpoint_interval
        self._owned_buckets = [
            b for b in range(num_buckets) if self.buckets[b] == my_config.id
        ]
        # Shared live count of messages parked in this epoch's buffers, so the
        # per-event drain scan is O(1) when nothing is parked.
        self._buffered = [0]
        self.lowest_unallocated = [0] * num_buckets
        for i in range(num_buckets):
            first_seq_no = starting_seq_no + i + 1
            self.lowest_unallocated[
                seq_to_bucket(first_seq_no, network_config)
            ] = first_seq_no

        self.lowest_uncommitted = commit_state.highest_commit + 1

        self.proposer = Proposer(
            base_checkpoint=starting_seq_no,
            checkpoint_interval=network_config.checkpoint_interval,
            my_config=my_config,
            ready_list=client_tracker.ready_list,
            buckets=self.buckets,
            network_config=network_config,
        )

        self.preprepare_buffers = [
            PreprepareBuffer(
                next_seq_no=self.lowest_unallocated[i],
                buffer=MsgBuffer(
                    f"epoch-{epoch_config.number}-preprepare",
                    node_buffers.node_buffer(self.buckets[i]),
                    group=self._buffered,
                ),
            )
            for i in range(num_buckets)
        ]
        self.other_buffers = {
            node: MsgBuffer(
                f"epoch-{epoch_config.number}-other",
                node_buffers.node_buffer(node),
                group=self._buffered,
            )
            for node in network_config.nodes
        }

        # checkpoint-interval chunks of Sequence (window)
        self.sequences: List[List[Sequence]] = []
        self.last_committed_at_tick = 0
        self.ticks_since_progress = 0

        # Native vote plane for this epoch's window (None = pure-Python).
        # Mirrors the watermark window exactly; see voteplane.py.
        plane = make_seq_plane(
            len(network_config.nodes),
            my_config.id,
            intersection_quorum(network_config),
        )
        if plane is not None:
            import struct

            plane.reset(
                epoch_config.number,
                epoch_config.planned_expiration,
                struct.pack(
                    f"<{num_buckets}i",
                    *(self.buckets[i] for i in range(num_buckets)),
                ),
            )
        self.seq_plane = plane

    # --- window geometry ---

    def seq_to_bucket(self, seq_no: int) -> int:
        return seq_to_bucket(seq_no, self.network_config)

    def low_watermark(self) -> int:
        return self.sequences[0][0].seq_no

    def high_watermark(self) -> int:
        if not self.sequences:
            return self.commit_state.low_watermark
        return self.sequences[-1][-1].seq_no

    def in_watermarks(self, seq_no: int) -> bool:
        return self.low_watermark() <= seq_no <= self.high_watermark()

    def sequence(self, seq_no: int) -> Sequence:
        ci = self.network_config.checkpoint_interval
        index = (seq_no - self.low_watermark()) // ci
        offset = (seq_no - self.low_watermark()) % ci
        seq = self.sequences[index][offset]
        if seq.seq_no != seq_no:
            raise AssertionError("sequence retrieved had unexpected seq_no")
        return seq

    # --- message filtering (reference epoch_active.go:142-213) ---

    def filter(self, source: int, msg: Msg) -> Applyable:
        # NOTE: the Prepare/Commit arms are duplicated (fused with their
        # apply step) in _step_prepare/_step_commit for the live hot path;
        # any rule change here must be mirrored there.
        if isinstance(msg, Preprepare):
            seq_no = msg.seq_no
            bucket = self.seq_to_bucket(seq_no)
            if self.buckets[bucket] != source:
                return Applyable.INVALID
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
            if seq_no > self.high_watermark():
                return Applyable.FUTURE
            if seq_no < self.low_watermark():
                return Applyable.PAST
            next_preprepare = self.preprepare_buffers[bucket].next_seq_no
            if seq_no < next_preprepare:
                return Applyable.PAST
            if seq_no > next_preprepare:
                return Applyable.FUTURE
            return Applyable.CURRENT
        if isinstance(msg, Prepare):
            seq_no = msg.seq_no
            bucket = self.seq_to_bucket(seq_no)
            if self.buckets[bucket] == source:
                return Applyable.INVALID  # owners never send Prepare
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
            if seq_no < self.low_watermark():
                return Applyable.PAST
            if seq_no > self.high_watermark():
                return Applyable.FUTURE
            return Applyable.CURRENT
        if isinstance(msg, Commit):
            seq_no = msg.seq_no
            if seq_no > self.epoch_config.planned_expiration:
                return Applyable.INVALID
            if seq_no < self.low_watermark():
                return Applyable.PAST
            if seq_no > self.high_watermark():
                return Applyable.FUTURE
            return Applyable.CURRENT
        raise AssertionError(f"unexpected msg type {type(msg).__name__}")

    def apply(self, source: int, msg: Msg) -> Actions:
        """Reference epoch_active.go:215-241."""
        actions = Actions()
        if isinstance(msg, Preprepare):
            bucket = self.seq_to_bucket(msg.seq_no)
            buffer = self.preprepare_buffers[bucket]
            next_msg: Optional[Msg] = msg
            while next_msg is not None:
                own = self.sequence(next_msg.seq_no).owner == self.my_config.id
                before = self.lowest_unallocated[bucket]
                actions.concat(
                    self.apply_preprepare_msg(
                        source, next_msg.seq_no, list(next_msg.batch)
                    )
                )
                if not own and self.lowest_unallocated[bucket] == before:
                    # Rejected (leader demoted, apply_preprepare_msg): the
                    # slot is still unallocated, so the cursor must not move
                    # past it — a later valid Preprepare for this seq_no has
                    # to remain CURRENT, not trip the in-order guard.
                    break
                buffer.next_seq_no += len(self.buckets)
                next_msg = buffer.buffer.next(self.filter)
        elif isinstance(msg, Prepare):
            actions.concat(self.apply_prepare_msg(source, msg.seq_no, msg.digest))
        elif isinstance(msg, Commit):
            actions.concat(self.apply_commit_msg(source, msg.seq_no, msg.digest))
        else:
            raise AssertionError(f"unexpected msg type {type(msg).__name__}")
        return actions

    def step(self, source: int, msg: Msg) -> Actions:
        # Prepare/Commit are the cluster's two hottest message types (O(n)
        # per sequence per replica): fused filter+apply handlers below skip
        # the generic two-pass classification.
        t = msg.__class__
        if t is Prepare:
            return self._step_prepare(source, msg)
        if t is Commit:
            return self._step_commit(source, msg)
        verdict = self.filter(source, msg)
        if verdict == Applyable.CURRENT:
            return self.apply(source, msg)
        if verdict == Applyable.FUTURE:
            if isinstance(msg, Preprepare):
                bucket = self.seq_to_bucket(msg.seq_no)
                self.preprepare_buffers[bucket].buffer.store(msg)
            else:
                self.other_buffers[source].store(msg)
        # PAST / INVALID: drop
        return Actions()

    def _step_prepare(self, source: int, msg: Prepare) -> Actions:
        """filter()+apply() for a Prepare, in one pass (same verdicts)."""
        seq_no = msg.seq_no
        if self.buckets[seq_no % self._nb] == source:
            return Actions()  # INVALID: owners never send Prepare
        if seq_no > self.epoch_config.planned_expiration:
            return Actions()  # INVALID
        seqs = self.sequences
        low = seqs[0][0].seq_no
        if seq_no < low:
            return Actions()  # PAST
        if seq_no > seqs[-1][-1].seq_no:
            self.other_buffers[source].store(msg)  # FUTURE
            return Actions()
        offset = seq_no - low
        seq = seqs[offset // self._ci][offset % self._ci]
        return seq.apply_prepare_msg(source, msg.digest)

    def _step_commit(self, source: int, msg: Commit) -> Actions:
        """filter()+apply() for a Commit, in one pass (same verdicts),
        including the in-order commit cascade into CommitState."""
        seq_no = msg.seq_no
        if seq_no > self.epoch_config.planned_expiration:
            return Actions()  # INVALID
        seqs = self.sequences
        low = seqs[0][0].seq_no
        if seq_no < low:
            return Actions()  # PAST
        high = seqs[-1][-1].seq_no
        if seq_no > high:
            self.other_buffers[source].store(msg)  # FUTURE
            return Actions()
        offset = seq_no - low
        seq = seqs[offset // self._ci][offset % self._ci]
        seq.apply_commit_msg(source, msg.digest)
        if seq.state is not SeqState.COMMITTED or seq_no != self.lowest_uncommitted:
            return Actions()
        self._commit_cascade()
        return Actions()

    def apply_envelope_votes(
        self, packed: bytes, vote_msgs: List[Msg], source: int, step
    ) -> Actions:
        """Apply one transport envelope's Prepare/Commit votes through the
        native plane in a single call, then run the returned records in vote
        order: fallbacks re-enter the generic ``step`` with the original
        message (future buffering, other epochs), hints run the transition
        checks the per-message path would have run — which re-validate every
        quorum condition against the plane's live counts, so hints are safe
        to be liberal."""
        actions = Actions()
        records = self.seq_plane.apply_votes(packed, source)
        for rec in records:
            if len(rec) == 1:
                actions.concat(step(source, vote_msgs[rec[0]]))
                continue
            kind, seq_no = rec
            seq = self.sequence(seq_no)
            if kind == 0:
                # Mirrors apply_prepare_msg's state arms.
                s = seq.state
                if (
                    s is SeqState.PREPREPARED
                    or s is SeqState.READY
                    or s is SeqState.PENDING_REQUESTS
                ):
                    actions.concat(seq.advance_state())
            else:
                seq._check_commit_quorum()
            if (
                seq.state is SeqState.COMMITTED
                and seq.seq_no == self.lowest_uncommitted
            ):
                self._commit_cascade()
        return actions

    def _commit_cascade(self) -> None:
        """Feed consecutive committed sequences into CommitState, in order."""
        seqs = self.sequences
        low = seqs[0][0].seq_no
        high = seqs[-1][-1].seq_no
        ci = self._ci
        lowest = self.lowest_uncommitted
        commit = self.commit_state.commit
        while lowest <= high:
            offset = lowest - low
            seq = seqs[offset // ci][offset % ci]
            if seq.state is not SeqState.COMMITTED:
                break
            commit(seq.q_entry)
            lowest += 1
        self.lowest_uncommitted = lowest

    # --- three-phase message application ---

    def apply_preprepare_msg(
        self, source: int, seq_no: int, batch: List[RequestAck]
    ) -> Actions:
        """Reference epoch_active.go:247-271."""
        seq = self.sequence(seq_no)

        if seq.owner == self.my_config.id:
            # Already allocated at proposal time; the loopback Preprepare is
            # our own prepare-equivalent.
            return seq.apply_prepare_msg(source, seq.digest)

        bucket = self.seq_to_bucket(seq_no)
        if seq_no != self.lowest_unallocated[bucket]:
            raise AssertionError(
                "step should defer all but the next expected preprepare"
            )

        # Validates in-order request consumption and allocates the sequence.
        # ValueError means a protocol-invalid batch (unknown client,
        # out-of-order req_no) from the bucket's leader: the reference
        # panics here with a "TODO to suspect instead" — this emits the
        # Suspect.  apply_acks is validate-then-apply, so the rejected
        # batch left no partial state; the sequence stays unallocated and
        # the view change demotes the leader instead of the crash demoting
        # this node.
        try:
            actions = self.outstanding_reqs.apply_acks(bucket, seq, batch)
        except ValueError as err:
            suspect = Suspect(epoch=self.epoch_config.number)
            actions = Actions()
            actions.send(self.network_config.nodes, suspect)
            actions.concat(self.persisted.add_suspect(suspect))
            if self.logger is not None:
                self.logger.warn(
                    "suspecting epoch: protocol-invalid preprepare from leader",
                    epoch=self.epoch_config.number,
                    leader=source,
                    seq_no=seq_no,
                    error=str(err),
                )
            return actions
        self.lowest_unallocated[bucket] += len(self.buckets)
        return actions

    def apply_prepare_msg(self, source: int, seq_no: int, digest: bytes) -> Actions:
        return self.sequence(seq_no).apply_prepare_msg(source, digest)

    def apply_commit_msg(self, source: int, seq_no: int, digest: bytes) -> Actions:
        """Commit plus in-order cascade into CommitState
        (reference epoch_active.go:296-317)."""
        seq = self.sequence(seq_no)
        seq.apply_commit_msg(source, digest)
        if seq.state != SeqState.COMMITTED or seq_no != self.lowest_uncommitted:
            return Actions()
        self._commit_cascade()
        return Actions()

    def apply_batch_hash_result(self, seq_no: int, digest: bytes) -> Actions:
        """Route a TPU-computed batch digest to its sequence
        (reference epoch_active.go:425-436)."""
        if not self.in_watermarks(seq_no):
            return Actions()  # benign during/after state transfer
        return self.sequence(seq_no).apply_batch_hash_result(digest)

    # --- watermark movement / window advance ---

    def move_low_watermark(self, seq_no: int) -> Tuple[Actions, bool]:
        """Returns (actions, epoch_done) (reference epoch_active.go:319-337)."""
        if seq_no == self.epoch_config.planned_expiration:
            return Actions(), True
        if seq_no == self.commit_state.stop_at_seq_no:
            return Actions(), True

        actions = self.advance()
        while seq_no > self.low_watermark():
            self.sequences = self.sequences[1:]
        if self.seq_plane is not None and self.sequences:
            self.seq_plane.set_window(self.low_watermark(), self.high_watermark())
        return actions, False

    def _drain_fp(self):
        """Everything ``filter`` verdicts depend on (watermarks + per-bucket
        in-order cursors; bucket map and expiration are epoch-static)."""
        return (
            self.low_watermark(),
            self.high_watermark(),
            tuple(b.next_seq_no for b in self.preprepare_buffers),
        )

    def drain_buffers(self) -> Actions:
        """Reference epoch_active.go:339-366."""
        actions = Actions()
        if not self._buffered[0]:
            return actions  # nothing parked anywhere in this epoch
        memo = self._drain_memo
        fp = self._drain_fp()
        for bucket in range(len(self.buckets)):
            buffer = self.preprepare_buffers[bucket]
            if not buffer.buffer:
                continue
            key = ("pp", bucket)
            if memo.get(key) == (fp, buffer.buffer.version):
                continue  # provably the same all-FUTURE scan as last time
            source = self.buckets[bucket]
            next_msg = buffer.buffer.next(self.filter)
            if next_msg is None:
                memo[key] = (fp, buffer.buffer.version)
                continue
            # apply() loops over consecutive preprepares internally
            actions.concat(self.apply(source, next_msg))
            fp = self._drain_fp()  # cursors/watermarks may have moved

        for node in self.network_config.nodes:
            other = self.other_buffers[node]
            if not other.buffer:
                continue
            if memo.get(node) == (fp, other.version):
                continue
            hit = [False]

            def apply_msg(nid, msg, _hit=hit):
                _hit[0] = True
                actions.concat(self.apply(nid, msg))

            other.iterate(self.filter, apply_msg)
            if hit[0]:
                fp = self._drain_fp()
            else:
                memo[node] = (fp, other.version)
        return actions

    def needs_advance(self) -> bool:
        """Cheap predicate for the per-event fixpoint: advance() is a no-op
        unless the window can extend, buffered messages may drain, or new
        ready proposals can be pulled/allocated.  Mirrors exactly the
        conditions under which advance() emits actions or mutates state."""
        hw = self.high_watermark()
        if (
            hw < self.epoch_config.planned_expiration
            and hw < self.commit_state.stop_at_seq_no
        ):
            return True  # window extension pending
        if self._buffered[0]:
            return True  # buffered consensus msgs may now apply
        proposer = self.proposer
        if proposer.ready_iterator.has_next():
            return True  # new strong-cert requests to pull
        for bucket in self._owned_buckets:
            seq_no = self.lowest_unallocated[bucket]
            if seq_no <= hw and proposer.proposal_bucket(bucket).has_pending(
                seq_no
            ):
                return True
        return False

    def advance(self) -> Actions:
        """Extend the window with new checkpoint intervals (persisting an
        NEntry per chunk), drain buffers, pull proposals into owned buckets
        (reference epoch_active.go:368-423)."""
        actions = Actions()
        if self.high_watermark() > self.epoch_config.planned_expiration:
            raise AssertionError("window extends beyond planned expiration")
        if self.high_watermark() > self.commit_state.stop_at_seq_no:
            raise AssertionError("window extends beyond the stop sequence")

        ci = self.network_config.checkpoint_interval
        while (
            self.high_watermark() < self.epoch_config.planned_expiration
            and self.high_watermark() < self.commit_state.stop_at_seq_no
        ):
            base = self.high_watermark() + 1
            actions.concat(
                self.persisted.add_n_entry(
                    NEntry(seq_no=base, epoch_config=self.epoch_config)
                )
            )
            chunk = [
                Sequence(
                    owner=self.buckets[self.seq_to_bucket(base + i)],
                    epoch=self.epoch_config.number,
                    seq_no=base + i,
                    persisted=self.persisted,
                    network_config=self.network_config,
                    my_id=self.my_config.id,
                    plane=self.seq_plane,
                )
                for i in range(ci)
            ]
            self.sequences.append(chunk)

        if self.seq_plane is not None and self.sequences:
            self.seq_plane.set_window(self.low_watermark(), self.high_watermark())

        actions.concat(self.drain_buffers())

        self.proposer.advance(self.lowest_uncommitted)

        for bucket in self._owned_buckets:
            prb = self.proposer.proposal_bucket(bucket)
            while True:
                seq_no = self.lowest_unallocated[bucket]
                if seq_no > self.high_watermark():
                    break
                if not prb.has_pending(seq_no):
                    break
                seq = self.sequence(seq_no)
                actions.concat(seq.allocate_as_owner(prb.next()))
                self.lowest_unallocated[bucket] += len(self.buckets)
        return actions

    # --- ticks (reference epoch_active.go:438-490) ---

    def tick(self) -> Actions:
        if self.last_committed_at_tick < self.commit_state.highest_commit:
            self.last_committed_at_tick = self.commit_state.highest_commit
            self.ticks_since_progress = 0
            return Actions()

        self.ticks_since_progress += 1
        actions = Actions()

        if self.ticks_since_progress > self.my_config.suspect_ticks:
            suspect = Suspect(epoch=self.epoch_config.number)
            actions.send(self.network_config.nodes, suspect)
            actions.concat(self.persisted.add_suspect(suspect))
            if self.logger is not None:
                self.logger.warn(
                    "suspecting epoch: no progress",
                    epoch=self.epoch_config.number,
                    ticks_since_progress=self.ticks_since_progress,
                )

        if (
            self.my_config.heartbeat_ticks == 0
            or self.ticks_since_progress % self.my_config.heartbeat_ticks != 0
        ):
            return actions

        # Heartbeat: cut a partial (possibly null) batch in every owned bucket.
        for bucket in self._owned_buckets:
            unallocated_seq_no = self.lowest_unallocated[bucket]
            if unallocated_seq_no > self.high_watermark():
                continue
            seq = self.sequence(unallocated_seq_no)
            prb = self.proposer.proposal_bucket(bucket)
            client_reqs = []
            if prb.has_outstanding(unallocated_seq_no):
                client_reqs = prb.next()
            actions.concat(seq.allocate_as_owner(client_reqs))
            self.lowest_unallocated[bucket] += len(self.buckets)
        return actions
