"""Client request ACK/dissemination protocol (consensus side).

Rebuild of reference ``pkg/statemachine/client_hash_disseminator.go`` — the
library's request-dissemination departure from the Mir paper
(``docs/Clients.md`` "Client ACKs"): per client × req_no, accumulate
``RequestAck``s; a weak quorum (f+1) marks a request *correct*
(→ CorrectRequest action), a strong quorum (2f+1) marks it *ready to
propose*; conflicting correct requests from a byzantine client are resolved
by promoting the null request; un-replicated correct requests are proactively
fetched with timeouts; own acks are rebroadcast with linear backoff
(reference :507-629).

Hardening vs the reference: a replica's first non-null ack per req_no is
binding — later non-null acks for different digests from the same replica are
ignored (the reference documents this rule at :106-112 but does not enforce it
on the hot ack path, see its ``filter`` TODO at :194).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from .. import _native
from ..messages import (
    AckBatch,
    AckMsg,
    ClientState,
    FetchRequest,
    Msg,
    NetworkConfig,
    NetworkState,
    RequestAck,
)
from ..state import EventInitialParameters
from .actions import EMPTY_ACTIONS, Actions
from .client_tracker import ClientTracker
from .msgbuffers import Applyable, MsgBuffer, NodeBuffers
from .stateless import intersection_quorum, is_committed, some_correct_quorum

CORRECT_FETCH_TICKS = 4
FETCH_TIMEOUT_TICKS = 4
ACK_RESEND_TICKS = 20

# Packed-ack cache for the native ack plane: one AckBatch is delivered to
# every replica (N times in an in-process testengine run), but its packed
# (client_id, digest_id, req_no) representation is identical everywhere, so
# it is computed once per batch object.  Keyed by id() with an identity check
# (the stored strong reference keeps a live entry's id stable; an evicted
# entry whose id gets reused fails the identity check and is recomputed).
_PACK_CACHE: "OrderedDict[int, Tuple[object, bytes]]" = OrderedDict()
_PACK_CAP = 8192


def _packed_acks(batch) -> bytes:
    # mirlint: allow(id-ordering) — identity memo key; the cache entry
    # pins the object and is is-checked before use, never ordered.
    key = id(batch)
    entry = _PACK_CACHE.get(key)
    if entry is not None and entry[0] is batch:
        return entry[1]
    packed = _native.core.pack_acks(batch.acks)
    _PACK_CACHE[key] = (batch, packed)
    if len(_PACK_CACHE) > _PACK_CAP:
        _PACK_CACHE.popitem(last=False)
    return packed


def mask_to_nodes(mask: int) -> Tuple[int, ...]:
    """Replica-id bitmask -> ascending id tuple."""
    return tuple(i for i in range(mask.bit_length()) if (mask >> i) & 1)


class ClientRequest:
    """One (client, req_no, digest) candidate (reference :631-668)."""

    __slots__ = (
        "ack",
        "agreements",
        "stored",
        "fetching",
        "ticks_fetching",
        "ticks_correct",
        "refresh_ref",
    )

    def __init__(self, ack: RequestAck):
        self.ack = ack
        self.agreements = 0  # bitmask of replica ids that acked this digest
        self.stored = False  # persisted locally
        self.fetching = False
        self.ticks_fetching = 0
        self.ticks_correct = 0
        # (plane, client_id, req_no) when the native ack plane is
        # accumulating votes for this (canonical-digest) request; consulted
        # by refresh() at the few sites that read a live agreement mask.
        self.refresh_ref = None

    def refresh(self) -> int:
        """Merge any native-plane votes into ``agreements`` and return it."""
        ref = self.refresh_ref
        if ref is not None:
            plane, client_id, req_no = ref
            state = plane.peek(client_id, req_no)
            if state is None:
                self.refresh_ref = None  # ejected or out of window
            else:
                self.agreements |= int.from_bytes(state[1], "little")
        return self.agreements

    def fetch(self) -> Actions:
        if self.fetching:
            return Actions()
        self.fetching = True
        self.ticks_fetching = 0
        return Actions().send(
            mask_to_nodes(self.refresh()), FetchRequest(ack=self.ack)
        )


class ClientReqNo:
    """Ack accumulation for one (client, req_no) (reference :339-629)."""

    __slots__ = (
        "my_config",
        "network_config",
        "client_id",
        "req_no",
        "valid_after_seq_no",
        "non_null_voters",
        "requests",
        "weak_requests",
        "strong_requests",
        "my_requests",
        "committed",
        "acks_sent",
        "acked_digest",
        "resend_nonce",
    )

    def __init__(
        self,
        my_config: EventInitialParameters,
        client_id: int,
        req_no: int,
        network_config: NetworkConfig,
        valid_after_seq_no: int,
    ):
        self.my_config = my_config
        self.client_id = client_id
        self.req_no = req_no
        self.network_config = network_config
        self.valid_after_seq_no = valid_after_seq_no
        self.non_null_voters = 0  # bitmask of replicas that voted non-null
        self.requests: Dict[bytes, ClientRequest] = {}  # all observed
        self.weak_requests: Dict[bytes, ClientRequest] = {}  # correct
        self.strong_requests: Dict[bytes, ClientRequest] = {}  # proposable
        self.my_requests: Dict[bytes, ClientRequest] = {}  # locally persisted
        self.committed = False
        self.acks_sent = 0
        self.acked_digest: Optional[bytes] = None  # digest our ack endorsed
        self.resend_nonce = 0  # invalidates stale resend-schedule entries

    def reinitialize(
        self, network_config: NetworkConfig, same_config: Optional[bool] = None
    ) -> None:
        """Re-derive quorum sets under a (possibly changed) config
        (reference :371-408).  ``same_config`` lets the caller hoist the
        config comparison out of the per-slot loop."""
        if same_config is None:
            same_config = network_config == self.network_config
        if same_config:
            # Graceful epoch rotation under an unchanged config: the same
            # node set and quorum thresholds re-derive the same agreement
            # masks and weak/strong/my sets, so the rebuild below is an
            # identity on them.  Only the per-candidate fetch state resets
            # (the rebuild drops it by constructing fresh ClientRequests).
            for req in self.requests.values():
                req.fetching = False
                req.ticks_fetching = 0
                req.ticks_correct = 0
            return
        self.network_config = network_config
        old_requests = self.requests
        self.non_null_voters = 0
        self.requests = {}
        self.weak_requests = {}
        self.strong_requests = {}
        self.my_requests = {}

        for digest in sorted(old_requests):
            old_req = old_requests[digest]
            for node in network_config.nodes:
                if (old_req.agreements >> node) & 1:
                    self._apply_request_ack(node, old_req.ack)
            if old_req.stored:
                new_req = self.client_req(old_req.ack)
                new_req.stored = True
                self.my_requests[digest] = new_req

    def client_req(self, ack: RequestAck) -> ClientRequest:
        digest_key = ack.digest  # null request → b""
        req = self.requests.get(digest_key)
        if req is None:
            req = ClientRequest(ack)
            self.requests[digest_key] = req
        return req

    def apply_new_request(self, ack: RequestAck) -> None:
        """A request body was persisted locally (reference :431-443)."""
        if ack.digest in self.my_requests:
            return  # race between a forward and a local proposal
        req = self.client_req(ack)
        req.stored = True
        self.my_requests[ack.digest] = req

    def generate_ack(self) -> Optional[Msg]:
        """Reference :445-479."""
        if not self.my_requests:
            return None
        if len(self.my_requests) == 1:
            self.acks_sent = 1
            (req,) = self.my_requests.values()
            self.acked_digest = req.ack.digest
            return AckMsg(ack=req.ack)

        # Multiple locally-known requests: ack the null request.
        null_ack = RequestAck(client_id=self.client_id, req_no=self.req_no, digest=b"")
        null_req = self.client_req(null_ack)
        null_req.stored = True
        self.my_requests[b""] = null_req
        self.acks_sent = 1
        self.acked_digest = b""
        return AckMsg(ack=null_ack)

    def _apply_request_ack(self, source: int, ack: RequestAck) -> None:
        """Quorum bookkeeping used during reinitialize (reference :481-505)."""
        if ack.digest:
            self.non_null_voters |= 1 << source
        req = self.client_req(ack)
        req.agreements |= 1 << source
        count = req.agreements.bit_count()
        if count < some_correct_quorum(self.network_config):
            return
        self.weak_requests[ack.digest] = req
        if count < intersection_quorum(self.network_config):
            return
        self.strong_requests[ack.digest] = req

    def needs_attention(self) -> bool:
        """Whether the per-tick scan (attention_tick) has work or counters to
        advance for this req-no.  Mirrors exactly the conditions under which
        the reference's per-req-no tick body (reference :507-629) mutates
        state: a pending null promotion, a proactive-fetch countdown, or an
        in-flight fetch timing out.  Ack-rebroadcast backoff is NOT included —
        it is handled by the client's resend schedule."""
        wr = self.weak_requests
        if not wr:
            return False
        if len(wr) == 1:
            (req,) = wr.values()
            if req.fetching:
                return True  # fetch-timeout counting
            return not req.stored  # counting down to a proactive fetch
        if b"" not in self.my_requests:
            return True  # null promotion pending
        for req in wr.values():
            if req.fetching:
                return True  # fetch-timeout counting
        return False

    def attention_tick(self, actions: Actions) -> bool:
        """Null-promotion, proactive fetch, and fetch retry — the per-tick
        body of reference :507-614, minus ack rebroadcast (scheduled by the
        owning Client).  Returns True when a null promotion fired (the client
        must then schedule the promoted ack's first rebroadcast)."""
        promoted = False

        # 1. Conflicting correct requests and no null yet -> promote null.
        if b"" not in self.my_requests and len(self.weak_requests) > 1:
            null_ack = RequestAck(
                client_id=self.client_id, req_no=self.req_no, digest=b""
            )
            null_req = self.client_req(null_ack)
            null_req.stored = True
            self.my_requests[b""] = null_req
            self.acks_sent = 1
            self.acked_digest = b""
            promoted = True
            actions.send(self.network_config.nodes, AckMsg(ack=null_ack)).correct_request(
                null_ack
            )

        # 2. Exactly one correct request we don't hold -> proactively fetch.
        if len(self.weak_requests) == 1:
            (req,) = self.weak_requests.values()
            if not req.stored and not req.fetching:
                if req.ticks_correct <= CORRECT_FETCH_TICKS:
                    req.ticks_correct += 1
                else:
                    actions.concat(req.fetch())

        # 3. Fetches that timed out -> retry (deterministic digest order).
        to_fetch: Optional[List[ClientRequest]] = None
        for req in self.weak_requests.values():
            if not req.fetching:
                continue
            if req.ticks_fetching <= FETCH_TIMEOUT_TICKS:
                req.ticks_fetching += 1
                continue
            req.fetching = False
            if to_fetch is None:
                to_fetch = []
            to_fetch.append(req)
        if to_fetch is not None:
            to_fetch.sort(key=lambda r: r.ack.digest, reverse=True)
            for req in to_fetch:
                actions.concat(req.fetch())

        return promoted


class Client:
    """Watermark window of ClientReqNos for one client (reference :670-904)."""

    __slots__ = (
        "my_config",
        "logger",
        "network_config",
        "client_state",
        "client_tracker",
        "high_watermark",
        "next_ready_mark",
        "next_ack_mark",
        "req_nos",
        "tick_count",
        "attention",
        "resend_schedule",
        "resend_seq",
        "weak_quorum",
        "strong_quorum",
    )

    def __init__(self, my_config: EventInitialParameters, tracker: ClientTracker, logger=None):
        self.my_config = my_config
        self.logger = logger
        self.client_tracker = tracker
        self.network_config: Optional[NetworkConfig] = None
        self.client_state: Optional[ClientState] = None
        self.high_watermark = 0
        self.next_ready_mark = 0
        self.next_ack_mark = 0
        self.req_nos: Dict[int, ClientReqNo] = {}  # insertion-ordered window
        # Tick machinery: instead of scanning every in-window req-no each
        # tick (O(window) per tick per client, reference :507-629), req-nos
        # that have per-tick work register in `attention`, and ack
        # rebroadcasts are scheduled by absolute tick number with a per-crn
        # nonce guarding stale entries.  Observable behavior (which tick a
        # given action fires on) is identical to the reference's counters.
        self.tick_count = 0
        self.attention: Set[int] = set()
        self.resend_schedule: Dict[int, List[Tuple[int, int]]] = {}
        # Nonces are unique across the client's lifetime so a schedule entry
        # left by a dropped ClientReqNo can never match a later incarnation
        # of the same req_no.
        self.resend_seq = 0
        self.weak_quorum = 0  # f+1, cached at (re)initialization
        self.strong_quorum = 0  # (n+f+2)//2, cached at (re)initialization

    def reinitialize(
        self,
        seq_no: int,
        network_config: NetworkConfig,
        client_state: ClientState,
        reconfiguring: bool,
    ) -> Actions:
        """Reference :692-743."""
        actions = Actions()
        self.weak_quorum = some_correct_quorum(network_config)
        self.strong_quorum = intersection_quorum(network_config)
        old_req_nos = self.req_nos
        old_config = self.network_config

        # Window is exactly `width` slots, [lw, lw+width-1]; the portion
        # usable before the next checkpoint excludes what the previous
        # checkpoint consumed.  (The reference exposes one extra slot here —
        # see stateless.is_committed docstring.)
        intermediate_high = (
            client_state.low_watermark
            + client_state.width
            - client_state.width_consumed_last_checkpoint
            - 1
        )
        self.network_config = network_config
        self.client_state = client_state
        self.high_watermark = (
            client_state.low_watermark + client_state.width - 1
            if not reconfiguring
            else intermediate_high
        )
        self.next_ready_mark = client_state.low_watermark
        if self.next_ack_mark < client_state.low_watermark:
            self.next_ack_mark = client_state.low_watermark
        self.req_nos = {}

        same_config = network_config == old_config
        for req_no in range(client_state.low_watermark, self.high_watermark + 1):
            crn = old_req_nos.get(req_no)
            if crn is None:
                valid_after = (
                    seq_no + network_config.checkpoint_interval
                    if req_no > intermediate_high
                    else seq_no
                )
                crn = ClientReqNo(
                    self.my_config,
                    client_state.id,
                    req_no,
                    network_config,
                    valid_after,
                )
                actions.allocate_request(client_state.id, req_no)
            crn.committed = is_committed(req_no, client_state)
            crn.reinitialize(network_config, same_config)
            self.req_nos[req_no] = crn

        self.attention = {
            rn
            for rn, crn in self.req_nos.items()
            if not crn.committed and crn.needs_attention()
        }
        self.advance_ready()
        return actions

    def allocate(
        self, seq_no: int, state: ClientState, reconfiguring: bool
    ) -> Actions:
        """Roll the window forward after a checkpoint (reference :745-804)."""
        actions = Actions()
        intermediate_high = (
            state.low_watermark
            + state.width
            - state.width_consumed_last_checkpoint
            - 1
        )
        if intermediate_high != self.high_watermark:
            raise AssertionError(
                "new intermediate high watermark must equal the old high "
                f"watermark for client {state.id}"
            )
        new_high = (
            state.low_watermark + state.width - 1
            if not reconfiguring
            else intermediate_high
        )

        if state.low_watermark > self.next_ready_mark:
            # A request we never saw as ready may have committed as correct.
            self.next_ready_mark = state.low_watermark
        if state.low_watermark > self.next_ack_mark:
            self.next_ack_mark = state.low_watermark

        for req_no in list(self.req_nos):
            if req_no == state.low_watermark:
                break
            del self.req_nos[req_no]

        for req_no in range(state.low_watermark, self.high_watermark + 1):
            if is_committed(req_no, state):
                self.req_nos[req_no].committed = True

        self.client_state = state

        valid_after = seq_no + self.network_config.checkpoint_interval
        for req_no in range(intermediate_high + 1, new_high + 1):
            actions.allocate_request(state.id, req_no)
            self.req_nos[req_no] = ClientReqNo(
                self.my_config, state.id, req_no, self.network_config, valid_after
            )

        self.high_watermark = new_high
        self.advance_ready()
        return actions

    def ack(self, source: int, ack: RequestAck, force: bool = False) -> Tuple[Actions, ClientRequest]:
        actions = Actions()
        cr = self.ack_into(actions, source, ack, force=force)
        return actions, cr

    def ack_into(
        self, actions: Actions, source: int, ack: RequestAck, force: bool = False
    ) -> ClientRequest:
        """Record a replica's ack; drive correct/available/ready transitions
        (reference :806-840).  Appends into the caller's accumulator — this
        is the per-ack hot loop (O(N^2) calls per request across the
        cluster), so per-call allocations are kept off it."""
        crn = self.req_nos.get(ack.req_no)
        if crn is None:
            raise AssertionError(
                f"client {ack.client_id} ack for req_no {ack.req_no} outside "
                f"watermarks [{self.client_state.low_watermark}, "
                f"{self.high_watermark}]"
            )

        # First-non-null-ack-is-binding rule (see module docstring): a replica
        # that already voted for a different non-null digest is ignored unless
        # the digest is known-correct (force).
        bit = 1 << source
        if ack.digest and not force:
            existing = crn.requests.get(ack.digest)
            already_voted_this = existing is not None and existing.agreements & bit
            if crn.non_null_voters & bit and not already_voted_this:
                return crn.client_req(ack)

        if ack.digest:
            crn.non_null_voters |= bit

        cr = crn.client_req(ack)
        cr.agreements |= bit
        agreement_count = cr.agreements.bit_count()

        newly_correct = agreement_count == self.weak_quorum
        if newly_correct:
            crn.weak_requests[ack.digest] = cr
            if not cr.stored:
                actions.correct_request(ack)
            # Attention membership only changes when the weak set changes
            # (stored/fetching/my_requests are not touched on this path).
            self._update_attention(crn)

        if cr.stored and (
            newly_correct
            or (agreement_count >= self.weak_quorum and source == self.my_config.id)
        ):
            self.client_tracker.add_available(ack)

        if agreement_count == self.strong_quorum:
            crn.strong_requests[ack.digest] = cr
            self.advance_ready()

        return cr

    def ack_run(
        self, actions: Actions, source: int, acks: List[RequestAck], start: int
    ) -> int:
        """Apply a run of in-window acks from one source for this client,
        beginning at ``acks[start]``; returns the index after the run.

        Semantically a loop of ack_into; the common case (non-null digest,
        source not previously bound elsewhere, no quorum crossing) is inlined
        with hoisted locals because at N replicas this loop runs O(N²) times
        per request cluster-wide."""
        req_nos = self.req_nos
        bit = 1 << source
        weak_q = self.weak_quorum
        strong_q = self.strong_quorum
        low = self.client_state.low_watermark
        high = self.high_watermark
        client_id = acks[start].client_id
        n = len(acks)
        i = start
        while i < n:
            ack = acks[i]
            if ack.client_id != client_id:
                break
            req_no = ack.req_no
            if req_no < low or req_no > high:
                break
            i += 1
            digest = ack.digest
            crn = req_nos.get(req_no)
            if digest and crn.non_null_voters & bit:
                existing = crn.requests.get(digest)
                if existing is None:
                    # Bound to a different digest: vote ignored, but the
                    # candidate is still registered (as in ack_into).
                    crn.requests[digest] = ClientRequest(ack)
                    continue
                if not existing.agreements & bit:
                    continue  # bound to a different digest: ignored
                cr = existing
            else:
                if digest:
                    crn.non_null_voters |= bit
                cr = crn.requests.get(digest)
                if cr is None:
                    cr = ClientRequest(ack)
                    crn.requests[digest] = cr
            votes = cr.agreements | bit
            cr.agreements = votes
            count = votes.bit_count()
            if count < weak_q:
                continue
            # Quorum-relevant tail: rare, shared with ack_into's logic.
            newly_correct = count == weak_q
            if newly_correct:
                crn.weak_requests[digest] = cr
                if not cr.stored:
                    actions.correct_request(ack)
                self._update_attention(crn)
            if cr.stored and (newly_correct or source == self.my_config.id):
                self.client_tracker.add_available(ack)
            if count == strong_q:
                crn.strong_requests[digest] = cr
                self.advance_ready()
        return i

    def in_watermarks(self, req_no: int) -> bool:
        return self.client_state.low_watermark <= req_no <= self.high_watermark

    def req_no(self, req_no: int) -> ClientReqNo:
        crn = self.req_nos.get(req_no)
        if crn is None:
            raise AssertionError(
                f"client {self.client_state.id} should have req_no {req_no}"
            )
        return crn

    def advance_ready(self) -> None:
        """Reference :852-876."""
        for i in range(self.next_ready_mark, self.high_watermark + 1):
            if i != self.next_ready_mark:
                return  # previous iteration failed to advance
            crn = self.req_no(i)
            if crn.committed:
                self.next_ready_mark = i + 1
                continue
            for digest in crn.strong_requests:
                if digest not in crn.my_requests:
                    continue
                self.client_tracker.add_ready(crn)
                self.next_ready_mark = i + 1
                break

    def advance_acks(self) -> List[RequestAck]:
        """Reference :878-895 — returns the freshly generated acks instead
        of broadcasting them: the disseminator's flush_acks merges acks
        across ALL dirty clients into one AckBatch per event batch (the
        reference broadcasts one AckMsg per ack; one batch per client was
        the first aggregation step, cross-client coalescing the second)."""
        acks: List[RequestAck] = []
        for i in range(self.next_ack_mark, self.high_watermark + 1):
            crn = self.req_no(i)
            ack_msg = crn.generate_ack()
            if ack_msg is None:
                break
            acks.append(ack_msg.ack)
            # First rebroadcast is due after ACK_RESEND_TICKS full ticks have
            # elapsed, firing on the tick after (reference backoff counter
            # semantics, :614-629).
            self._schedule_resend(crn, self.tick_count + ACK_RESEND_TICKS + 1)
            self._update_attention(crn)
            self.next_ack_mark = i + 1
        return acks

    def _update_attention(self, crn: ClientReqNo) -> None:
        if not crn.committed and crn.needs_attention():
            self.attention.add(crn.req_no)
        else:
            self.attention.discard(crn.req_no)

    def _schedule_resend(self, crn: ClientReqNo, due_tick: int) -> None:
        self.resend_seq += 1
        crn.resend_nonce = self.resend_seq
        self.resend_schedule.setdefault(due_tick, []).append(
            (crn.req_no, crn.resend_nonce)
        )

    def apply_new_request(self, ack: RequestAck) -> None:
        crn = self.req_no(ack.req_no)
        crn.apply_new_request(ack)
        self._update_attention(crn)

    def note_fetching(self, ack: RequestAck) -> None:
        """A fetch was initiated outside the tick path (epoch-change request
        recovery): make sure its timeout counting is attended to."""
        crn = self.req_nos.get(ack.req_no)
        if crn is not None:
            self._update_attention(crn)

    def tick(self, actions: Actions) -> None:
        self.tick_count += 1

        if self.attention:
            for rn in sorted(self.attention):
                crn = self.req_nos.get(rn)
                if crn is None or crn.committed:
                    self.attention.discard(rn)
                    continue
                if crn.attention_tick(actions):
                    # Null promotion counts its first backoff window from
                    # this very tick (the reference increments the fresh
                    # counter in the same tick body, :614-617).
                    self._schedule_resend(
                        crn, self.tick_count + ACK_RESEND_TICKS
                    )
                self._update_attention(crn)

        resend: List[RequestAck] = []
        due = self.resend_schedule.pop(self.tick_count, None)
        if due:
            for rn, nonce in due:
                crn = self.req_nos.get(rn)
                if crn is None or crn.committed or crn.resend_nonce != nonce:
                    continue
                req = crn.my_requests.get(crn.acked_digest)
                if req is None:
                    raise AssertionError(
                        "sent an ack for a request we do not have"
                    )
                ack = req.ack
                crn.acks_sent += 1
                resend.append(ack)
                self._schedule_resend(
                    crn,
                    self.tick_count + crn.acks_sent * ACK_RESEND_TICKS + 1,
                )
        if len(resend) == 1:
            actions.send(self.network_config.nodes, AckMsg(ack=resend[0]))
        elif resend:
            actions.send(self.network_config.nodes, AckBatch(acks=tuple(resend)))


class ClientHashDisseminator:
    """Reference :121-321."""

    __slots__ = (
        "logger",
        "my_config",
        "node_buffers",
        "allocated_through",
        "network_config",
        "client_states",
        "msg_buffers",
        "clients",
        "client_tracker",
        "plane",
        "_mask_bytes",
        "_ack_dirty",
        "coalesce_acks",
    )

    def __init__(
        self,
        node_buffers: NodeBuffers,
        my_config: EventInitialParameters,
        client_tracker: ClientTracker,
        logger=None,
    ):
        self.logger = logger
        self.my_config = my_config
        self.node_buffers = node_buffers
        self.client_tracker = client_tracker
        self.allocated_through = 0
        self.network_config: Optional[NetworkConfig] = None
        self.client_states: Tuple[ClientState, ...] = ()
        self.msg_buffers: Dict[int, MsgBuffer] = {}
        self.clients: Dict[int, Client] = {}
        # Native ack-vote plane (mirbft_tpu/_native): owns green-path vote
        # accumulation; None when the extension is unavailable/disabled.
        self.plane = None
        self._mask_bytes = 0
        # Clients with persisted-but-not-yet-acked requests; drained by
        # flush_acks() at each event-batch boundary (EventActionsReceived),
        # which coalesces every dirty client's acks into one AckBatch per
        # processing batch instead of one broadcast per persisted request
        # (or per client).  False restores the per-client shape for the
        # differential test.
        self._ack_dirty: Set[int] = set()
        self.coalesce_acks = True

    def reinitialize(self, seq_no: int, network_state: NetworkState) -> Actions:
        """Reference :143-180."""
        actions = Actions()
        reconfiguring = bool(network_state.pending_reconfigurations)

        # Unchanged config + client set (the graceful epoch-rotation case):
        # the per-req-no rebuild is an identity on vote state, so the native
        # plane keeps ownership and only the windows are re-based.  Otherwise
        # fold the native votes back into Python before the rebuild
        # re-derives quorum sets from them, and build a fresh plane after.
        keep_plane = (
            self.plane is not None
            and self.network_config == network_state.config
            and tuple(cs.id for cs in self.client_states)
            == tuple(cs.id for cs in network_state.clients)
        )
        if not keep_plane:
            # Fold any native-plane vote state back into the Python objects
            # before the Python-side rebuild re-derives quorum sets from them.
            self._sync_all_from_plane()

        self.allocated_through = seq_no
        self.network_config = network_state.config

        old_clients = self.clients
        self.clients = {}
        self.client_states = network_state.clients
        for client_state in self.client_states:
            client = old_clients.get(client_state.id)
            if client is None:
                client = Client(self.my_config, self.client_tracker, self.logger)
            self.clients[client_state.id] = client
            actions.concat(
                client.reinitialize(
                    seq_no, network_state.config, client_state, reconfiguring
                )
            )

        old_msg_buffers = self.msg_buffers
        self.msg_buffers = {}
        for node in network_state.config.nodes:
            buffer = old_msg_buffers.get(node)
            if buffer is None:
                buffer = MsgBuffer("clients", self.node_buffers.node_buffer(node))
            self.msg_buffers[node] = buffer

        if keep_plane:
            plane = self.plane
            for client_state in self.client_states:
                client = self.clients[client_state.id]
                plane.set_client(
                    client_state.id,
                    client.client_state.low_watermark,
                    client.high_watermark,
                )
        else:
            self._rebuild_plane()
        return actions

    # --- native ack plane lifecycle -------------------------------------

    def _sync_all_from_plane(self) -> None:
        """Merge every live native slot's votes into the Python objects
        (without marking anything ejected — used before a full rebuild,
        which discards the plane anyway)."""
        plane = self.plane
        if plane is None:
            return
        for client_id, client in self.clients.items():
            for req_no, digest_id, mask_b, _count in plane.export_client(
                client_id
            ):
                crn = client.req_nos.get(req_no)
                if crn is None:
                    continue
                self._merge_state(client_id, crn, digest_id, mask_b, None)
        self.plane = None

    def _merge_state(
        self, client_id: int, crn: ClientReqNo, digest_id: int, mask_b: bytes,
        refresh_ref,
    ) -> "ClientRequest":
        digest = _native.core.digest_bytes(digest_id)
        ack = RequestAck(client_id=client_id, req_no=crn.req_no, digest=digest)
        cr = crn.client_req(ack)
        mask = int.from_bytes(mask_b, "little")
        cr.agreements |= mask
        cr.refresh_ref = refresh_ref
        crn.non_null_voters |= mask
        return cr

    def _rebuild_plane(self) -> None:
        """Create a fresh plane for the (possibly changed) config and
        re-import every green-path slot (single non-null digest candidate,
        no null candidate); everything else is marked ejected and handled
        by the pure-Python path."""
        if not _native.available:
            self.plane = None
            return
        config = self.network_config
        n_nodes = max(config.nodes) + 1
        plane = _native.core.AckPlane(
            n_nodes,
            self.my_config.id,
            some_correct_quorum(config),
            intersection_quorum(config),
        )
        self._mask_bytes = ((n_nodes + 63) // 64) * 8
        for client_state in self.client_states:
            client_id = client_state.id
            client = self.clients[client_id]
            plane.set_client(
                client_id, client.client_state.low_watermark, client.high_watermark
            )
            for req_no, crn in client.req_nos.items():
                if b"" in crn.requests:
                    plane.mark_ejected(client_id, req_no)
                    continue
                non_null = [(d, r) for d, r in crn.requests.items() if d]
                if len(non_null) > 1:
                    plane.mark_ejected(client_id, req_no)
                    continue
                if not non_null:
                    continue  # untouched slot: native starts fresh
                digest, cr = non_null[0]
                if plane.import_slot(
                    client_id,
                    req_no,
                    digest,
                    cr.agreements.to_bytes(self._mask_bytes, "little"),
                    cr.agreements.bit_count(),
                ):
                    cr.refresh_ref = (plane, client_id, req_no)
                else:  # digest not internable (table at capacity)
                    plane.mark_ejected(client_id, req_no)
        self.plane = plane

    def _eject_reqno(self, client: "Client", req_no: int) -> None:
        """Hand a (client, req_no) back to the pure-Python path: merge the
        native votes into the Python objects and mark the slot ejected so
        every later ack for it falls through to Python."""
        state = self.plane.eject(client.client_state.id, req_no)
        if state is None:
            return
        digest_id, mask_b, _count = state
        crn = client.req_nos.get(req_no)
        if crn is not None and digest_id >= 0:
            cr = self._merge_state(
                client.client_state.id, crn, digest_id, mask_b, None
            )
            cr.refresh_ref = None

    def _peek_merge(self, client: "Client", crn: ClientReqNo) -> None:
        """Snapshot-merge native votes into Python (read-only sites:
        fetch replies, status introspection); the plane stays the owner."""
        plane = self.plane
        if plane is None:
            return
        client_id = client.client_state.id
        state = plane.peek(client_id, crn.req_no)
        if state is None:
            return
        digest_id, mask_b, _count = state
        self._merge_state(
            client_id, crn, digest_id, mask_b, (plane, client_id, crn.req_no)
        )

    def sync_for_introspection(self) -> None:
        """Make Python-side vote state current for status()/debugging."""
        if self.plane is None:
            return
        for client in self.clients.values():
            for crn in client.req_nos.values():
                self._peek_merge(client, crn)

    def _pyfall_ack(self, actions: Actions, source: int, ack: RequestAck) -> None:
        """Classification + application for an ack the native plane refused
        (unknown client, out of window, null digest, conflicting digest, or
        ejected slot) — mirrors the legacy AckBatch classification."""
        client = self.clients.get(ack.client_id)
        if client is None:
            self.msg_buffers[source].store(AckMsg(ack=ack))  # FUTURE
            return
        if client.client_state.low_watermark > ack.req_no:
            return  # PAST
        if client.high_watermark < ack.req_no:
            self.msg_buffers[source].store(AckMsg(ack=ack))  # FUTURE
            return
        self._eject_reqno(client, ack.req_no)
        client.ack_into(actions, source, ack)

    def _native_crossing(
        self,
        actions: Actions,
        source: int,
        client: "Client",
        req_no: int,
        digest_id: int,
        count: int,
        mask_b: bytes,
    ) -> None:
        """Replay of the quorum tail of Client.ack_into/ack_run for a
        crossing detected natively.  The conditions and action order are
        exactly the Python path's; the native plane guarantees records are
        emitted precisely when count == weak_q, count == strong_q, or
        source == my_id with count >= weak_q (duplicates included — a
        duplicate vote arriving while the count sits at a threshold re-runs
        the tail in the reference semantics too)."""
        crn = client.req_nos[req_no]
        digest = _native.core.digest_bytes(digest_id)
        cr = crn.requests.get(digest)
        if cr is None:
            cr = ClientRequest(
                RequestAck(
                    client_id=client.client_state.id,
                    req_no=req_no,
                    digest=digest,
                )
            )
            crn.requests[digest] = cr
        mask = int.from_bytes(mask_b, "little")
        cr.agreements |= mask
        crn.non_null_voters |= mask
        if cr.refresh_ref is None:
            cr.refresh_ref = (self.plane, client.client_state.id, req_no)
        # cr.ack is value-identical to the received ack (same client/req_no,
        # and cr is keyed by the canonical digest).
        ack = cr.ack
        newly_correct = count == client.weak_quorum
        if newly_correct:
            crn.weak_requests[digest] = cr
            if not cr.stored:
                actions.correct_request(ack)
            # Inlined _update_attention: with exactly one weak candidate and
            # no null candidate (guaranteed on a native-owned slot),
            # needs_attention reduces to (not stored) or fetching.
            if not crn.committed and (not cr.stored or cr.fetching):
                client.attention.add(req_no)
            else:
                client.attention.discard(req_no)
        if cr.stored and (newly_correct or source == self.my_config.id):
            client.client_tracker.add_available(ack)
        if count == client.strong_quorum:
            crn.strong_requests[digest] = cr
            client.advance_ready()

    def tick(self) -> Actions:
        actions = Actions()
        for client_state in self.client_states:
            self.clients[client_state.id].tick(actions)
        return actions

    def filter(self, _source: int, msg: Msg) -> Applyable:
        """Reference :191-213."""
        if isinstance(msg, AckMsg):
            ack = msg.ack
            client = self.clients.get(ack.client_id)
            if client is None:
                return Applyable.FUTURE
            if client.client_state.low_watermark > ack.req_no:
                return Applyable.PAST
            if client.high_watermark < ack.req_no:
                return Applyable.FUTURE
            return Applyable.CURRENT
        if isinstance(msg, FetchRequest):
            return Applyable.CURRENT
        raise AssertionError(f"unexpected client message type {type(msg).__name__}")

    def step(self, source: int, msg: Msg) -> Actions:
        if isinstance(msg, AckBatch):
            plane = self.plane
            if plane is not None:
                # Native fast path: the whole batch is applied in C against
                # packed vote bitmasks; only quorum crossings and acks the
                # plane refuses come back, in original ack order, and are
                # replayed through the exact Python semantics.
                actions = Actions()
                acks = msg.acks
                for rec in plane.apply_batch(_packed_acks(msg), source):
                    if len(rec) == 1:
                        self._pyfall_ack(actions, source, acks[rec[0]])
                    else:
                        _idx, cid, req_no, did, count, mask_b = rec
                        self._native_crossing(
                            actions, source, self.clients[cid], req_no,
                            did, count, mask_b,
                        )
                return actions
            # Per-ack classification: a batch may straddle a window boundary.
            # PAST acks are dropped, FUTURE acks are buffered individually
            # (so later buffer iteration applies them one by one, exactly as
            # if they had arrived as single AckMsgs), CURRENT acks apply now.
            # Classification is inlined (same logic as filter's AckMsg arm):
            # this is the cluster's hottest message path.
            actions = Actions()
            clients = self.clients
            acks = msg.acks
            n = len(acks)
            i = 0
            while i < n:
                ack = acks[i]
                client = clients.get(ack.client_id)
                if client is None:
                    self.msg_buffers[source].store(AckMsg(ack=ack))  # FUTURE
                    i += 1
                    continue
                req_no = ack.req_no
                if client.client_state.low_watermark > req_no:
                    i += 1
                    continue  # PAST
                if client.high_watermark < req_no:
                    self.msg_buffers[source].store(AckMsg(ack=ack))  # FUTURE
                    i += 1
                    continue
                # In-window: hand the whole same-client in-window run to the
                # client's inlined loop.
                i = client.ack_run(actions, source, acks, i)
            return actions
        if isinstance(msg, AckMsg) and self.plane is not None:
            ack = msg.ack
            result = self.plane.apply_one(
                ack.client_id, ack.req_no, ack.digest, source
            )
            actions = Actions()
            if type(result) is tuple:
                count, did, mask_b = result
                self._native_crossing(
                    actions, source, self.clients[ack.client_id], ack.req_no,
                    did, count, mask_b,
                )
            elif result == 1:  # plane refused: classify + apply in Python
                self._pyfall_ack(actions, source, ack)
            # result 0 (applied, no crossing) / 2 (past, dropped): no actions
            return actions
        verdict = self.filter(source, msg)
        if verdict == Applyable.PAST:
            return Actions()
        if verdict == Applyable.FUTURE:
            self.msg_buffers[source].store(msg)
            return Actions()
        return self.apply_msg(source, msg)

    def apply_msg(self, source: int, msg: Msg) -> Actions:
        if isinstance(msg, AckMsg):
            actions, _ = self.ack(source, msg.ack)
            return actions
        if isinstance(msg, FetchRequest):
            ack = msg.ack
            return self.reply_fetch_request(
                source, ack.client_id, ack.req_no, ack.digest
            )
        raise AssertionError(f"unexpected client message type {type(msg).__name__}")

    def apply_new_request(self, ack: RequestAck) -> Actions:
        """EventRequestPersisted: our processor persisted a request body
        (reference :242-257).  Ack generation is deferred to flush_acks()
        at the event-batch boundary so acks for all requests persisted in
        one batch broadcast as one AckBatch per client."""
        client = self.clients.get(ack.client_id)
        if client is None:
            return Actions()  # client removed since the request was processed
        if not client.in_watermarks(ack.req_no):
            return Actions()  # already committed
        client.apply_new_request(ack)
        self._ack_dirty.add(ack.client_id)
        return Actions()

    def flush_acks(self) -> Actions:
        """Generate deferred ack broadcasts (deterministic client order).

        All dirty clients' fresh acks coalesce into ONE AckBatch per flush
        — one broadcast per event batch instead of one per client.  The
        receive side classifies per ack (step's AckBatch arm), so
        cross-client batches need no special handling there.  Setting
        ``coalesce_acks=False`` restores the one-batch-per-client shape
        (the differential test pins the two to identical client state)."""
        if not self._ack_dirty:
            return Actions()
        actions = Actions()
        merged: List[RequestAck] = []
        for client_id in sorted(self._ack_dirty):
            client = self.clients.get(client_id)
            if client is None:
                continue
            acks = client.advance_acks()
            if self.coalesce_acks:
                merged.extend(acks)
            elif acks:
                self._send_acks(actions, acks)
        self._ack_dirty.clear()
        if merged:
            self._send_acks(actions, merged)
        return actions

    def _send_acks(self, actions: Actions, acks: List[RequestAck]) -> None:
        if len(acks) == 1:
            actions.send(self.network_config.nodes, AckMsg(ack=acks[0]))
        else:
            actions.send(self.network_config.nodes, AckBatch(acks=tuple(acks)))

    def allocate(self, seq_no: int, network_state: NetworkState) -> Actions:
        """Advance client windows after a checkpoint (reference :260-278)."""
        if seq_no != network_state.config.checkpoint_interval + self.allocated_through:
            raise AssertionError(
                "unexpected skip in allocate; expected next allocation at "
                "next checkpoint"
            )
        actions = Actions()
        self.allocated_through = seq_no
        reconfiguring = bool(network_state.pending_reconfigurations)
        plane = self.plane
        for client_state in network_state.clients:
            client = self.clients[client_state.id]
            actions.concat(client.allocate(seq_no, client_state, reconfiguring))
            if plane is not None:
                # Roll the native window with the Python one: the overlap
                # keeps its votes, dropped slots are GC'd, new slots empty.
                plane.set_client(
                    client_state.id,
                    client_state.low_watermark,
                    client.high_watermark,
                )
        for node in self.network_config.nodes:
            self.msg_buffers[node].iterate(
                self.filter,
                lambda source, msg: actions.concat(self.apply_msg(source, msg)),
            )
        return actions

    def reply_fetch_request(
        self, source: int, client_id: int, req_no: int, digest: bytes
    ) -> Actions:
        """Reference :280-308."""
        client = self.clients.get(client_id)
        if client is None or not client.in_watermarks(req_no):
            return Actions()
        crn = client.req_no(req_no)
        self._peek_merge(client, crn)
        data = crn.requests.get(digest)
        if data is None or not (data.agreements >> self.my_config.id) & 1:
            return Actions()
        return Actions().forward_request(
            (source,),
            RequestAck(client_id=client_id, req_no=req_no, digest=digest),
        )

    def ack(self, source: int, ack: RequestAck, force: bool = False) -> Tuple[Actions, ClientRequest]:
        client = self.clients.get(ack.client_id)
        if client is None:
            raise AssertionError(
                "step filtering should delay reqs for non-existent clients"
            )
        if self.plane is not None:
            # Direct/forced acks (buffer replay, epoch-change request
            # recovery) use the full Python semantics: hand the slot back.
            self._eject_reqno(client, ack.req_no)
        return client.ack(source, ack, force=force)

    def note_fetching(self, ack: RequestAck) -> None:
        """See Client.note_fetching."""
        client = self.clients.get(ack.client_id)
        if client is not None:
            client.note_fetching(ack)

    def client(self, client_id: int) -> Optional[Client]:
        return self.clients.get(client_id)
