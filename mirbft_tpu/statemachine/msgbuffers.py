"""Byte-bounded per-node message buffering.

Rebuild of reference ``pkg/statemachine/msgbuffers.go``: each peer node gets a
byte-budgeted ``NodeBuffer`` (EventInitialParameters.buffer_size) shared by all
per-component ``MsgBuffer``s; when over capacity the storing buffer drops its
own oldest messages first (:145-164).  Classification of buffered messages is
4-way: PAST (drop), CURRENT (apply), FUTURE (keep), INVALID (drop).
"""

from __future__ import annotations

import enum
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from .. import wire
from ..messages import Msg
from ..state import EventInitialParameters


class Applyable(enum.IntEnum):
    PAST = 0
    CURRENT = 1
    FUTURE = 2
    INVALID = 3


FilterFn = Callable[[int, Msg], Applyable]
ApplyFn = Callable[[int, Msg], None]


def msg_size(msg: Msg) -> int:
    """Wire size of a message; the unit of buffer accounting (the reference
    uses proto.Size)."""
    return len(wire.encode(msg))


class NodeBuffers:
    """Registry of per-peer buffers (reference msgbuffers.go:20-44)."""

    __slots__ = ("my_config", "logger", "node_map")

    def __init__(self, my_config: EventInitialParameters, logger=None):
        self.my_config = my_config
        self.logger = logger
        self.node_map: Dict[int, "NodeBuffer"] = {}

    def node_buffer(self, source: int) -> "NodeBuffer":
        nb = self.node_map.get(source)
        if nb is None:
            nb = NodeBuffer(source, self.my_config, self.logger)
            self.node_map[source] = nb
        return nb


class NodeBuffer:
    """Aggregate byte budget for one peer (reference msgbuffers.go:64-77)."""

    __slots__ = ("id", "my_config", "logger", "total_size", "msg_bufs")

    def __init__(self, node_id: int, my_config: EventInitialParameters, logger=None):
        self.id = node_id
        self.my_config = my_config
        self.logger = logger
        self.total_size = 0
        self.msg_bufs: List["MsgBuffer"] = []  # for status reporting only

    def over_capacity(self) -> bool:
        return self.total_size > self.my_config.buffer_size

    def _msg_stored(self, size: int) -> None:
        self.total_size += size

    def _msg_removed(self, size: int) -> None:
        self.total_size -= size


class MsgBuffer:
    """One component's buffer of not-yet-applyable messages from one peer
    (reference msgbuffers.go:121-226)."""

    __slots__ = ("component", "buffer", "node_buffer", "group", "version")

    def __init__(self, component: str, node_buffer: NodeBuffer, group=None):
        self.component = component
        # deque of (msg, cached wire size)
        self.buffer: Deque[Tuple[Msg, int]] = deque()
        self.node_buffer = node_buffer
        # Optional shared one-element counter cell: the owner's live message
        # count across a group of buffers (lets it skip drain scans cheaply).
        self.group = group
        # Monotone store counter: lets drain loops skip a re-scan when
        # neither the buffer nor the filter-relevant state has changed
        # since a scan that applied and dropped nothing (a no-op iterate
        # is observably pure, so skipping it preserves bit-identity).
        self.version = 0

    def store(self, msg: Msg) -> None:
        self.version += 1
        # Over budget: drop our own oldest first (see reference's fairness
        # note, msgbuffers.go:146-151).
        while self.node_buffer.over_capacity() and self.buffer:
            old_msg, old_size = self.buffer.popleft()
            if self.group is not None:
                self.group[0] -= 1
            self.node_buffer._msg_removed(old_size)
            self._deregister_if_empty()
            if self.node_buffer.logger is not None:
                self.node_buffer.logger.warn(
                    "dropping buffered msg",
                    component=self.component,
                    type=type(old_msg).__name__,
                )
        size = msg_size(msg)
        if not self.buffer:
            self.node_buffer.msg_bufs.append(self)
        self.buffer.append((msg, size))
        if self.group is not None:
            self.group[0] += 1
        self.node_buffer._msg_stored(size)

    def _deregister_if_empty(self) -> None:
        if not self.buffer:
            try:
                self.node_buffer.msg_bufs.remove(self)
            except ValueError:
                pass

    # next/iterate compact the deque in ONE pass instead of deleting from
    # the middle per removed entry: ``del deque[i]`` is O(n), which turned
    # big-buffer drains (cascading view changes buffer enormous message
    # piles) into O(n^2) wall time.  Kept entries preserve their relative
    # order and apply_fn-appended entries are still visited, so behavior is
    # identical to the delete-based loop.

    def next(self, filter_fn: FilterFn) -> Optional[Msg]:
        """Pop the first CURRENT message, dropping PAST/INVALID along the way;
        FUTURE messages are skipped in place (reference msgbuffers.go:178-204).

        Rotation pass (deque indexing is O(n)): scanned FUTURE entries are
        re-appended and, once the CURRENT message is found, the deque is
        rotated back so order is preserved — a front-resident CURRENT entry
        costs O(1), keeping consecutive-drain loops linear."""
        buf = self.buffer
        kept = 0
        scanned = 0
        total = len(buf)
        found = None
        while scanned < total:
            scanned += 1
            entry = buf.popleft()
            msg, size = entry
            verdict = filter_fn(self.node_buffer.id, msg)
            if verdict == Applyable.FUTURE:
                buf.append(entry)
                kept += 1
                continue
            if self.group is not None:
                self.group[0] -= 1
            self.node_buffer._msg_removed(size)
            if verdict == Applyable.CURRENT:
                found = msg
                break
        if kept:
            buf.rotate(kept)
        self._deregister_if_empty()
        return found

    def iterate(self, filter_fn: FilterFn, apply_fn: ApplyFn) -> None:
        """Apply every CURRENT message, dropping PAST/INVALID, keeping FUTURE
        (reference msgbuffers.go:206-226).

        Single pass draining the deque; kept (FUTURE) entries collect into
        a side list restored at the end, so entries stored by apply_fn
        during the pass are drained and visited too — matching the C++
        twin's compaction loop, which re-reads buffer.size().  Kept
        originals precede kept apply_fn-appended entries, as in C++."""
        buf = self.buffer
        kept = []
        while buf:
            msg, size = buf.popleft()
            verdict = filter_fn(self.node_buffer.id, msg)
            if verdict == Applyable.FUTURE:
                kept.append((msg, size))
                continue
            if self.group is not None:
                self.group[0] -= 1
            self.node_buffer._msg_removed(size)
            if verdict == Applyable.CURRENT:
                apply_fn(self.node_buffer.id, msg)
        buf.extend(kept)
        self._deregister_if_empty()

    def __len__(self) -> int:
        return len(self.buffer)
