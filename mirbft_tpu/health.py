"""Protocol health plane: anomaly detection over snapshots and events.

The reference has no health subsystem — its observability ends at the
status snapshot and the replayable event log.  PR 1 added *measurement*
(metrics, spans, Prometheus); this layer adds *judgment*: detectors that
watch those signals and say what is wrong, which peer caused it, and when
it started.  Mir's deterministic event/action architecture makes every
liveness or safety anomaly mechanically detectable from state the tree
already exposes, so the monitor is a pure consumer — it never touches the
state machine, only ``status.snapshot()`` views and the event stream.

Detector suite (thresholds in :class:`HealthThresholds`, documented in
docs/OBSERVABILITY.md):

- **watermark_stall** — commit progress (watermark movement, client-window
  movement, and commits observed on the event stream, null batches
  included) stops for N consecutive observations while work is pending
  (allocated-uncommitted client requests, live suspicions, or undecided
  checkpoints).
- **epoch_thrash** — repeated view changes without an intervening commit
  (the cascade shape of BASELINE config 4, flagged as it happens).
- **checkpoint_stagnation** — a checkpoint this node decided locally that
  cannot reach a network quorum.
- **client_starvation** — one client's window stops advancing while it
  still holds allocated-uncommitted requests.
- **msg_buffer_growth** — monotonic message-buffer growth above a floor
  (a backpressure leak: something buffers faster than it drains).
- **peer_fault** — the per-peer fault ledger: ingress rejections, invalid
  digests, and suspicion votes attributed to the offending node id
  (suspicions attribute to the suspected epoch's primary,
  ``epoch % num_nodes`` — epoch_tracker.py:288).
- **checkpoint_divergence** — :class:`DivergenceDetector`, the testengine
  safety tripwire: cross-replica checkpoint fingerprints compared each
  interval; any same-seq mismatch flags the minority replica(s).

Every detection emits one structured :class:`Anomaly` through three
channels: the logger (``warn``), the tracer (an ``anomaly`` instant
event), and the metrics registry (``anomalies_total{kind}``,
``peer_faults_total{peer,kind}``, ``health_status``).  Consumers:
``Node.health()`` (runtime scrape surface), the testengine recorder
(``Recorder.health``), ``bench.py`` (BENCH_HEALTH.json), and
``mircat --doctor`` (offline analysis of any recorded event log).
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass, field, fields
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as metrics_mod
from . import state as st
from . import tracing
from .messages import CEntry, Suspect

# Anomaly kinds (the `kind` label of anomalies_total; linted snake_case +
# documented by tools/check_metric_names.py).
ANOMALY_KINDS = (
    "watermark_stall",
    "epoch_thrash",
    "checkpoint_stagnation",
    "client_starvation",
    "msg_buffer_growth",
    "peer_fault",
    "checkpoint_divergence",
)

# Per-peer fault kinds (the `kind` label of peer_faults_total; same lint).
FAULT_KINDS = (
    "ingress_reject",
    "invalid_digest",
    "suspicion_vote",
    "peer_unreachable",
)


@dataclass(frozen=True)
class Anomaly:
    """One detected protocol anomaly (JSON-ready via ``as_dict``)."""

    kind: str  # one of ANOMALY_KINDS
    node_id: int  # the node observing (or, for divergence, deviating)
    time: float  # clock value at detection (sim units or seconds)
    since: float  # clock value when the condition started
    peer: Optional[int] = None  # offending node id, when attributable
    detail: Dict[str, object] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, object]:
        return asdict(self)

    def describe(self) -> str:
        peer = f" peer={self.peer}" if self.peer is not None else ""
        extra = "".join(f" {k}={v}" for k, v in sorted(self.detail.items()))
        return (
            f"[{self.kind}] node={self.node_id}{peer} "
            f"since={self.since:g} at={self.time:g}{extra}"
        )


@dataclass
class HealthThresholds:
    """Detector thresholds, in consecutive *observations* (one observation
    per health tick / snapshot interval).  Defaults are sized so clean runs
    never trip them (the false-positive guard in tests/test_health.py) but
    a silenced-leader partition does within its suspect window."""

    # Observations with no commit progress AND pending work.
    stall_observations: int = 6
    # Epoch increments without intervening commit progress.
    thrash_epoch_increments: int = 3
    # Observations a locally-decided checkpoint may lack a net quorum.
    checkpoint_stalled_observations: int = 6
    # Observations one client's window may sit still with allocated reqs.
    starvation_observations: int = 8
    # Consecutive observations of strictly-growing buffered bytes...
    buffer_growth_observations: int = 5
    # ...counted only above this floor (small transients are normal).
    buffer_growth_floor_bytes: int = 256 * 1024

    def as_dict(self) -> Dict[str, int]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "HealthThresholds":
        """Build from a JSON dict, ignoring unknown keys — the shape
        ``tools/mirnet.py`` ships in ``cluster.json`` so wire deployments
        (one observation per 20 ms tick, not per sim event) can scale the
        observation counts, and the offline doctor can judge the recorded
        run by the very thresholds the live run used."""
        known = {f.name for f in fields(cls)}
        return cls(**{k: int(v) for k, v in d.items() if k in known})


@dataclass
class HealthConfig:
    """Testengine attachment knobs (``Recorder.health``): how often, in sim
    units, snapshots are observed and cross-replica fingerprints compared.
    Both default to the tick interval — one observation per node tick."""

    thresholds: HealthThresholds = field(default_factory=HealthThresholds)
    divergence_check_interval: int = 500


class HealthMonitor:
    """Per-node detector suite over periodic status snapshots plus the
    event stream.  Thread-safety: the node runtime observes snapshots on
    the coordinator thread and events on the result worker, so emission
    and the fault ledger are lock-protected; detector state is only
    touched by ``observe_snapshot`` (single caller in every wiring)."""

    def __init__(
        self,
        node_id: int,
        *,
        registry: Optional[metrics_mod.Registry] = None,
        tracer: Optional[tracing.Tracer] = None,
        logger=None,
        clock: Optional[Callable[[], float]] = None,
        thresholds: Optional[HealthThresholds] = None,
        num_nodes: Optional[int] = None,
    ):
        self.node_id = node_id
        self.registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self.tracer = tracer if tracer is not None else tracing.default_tracer
        self.logger = logger
        self.clock = clock if clock is not None else time.monotonic
        self.thresholds = thresholds if thresholds is not None else HealthThresholds()
        # Learned from the event stream (checkpoint/WAL network states) when
        # not provided; needed to attribute suspicions to the epoch primary.
        self.num_nodes = num_nodes

        self.anomalies: List[Anomaly] = []
        # (peer, fault_kind) -> count; every fault increments
        # peer_faults_total{peer,kind}, the first per key emits an Anomaly.
        self.faults: Dict[Tuple[int, str], int] = {}
        # Closed stall windows [(since, until, low_watermark), ...]; an open
        # window is (self._stall_since, None, low) until recovery.
        self.stall_windows: List[Tuple[float, Optional[float], int]] = []
        self.observations = 0
        # Monitor state is written by the node's processing loop and read
        # by snapshot(); the mutating entry points each take the lock,
        # while plain-counter reads tolerate staleness by design.
        # mirlint: allow(lock-map)
        self._lock = threading.Lock()

        # Commit progress, fed from the event stream (``ActionCommit`` in
        # ``observe_events``).  The status snapshot alone is too coarse: the
        # low watermark and client windows only move per checkpoint
        # interval, so a healthy fill phase would read as a stall.
        self._commits_seen = 0
        self._client_commits: Dict[int, int] = {}
        # watermark-stall state
        self._last_commit_sig: Optional[tuple] = None
        self._last_activity_sig: Optional[tuple] = None
        self._last_low: Optional[int] = None
        self._stall_count = 0
        self._stall_since: Optional[float] = None
        self._stall_flagged = False
        # epoch-thrash state
        self._last_epoch: Optional[int] = None
        self._thrash_increments = 0
        self._thrash_since: Optional[float] = None
        self._thrash_flagged = False
        # checkpoint-stagnation state: seq_no -> (count, since)
        self._cp_stalled: Dict[int, Tuple[int, float]] = {}
        self._cp_flagged: set = set()
        # client-starvation state: client_id -> (commit_sig, count, since)
        self._client_state: Dict[int, Tuple[tuple, int, float]] = {}
        self._client_flagged: set = set()
        # buffer-growth state
        self._last_buffer_bytes = 0
        self._growth_count = 0
        self._growth_since: Optional[float] = None
        self._growth_flagged = False
        # Flight-recorder auto-capture (docs/OBSERVABILITY.md "Flight
        # recorder"): deployments bind an eventlog.incident.AnomalyCapture
        # here; every emitted anomaly is offered for incident bundling.
        # Best-effort by contract — a capture failure never reaches the
        # detection path.
        self.capture_hook: Optional[Callable[[Anomaly], None]] = None

    def configure(
        self,
        thresholds: Optional[HealthThresholds] = None,
        num_nodes: Optional[int] = None,
    ) -> None:
        """Late-bind thresholds/num_nodes on an already-constructed monitor
        (``Node`` builds its monitor with defaults; ``tools/mirnet.py``
        reconfigures it from ``cluster.json`` before processing starts)."""
        if thresholds is not None:
            self.thresholds = thresholds
        if num_nodes is not None:
            self.num_nodes = num_nodes

    # --- emission (all three channels) ---

    def _emit(self, anomaly: Anomaly) -> None:
        with self._lock:
            self.anomalies.append(anomaly)
        self.registry.counter(
            "anomalies_total", labels={"kind": anomaly.kind}
        ).inc()
        self.tracer.instant(
            "anomaly",
            pid=anomaly.node_id,
            ts=anomaly.time,
            args=anomaly.as_dict(),
        )
        if self.logger is not None:
            self.logger.warn(
                "health anomaly",
                kind=anomaly.kind,
                node=anomaly.node_id,
                peer=anomaly.peer,
                since=anomaly.since,
                **{k: v for k, v in anomaly.detail.items()},
            )
        if self.capture_hook is not None:
            try:
                self.capture_hook(anomaly)
            except Exception:
                pass  # capture is evidence, never a failure mode

    def _set_status_gauge(self) -> None:
        self.registry.gauge(
            "health_status", labels={"node": str(self.node_id)}
        ).set(1.0 if self.anomalies else 0.0)

    # --- per-peer fault ledger ---

    def record_fault(
        self, peer: int, kind: str, now: Optional[float] = None, **detail
    ) -> None:
        """Attribute one fault to ``peer``.  Every fault counts in
        ``peer_faults_total{peer,kind}``; the first per (peer, kind) also
        emits a ``peer_fault`` anomaly (so clean runs stay anomaly-free and
        a misbehaving peer surfaces exactly once per misbehavior class)."""
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        now = self.clock() if now is None else now
        with self._lock:
            key = (peer, kind)
            count = self.faults.get(key, 0) + 1
            self.faults[key] = count
        self.registry.counter(
            "peer_faults_total", labels={"peer": str(peer), "kind": kind}
        ).inc()
        if count == 1:
            self._emit(
                Anomaly(
                    kind="peer_fault",
                    node_id=self.node_id,
                    time=now,
                    since=now,
                    peer=peer,
                    detail={"fault": kind, **detail},
                )
            )
        self._set_status_gauge()

    # --- event-stream detectors ---

    def observe_events(self, events, actions=None) -> None:
        """Fold one processed event batch: counts commits as progress for
        the snapshot detectors, learns the node count from network states
        in the stream, and feeds the fault ledger (suspicion votes,
        mismatched forwarded-batch digests)."""
        if actions is not None:
            for action in actions:
                if isinstance(action, st.ActionCommit):
                    # Null batches count too: the protocol making *any*
                    # forward progress (including heartbeat fill toward the
                    # next checkpoint) is not stalled.
                    self._commits_seen += 1
                    for ack in action.batch.requests:
                        self._client_commits[ack.client_id] = (
                            self._client_commits.get(ack.client_id, 0) + 1
                        )
        for event in events:
            t = event.__class__
            if t is st.EventStep:
                msg = event.msg
                if isinstance(msg, Suspect):
                    # A suspicion targets the suspected epoch's primary
                    # (number % num_nodes); without a learned node count the
                    # vote cannot be attributed and is skipped.
                    if self.num_nodes:
                        self.record_fault(
                            msg.epoch % self.num_nodes,
                            "suspicion_vote",
                            voter=event.source,
                            epoch=msg.epoch,
                        )
            elif t is st.EventHashResult:
                origin = event.origin
                if (
                    isinstance(origin, st.VerifyBatchOrigin)
                    and origin.expected_digest != event.digest
                ):
                    # A fetched batch whose content does not hash to the
                    # advertised digest: a byzantine forwarder.
                    self.record_fault(
                        origin.source, "invalid_digest", seq_no=origin.seq_no
                    )
            elif t is st.EventCheckpointResult or (
                t is st.EventStateTransferComplete
            ):
                self.num_nodes = len(event.network_state.config.nodes)
            elif t is st.EventLoadPersistedEntry:
                if isinstance(event.entry, CEntry):
                    self.num_nodes = len(event.entry.network_state.config.nodes)

    # --- snapshot detectors ---

    @staticmethod
    def _has_pending_work(snap) -> bool:
        """Evidence the watermark *should* be moving: allocated-uncommitted
        client requests, live suspicions, or undecided local checkpoints.
        Gates the stall detector so a quiescent node (everything committed,
        nothing submitted) is healthy, not stalled."""
        for cw in snap.client_windows:
            if 1 in cw.allocated:
                return True
        if snap.epoch_tracker.active_epoch.suspicions:
            return True
        for cp in snap.checkpoints:
            if cp.seq_no < snap.low_watermark:
                continue  # obsolete (the genesis entry never quorums)
            if cp.local_decision and not cp.net_quorum:
                return True
        return False

    def _commit_sig(self, snap) -> tuple:
        """Commit-progress fingerprint: watermark movement, commits seen on
        the event stream (null batches included), and client-window
        movement.  Resets the epoch-thrash streak and gates starvation —
        deliberately excludes three-phase activity, which churns during a
        view-change cascade without anything committing."""
        return (
            snap.low_watermark,
            self._commits_seen,
            tuple(
                (
                    cw.client_id,
                    cw.low_watermark,
                    sum(1 for a in cw.allocated if a == 2),
                )
                for cw in snap.client_windows
            ),
        )

    def _activity_sig(self, snap) -> tuple:
        """Protocol-activity fingerprint: commit progress plus three-phase
        sequence state transitions.  The stall detector resets on this —
        commits are too coarse during a healthy fill phase (the first
        commit can land several ticks after proposals start), but under a
        real partition every component freezes together."""
        return (
            self._commit_sig(snap),
            tuple(tuple(b.sequences) for b in snap.buckets),
        )

    def observe_snapshot(self, snap, now: Optional[float] = None) -> None:
        """Run the periodic detectors over one ``status.snapshot()`` view."""
        now = self.clock() if now is None else now
        self.observations += 1
        th = self.thresholds
        low = snap.low_watermark
        epoch = snap.epoch_tracker.active_epoch.number

        # -- watermark stall --
        activity_sig = self._activity_sig(snap)
        commit_sig = activity_sig[0]
        # Any protocol activity clears a stall; only commits clear a thrash
        # streak or count as the progress starvation is measured against.
        active = (
            self._last_activity_sig is not None
            and activity_sig != self._last_activity_sig
        )
        advanced = (
            self._last_commit_sig is not None
            and commit_sig != self._last_commit_sig
        )
        if active or self._last_activity_sig is None:
            if self._stall_since is not None:
                # Close the open stall window on recovery.
                self.stall_windows.append(
                    (self._stall_since, now, self._last_low)
                )
                if self.logger is not None and self._stall_flagged:
                    self.logger.info(
                        "watermark stall recovered",
                        node=self.node_id,
                        low_watermark=low,
                    )
            self._stall_count = 0
            self._stall_since = None
            self._stall_flagged = False
        elif self._has_pending_work(snap):
            if self._stall_since is None:
                self._stall_since = now
            self._stall_count += 1
            if self._stall_count >= th.stall_observations and (
                not self._stall_flagged
            ):
                self._stall_flagged = True
                self._emit(
                    Anomaly(
                        kind="watermark_stall",
                        node_id=self.node_id,
                        time=now,
                        since=self._stall_since,
                        detail={
                            "low_watermark": low,
                            "observations": self._stall_count,
                        },
                    )
                )
        self._last_activity_sig = activity_sig
        self._last_commit_sig = commit_sig
        self._last_low = low

        # -- epoch thrash --
        if self._last_epoch is not None and epoch > self._last_epoch:
            if advanced:
                self._thrash_increments = 1
                self._thrash_since = now
                self._thrash_flagged = False
            else:
                if self._thrash_increments == 0:
                    self._thrash_since = now
                self._thrash_increments += epoch - self._last_epoch
            if (
                self._thrash_increments >= th.thrash_epoch_increments
                and not self._thrash_flagged
            ):
                self._thrash_flagged = True
                self._emit(
                    Anomaly(
                        kind="epoch_thrash",
                        node_id=self.node_id,
                        time=now,
                        since=self._thrash_since or now,
                        detail={
                            "epoch": epoch,
                            "view_changes_without_commit": (
                                self._thrash_increments
                            ),
                        },
                    )
                )
        elif advanced:
            self._thrash_increments = 0
            self._thrash_flagged = False
        self._last_epoch = epoch

        # -- checkpoint-quorum stagnation --
        live = set()
        for cp in snap.checkpoints:
            if cp.seq_no < low:
                # Obsolete entry (notably the genesis checkpoint at seq 0,
                # which lingers in the map without ever reaching a network
                # quorum) — not a liveness signal.
                continue
            if cp.local_decision and not cp.net_quorum:
                live.add(cp.seq_no)
                count, since = self._cp_stalled.get(cp.seq_no, (0, now))
                count += 1
                self._cp_stalled[cp.seq_no] = (count, since)
                if count >= th.checkpoint_stalled_observations and (
                    cp.seq_no not in self._cp_flagged
                ):
                    self._cp_flagged.add(cp.seq_no)
                    self._emit(
                        Anomaly(
                            kind="checkpoint_stagnation",
                            node_id=self.node_id,
                            time=now,
                            since=since,
                            detail={
                                "seq_no": cp.seq_no,
                                "max_agreements": cp.max_agreements,
                            },
                        )
                    )
        for seq_no in list(self._cp_stalled):
            if seq_no not in live:
                del self._cp_stalled[seq_no]
                self._cp_flagged.discard(seq_no)

        # -- client-window starvation --
        seen = set()
        for cw in snap.client_windows:
            seen.add(cw.client_id)
            starving = 1 in cw.allocated
            # Per-client progress: the window advancing OR this client's
            # requests committing both reset the counter.
            cw_sig = (
                cw.low_watermark,
                sum(1 for a in cw.allocated if a == 2),
                self._client_commits.get(cw.client_id, 0),
            )
            last_sig, count, since = self._client_state.get(
                cw.client_id, (cw_sig, 0, now)
            )
            if cw_sig != last_sig or not starving:
                count = 0
                since = now
                self._client_flagged.discard(cw.client_id)
            elif advanced:
                # Starvation is relative: it only accrues while the rest of
                # the system makes progress this client is excluded from.
                # A global freeze is a stall, not starvation.
                count += 1
                if count >= th.starvation_observations and (
                    cw.client_id not in self._client_flagged
                ):
                    self._client_flagged.add(cw.client_id)
                    self._emit(
                        Anomaly(
                            kind="client_starvation",
                            node_id=self.node_id,
                            time=now,
                            since=since,
                            detail={
                                "client_id": cw.client_id,
                                "client_low_watermark": cw.low_watermark,
                            },
                        )
                    )
            self._client_state[cw.client_id] = (cw_sig, count, since)
        for client_id in list(self._client_state):
            if client_id not in seen:
                del self._client_state[client_id]
                self._client_flagged.discard(client_id)

        # -- message-buffer growth --
        total = sum(nb.size for nb in snap.node_buffers)
        if (
            total > self._last_buffer_bytes
            and total >= th.buffer_growth_floor_bytes
        ):
            if self._growth_count == 0:
                self._growth_since = now
            self._growth_count += 1
            if self._growth_count >= th.buffer_growth_observations and (
                not self._growth_flagged
            ):
                self._growth_flagged = True
                self._emit(
                    Anomaly(
                        kind="msg_buffer_growth",
                        node_id=self.node_id,
                        time=now,
                        since=self._growth_since or now,
                        detail={"buffered_bytes": total},
                    )
                )
        elif total <= self._last_buffer_bytes:
            self._growth_count = 0
            self._growth_flagged = False
        self._last_buffer_bytes = total

        self._set_status_gauge()

    # --- report surface ---

    def report(self) -> Dict[str, object]:
        """JSON-ready health report (``Node.health()``, BENCH_HEALTH.json,
        ``mircat --doctor``)."""
        with self._lock:
            anomalies = [a.as_dict() for a in self.anomalies]
            faults = {
                f"{peer}:{kind}": count
                for (peer, kind), count in sorted(self.faults.items())
            }
        windows = list(self.stall_windows)
        if self._stall_since is not None:
            windows.append((self._stall_since, None, self._last_low))
        return {
            "node_id": self.node_id,
            "healthy": not anomalies,
            "observations": self.observations,
            "anomaly_count": len(anomalies),
            "anomalies": anomalies,
            "peer_faults": faults,
            "stall_windows": [
                {"since": since, "until": until, "low_watermark": low}
                for since, until, low in windows
            ],
        }


class DivergenceDetector:
    """Cross-replica checkpoint-fingerprint comparison — the testengine
    safety tripwire.  Each interval the recorder feeds every simulated
    node's app-level ``(checkpoint_seq_no, checkpoint_hash)``; replicas at
    the same seq_no must report the same hash, and any mismatch flags the
    minority holder(s) as diverged.  One anomaly per (seq_no, node)."""

    def __init__(
        self,
        *,
        registry: Optional[metrics_mod.Registry] = None,
        tracer: Optional[tracing.Tracer] = None,
        logger=None,
    ):
        self.registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self.tracer = tracer if tracer is not None else tracing.default_tracer
        self.logger = logger
        self.anomalies: List[Anomaly] = []
        self.checks = 0
        self._flagged: set = set()  # (seq_no, node_id)

    def observe(
        self, fingerprints: Dict[int, Tuple[int, bytes]], now: float
    ) -> List[Anomaly]:
        """``fingerprints``: node_id -> (checkpoint_seq_no, checkpoint_hash).
        Returns the anomalies newly emitted by this sweep."""
        self.checks += 1
        by_seq: Dict[int, Dict[bytes, List[int]]] = {}
        for node_id, (seq_no, value) in fingerprints.items():
            by_seq.setdefault(seq_no, {}).setdefault(value, []).append(node_id)
        fresh: List[Anomaly] = []
        for seq_no, values in by_seq.items():
            if len(values) <= 1:
                continue
            majority = max(len(nodes) for nodes in values.values())
            tied = sum(
                1 for nodes in values.values() if len(nodes) == majority
            ) > 1
            for value, nodes in sorted(values.items()):
                if len(nodes) == majority and majority > 1 and not tied:
                    continue  # the agreeing side is not the deviant
                for node_id in nodes:
                    key = (seq_no, node_id)
                    if key in self._flagged:
                        continue
                    self._flagged.add(key)
                    anomaly = Anomaly(
                        kind="checkpoint_divergence",
                        node_id=node_id,
                        time=now,
                        since=now,
                        detail={
                            "seq_no": seq_no,
                            "value": value.hex()[:16],
                            "disagreeing_nodes": sorted(
                                n
                                for ns in values.values()
                                for n in ns
                                if n != node_id
                            ),
                        },
                    )
                    self.anomalies.append(anomaly)
                    fresh.append(anomaly)
                    self.registry.counter(
                        "anomalies_total",
                        labels={"kind": "checkpoint_divergence"},
                    ).inc()
                    self.tracer.instant(
                        "anomaly", pid=node_id, ts=now, args=anomaly.as_dict()
                    )
                    if self.logger is not None:
                        self.logger.error(
                            "checkpoint divergence",
                            node=node_id,
                            seq_no=seq_no,
                        )
        return fresh
