"""Fleet observability plane: telemetry collection, clock alignment,
trace merging, and trend detection (docs/OBSERVABILITY.md "Fleet plane").

The sharded deployment is a multi-process fleet whose tracing and metrics
planes are strictly node-local; this module is the parent-side aggregator
that turns them into one queryable surface:

- :class:`ClockAligner` — Cristian-style per-link offset estimation from
  pull/report echo timestamps.  Every process stamps trace events with
  its own ``time.perf_counter`` epoch, so raw timestamps from different
  processes are incomparable; the aligner maps each child's clock onto
  the collector's.
- :func:`build_report` — the child-side report builder: one metrics
  snapshot plus the trace-ring delta past the collector's cursor.
- :class:`TelemetryServer` — a standalone KIND_TELEMETRY listener for
  processes without a transport listener of their own (observers).
  Member nodes serve the same frames on their existing transport socket
  (``TcpTransport.start(on_telemetry=...)``).
- :class:`FleetCollector` — the mirnet parent's puller.  Periodically
  exchanges TEL_PULL/TEL_REPORT with every endpoint and maintains a
  rolling ``fleet/`` directory: ``latest.json`` (most recent snapshot
  per node), ``history.json`` (time-series ring), and ``trace.json``
  (the merged Chrome trace, pid = group, tid = node).
- :func:`detect_trends` — history-ring detectors for the leak shapes a
  soak cares about: monotonic RSS growth, fd growth, widening observer
  lag.
- :func:`slo_rows` — the cross-group SLO table behind ``mircat --fleet``
  and ``mirnet --top``.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from mirbft_tpu import metrics as metrics_mod
from mirbft_tpu import tracing
from mirbft_tpu.net import telemetry
from mirbft_tpu.net.framing import (
    KIND_TELEMETRY,
    FrameDecoder,
    FrameError,
    encode_frame,
)

# ---------------------------------------------------------------------------
# Clock alignment


class ClockAligner:
    """Cristian-style offset estimation over a sliding sample window.

    Each sample is one pull/report exchange: the parent sent at ``t0``,
    received at ``t1`` (both parent clock), and the child stamped the
    report at ``child_ts`` (child clock).  Assuming symmetric delay the
    child's stamp happened at the parent-clock midpoint ``(t0 + t1) / 2``,
    so ``offset = child_ts - midpoint`` converts child time to parent
    time by subtraction.  The estimate used is the offset of the
    *lowest-RTT* sample in the window — high-RTT exchanges bound the
    error loosely — and the window keeps the estimate fresh under drift.
    """

    def __init__(self, window: int = 16):
        self._samples: deque = deque(maxlen=window)

    def add(self, t0_us: float, t1_us: float, child_ts_us: float) -> None:
        rtt = max(0.0, float(t1_us) - float(t0_us))
        midpoint = (float(t0_us) + float(t1_us)) / 2.0
        self._samples.append((rtt, float(child_ts_us) - midpoint))

    def __len__(self) -> int:
        return len(self._samples)

    @property
    def offset_us(self) -> float:
        """Best current child-minus-parent offset estimate (0 until the
        first sample)."""
        if not self._samples:
            return 0.0
        return min(self._samples)[1]

    @property
    def rtt_us(self) -> float:
        if not self._samples:
            return 0.0
        return min(self._samples)[0]

    def to_parent(self, child_ts_us: float) -> float:
        return float(child_ts_us) - self.offset_us


# ---------------------------------------------------------------------------
# Child side: report building + the observer-side telemetry listener


def _rss_kb() -> Optional[int]:
    """Current resident set from /proc/self/statm — NOT ru_maxrss, which
    is a high-water mark and would trip the monotonic-growth detector on
    every healthy process."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGESIZE") // 1024
    except (OSError, ValueError, IndexError):
        return None


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def build_report(
    group: Optional[int],
    node_label: str,
    cursor: int,
    registry: Optional[metrics_mod.Registry] = None,
    tracer: Optional[tracing.Tracer] = None,
) -> Dict:
    """One TEL_REPORT body: the child's clock, metrics snapshot, trace
    delta past ``cursor``, and process vitals."""
    reg = registry if registry is not None else metrics_mod.default_registry
    trc = tracer if tracer is not None else tracing.default_tracer
    new_cursor, events, dropped = trc.drain(cursor)
    report: Dict = {
        "ts_us": tracing.wall_clock_us(),
        "group": group,
        "node": node_label,
        "metrics": reg.snapshot(),
        "trace": {
            "cursor": new_cursor,
            "dropped": dropped,
            "events": events,
            "meta": [],
        },
    }
    rss = _rss_kb()
    if rss is not None:
        report["rss_kb"] = rss
    fds = _open_fds()
    if fds is not None:
        report["open_fds"] = fds
    return report


def serve_pull(
    payload: bytes,
    send,
    group: Optional[int],
    node_label: str,
    node_id: int = 0,
    registry: Optional[metrics_mod.Registry] = None,
    tracer: Optional[tracing.Tracer] = None,
) -> bool:
    """Answer one KIND_TELEMETRY payload if it is a TEL_PULL; returns
    whether it was.  Shared by the member-node transport handler and
    :class:`TelemetryServer`."""
    subtype, _from_node, t0_us, body = telemetry.decode(payload)
    if subtype != telemetry.TEL_PULL:
        return False
    cursor = int(telemetry.decode_body(body).get("cursor", 0))
    report = build_report(
        group, node_label, cursor, registry=registry, tracer=tracer
    )
    send(telemetry.encode_report(node_id, t0_us, report))
    return True


class TelemetryServer:
    """Minimal KIND_TELEMETRY listener for listener-less processes.

    Observers have no :class:`TcpTransport`; this serves TEL_PULL on a
    dedicated port so the fleet collector can reach them the same way it
    reaches members."""

    def __init__(
        self,
        host: str,
        port: int,
        group: Optional[int],
        node_label: str,
        registry: Optional[metrics_mod.Registry] = None,
        tracer: Optional[tracing.Tracer] = None,
    ):
        self.group = group
        self.node_label = node_label
        self.registry = registry
        self.tracer = tracer
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(8)
        self._listener.settimeout(0.2)

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    def start(self) -> None:
        accept = threading.Thread(
            target=self._accept_loop, name="telemetry-accept", daemon=True
        )
        accept.start()
        self._threads.append(accept)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for thread in self._threads:
            thread.join(timeout=2)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.2)
            reader = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="telemetry-rx",
                daemon=True,
            )
            reader.start()
            self._threads.append(reader)

    def _serve_conn(self, conn: socket.socket) -> None:
        decoder = FrameDecoder()

        def send(payload: bytes) -> None:
            conn.sendall(encode_frame(KIND_TELEMETRY, payload))

        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    return
                for kind, payload in decoder.feed(data):
                    if kind != KIND_TELEMETRY:
                        return  # wrong plane: drop the connection
                    serve_pull(
                        payload,
                        send,
                        self.group,
                        self.node_label,
                        registry=self.registry,
                        tracer=self.tracer,
                    )
        except (FrameError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Parent side: the collector


class _Endpoint:
    __slots__ = (
        "group",
        "label",
        "addr",
        "sock",
        "decoder",
        "cursor",
        "aligner",
        "events",
        "tid",
        "last",
        "reachable",
    )

    def __init__(self, group: int, label: str, addr: Tuple[str, int]):
        self.group = group
        self.label = label
        self.addr = (str(addr[0]), int(addr[1]))
        self.sock: Optional[socket.socket] = None
        self.decoder: Optional[FrameDecoder] = None
        self.cursor = 0
        self.aligner = ClockAligner()
        self.events: deque = deque(maxlen=20000)
        self.tid = 0
        self.last: Optional[Dict] = None
        self.reachable = False


class FleetCollector:
    """Pull-based fleet telemetry aggregator (see module docstring).

    ``endpoints`` is ``[{"group": g, "node": label, "host": h,
    "port": p}, ...]`` — every member node (its transport listen port)
    and every observer (its :class:`TelemetryServer` port).  The
    collector's own clock is :func:`tracing.wall_clock_us`, the same
    domain every child stamps its reports and trace events in, so one
    aligner per endpoint closes the epoch gap.
    """

    # History entries keep only the metric series the SLO table and the
    # trend detectors read — a full per-node snapshot ballooned the ring's
    # JSON dump to >10 ms per flush.  latest.json keeps everything.
    HISTORY_METRIC_PREFIXES = (
        "commit_latency_seconds",
        "observer_lag_batches",
        "pipeline_admission_stall_seconds",
        "net_send_lock_wait_seconds",
        "wal_fsync_seconds",
    )

    def __init__(
        self,
        out_dir,
        endpoints: List[Dict],
        interval_s: float = 1.0,
        history_cap: int = 240,
        trace_every: int = 4,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.interval_s = interval_s
        # The merged trace is an analysis artifact, not a dashboard: it is
        # the expensive file (re-mapping + serializing every retained
        # event), so it lands every ``trace_every``-th flush and always on
        # stop.  latest/history stay fresh every interval.
        self.trace_every = max(1, int(trace_every))
        self._flushes = 0
        self._endpoints = [
            _Endpoint(int(ep["group"]), str(ep["node"]),
                      (ep["host"], ep["port"]))
            for ep in endpoints
        ]
        per_group: Dict[int, int] = {}
        for ep in self._endpoints:
            ep.tid = per_group.get(ep.group, 0)
            per_group[ep.group] = ep.tid + 1
        self.history: deque = deque(maxlen=history_cap)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else metrics_mod.default_registry
        self._pulls = reg.counter("fleet_pulls_total")
        self._pull_timer = reg.histogram("fleet_pull_seconds")
        self._trace_events = reg.counter("fleet_trace_events_total")
        self._trace_dropped = reg.counter("fleet_trace_dropped_total")
        self._registry = reg

    # -- one exchange -------------------------------------------------------

    def _drop_conn(self, ep: _Endpoint) -> None:
        if ep.sock is not None:
            try:
                ep.sock.close()
            except OSError:
                pass
        ep.sock = None
        ep.decoder = None
        ep.reachable = False

    def _exchange(self, ep: _Endpoint, timeout_s: float = 2.0) -> None:
        if ep.sock is None:
            ep.sock = socket.create_connection(ep.addr, timeout=timeout_s)
            ep.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ep.sock.settimeout(timeout_s)
            ep.decoder = FrameDecoder()
        t0 = tracing.wall_clock_us()
        ep.sock.sendall(
            encode_frame(
                KIND_TELEMETRY, telemetry.encode_pull(0, int(t0), ep.cursor)
            )
        )
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                data = ep.sock.recv(1 << 20)
            except socket.timeout:
                continue
            if not data:
                raise OSError("telemetry peer closed the connection")
            for kind, payload in ep.decoder.feed(data):
                if kind != KIND_TELEMETRY:
                    continue
                subtype, _node, echo_t0, body = telemetry.decode(payload)
                if subtype != telemetry.TEL_REPORT:
                    continue
                t1 = tracing.wall_clock_us()
                self.ingest_report(
                    ep, float(echo_t0), t1, telemetry.decode_body(body)
                )
                return
        raise OSError(f"telemetry pull to {ep.addr} timed out")

    def ingest_report(
        self, ep: _Endpoint, t0_us: float, t1_us: float, report: Dict
    ) -> None:
        """Fold one TEL_REPORT body into the endpoint's state.  Public so
        the bench can measure collector cost without sockets."""
        ts_us = float(report.get("ts_us", 0.0))
        if ts_us:
            ep.aligner.add(t0_us, t1_us, ts_us)
        trace = report.get("trace") or {}
        ep.cursor = int(trace.get("cursor", ep.cursor))
        dropped = int(trace.get("dropped", 0))
        if dropped:
            self._trace_dropped.inc(dropped)
        events = trace.get("events") or []
        for ev in events:
            ep.events.append(ev)
        if events:
            self._trace_events.inc(len(events))
        self._registry.gauge(
            "fleet_clock_offset_us", labels={"node": ep.label}
        ).set(ep.aligner.offset_us)
        ep.last = {
            "group": ep.group,
            "metrics": report.get("metrics") or {},
            "rss_kb": report.get("rss_kb"),
            "open_fds": report.get("open_fds"),
            "ts_us": ts_us,
            "offset_us": ep.aligner.offset_us,
            "rtt_us": ep.aligner.rtt_us,
        }
        ep.reachable = True
        self._pulls.inc()

    # -- one full cycle -----------------------------------------------------

    def pull_once(self) -> None:
        with metrics_mod.Timer(self._pull_timer):
            for ep in self._endpoints:
                try:
                    self._exchange(ep)
                except (OSError, FrameError):
                    self._drop_conn(ep)
            self._record_history()
            self.flush()

    def _prune_for_history(self, last: Dict) -> Dict:
        metrics = last.get("metrics") or {}
        kept = {
            k: v
            for k, v in metrics.items()
            if k.startswith(self.HISTORY_METRIC_PREFIXES)
        }
        pruned = dict(last)
        pruned["metrics"] = kept
        return pruned

    def _record_history(self) -> None:
        nodes = {}
        for ep in self._endpoints:
            if ep.last is not None:
                nodes[ep.label] = self._prune_for_history(ep.last)
        if nodes:
            self.history.append(
                {
                    "t_us": tracing.wall_clock_us(),
                    "wall": time.time(),
                    "nodes": nodes,
                }
            )

    def merged_trace(self) -> Dict:
        """The fleet Chrome trace: every endpoint's events mapped onto
        the collector clock, pid = group id, tid = node index within the
        group."""
        meta: List[Dict] = []
        groups_named = set()
        events: List[Dict] = []
        for ep in self._endpoints:
            if ep.group not in groups_named:
                groups_named.add(ep.group)
                meta.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": ep.group,
                        "tid": 0,
                        "args": {"name": f"group-{ep.group}"},
                    }
                )
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": ep.group,
                    "tid": ep.tid,
                    "args": {"name": ep.label},
                }
            )
            offset = ep.aligner.offset_us
            for ev in ep.events:
                out = dict(ev)
                out["ts"] = float(ev.get("ts", 0.0)) - offset
                out["pid"] = ep.group
                out["tid"] = ep.tid
                events.append(out)
        events.sort(key=lambda e: e["ts"])
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"clock_domain": "fleet"},
        }

    def _write_json(self, name: str, doc) -> None:
        tmp = self.out_dir / (name + ".tmp")
        tmp.write_text(json.dumps(doc, separators=(",", ":")))
        tmp.replace(self.out_dir / name)

    def flush(self, final: bool = False) -> None:
        latest = {
            "wall": time.time(),
            "nodes": {
                ep.label: dict(ep.last, reachable=ep.reachable)
                for ep in self._endpoints
                if ep.last is not None
            },
        }
        self._write_json("latest.json", latest)
        self._write_json("history.json", list(self.history))
        if final or self._flushes % self.trace_every == 0:
            self._write_json("trace.json", self.merged_trace())
        self._flushes += 1

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="fleet-collector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Flush before dropping connections: _drop_conn marks endpoints
        # unreachable (its meaning on the exchange path), which must not
        # leak into the final persisted snapshot.
        try:
            self.flush(final=True)
        except OSError:
            pass
        for ep in self._endpoints:
            self._drop_conn(ep)

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.pull_once()
            except Exception:
                # The collector must never take the deployment down.
                pass


# ---------------------------------------------------------------------------
# Query surface: metric extraction, SLO rows, trend detection


def _metric_values(snap: Dict, name: str, suffix: str = "") -> List[float]:
    """Values for ``name`` across label blocks: matches ``name<suffix>``
    and ``name{...}<suffix>`` keys in a flat snapshot dict."""
    pat = re.compile(
        re.escape(name) + r"(\{[^}]*\})?" + re.escape(suffix) + r"$"
    )
    return [
        float(v)
        for k, v in snap.items()
        if pat.fullmatch(k) and isinstance(v, (int, float))
    ]


def _median(values: List[float]) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def slo_rows(history: List[Dict]) -> List[Dict]:
    """Per-group SLO rows from a history ring: commit p50 (median across
    members) and p99 (max), observer lag, admission-stall p99, WAL fsync
    share of wall time over the window, and send-lock wait p99."""
    if not history:
        return []
    latest = history[-1]
    earliest = history[0]
    by_group: Dict[int, Dict[str, List]] = {}
    for label, node in latest["nodes"].items():
        group = node.get("group")
        if group is None:
            continue
        snap = node.get("metrics") or {}
        row = by_group.setdefault(
            int(group),
            {"p50": [], "p99": [], "lag": [], "stall": [], "lock": [],
             "fsync_share": [], "mapv": []},
        )
        row["p50"].extend(
            _metric_values(snap, "commit_latency_seconds", "_p50")
        )
        row["p99"].extend(
            _metric_values(snap, "commit_latency_seconds", "_p99")
        )
        row["lag"].extend(_metric_values(snap, "observer_lag_batches"))
        row["mapv"].extend(_metric_values(snap, "map_version"))
        row["stall"].extend(
            _metric_values(
                snap, "pipeline_admission_stall_seconds", "_p99"
            )
        )
        row["lock"].extend(
            _metric_values(snap, "net_send_lock_wait_seconds", "_p99")
        )
        first = (earliest["nodes"].get(label) or {}).get("metrics") or {}
        dt_s = (latest["t_us"] - earliest["t_us"]) / 1e6
        if dt_s > 0:
            now_sum = _metric_values(snap, "wal_fsync_seconds", "_sum")
            then_sum = _metric_values(first, "wal_fsync_seconds", "_sum")
            if now_sum:
                delta = sum(now_sum) - sum(then_sum)
                row["fsync_share"].append(max(0.0, delta) / dt_s * 100.0)
    rows = []
    for group in sorted(by_group):
        agg = by_group[group]
        rows.append(
            {
                "group": group,
                "commit_p50_ms": None if not agg["p50"] else round(
                    _median(agg["p50"]) * 1e3, 3
                ),
                "commit_p99_ms": None if not agg["p99"] else round(
                    max(agg["p99"]) * 1e3, 3
                ),
                "observer_lag": None if not agg["lag"] else max(agg["lag"]),
                "admission_stall_p99_ms": None if not agg["stall"] else round(
                    max(agg["stall"]) * 1e3, 3
                ),
                "send_lock_wait_p99_ms": None if not agg["lock"] else round(
                    max(agg["lock"]) * 1e3, 3
                ),
                "wal_fsync_share_pct": None if not agg["fsync_share"]
                else round(max(agg["fsync_share"]), 2),
                # Routing epoch (docs/SHARDING.md "Elastic resharding"):
                # the newest map any member of the group has installed.
                "map_version": None if not agg["mapv"]
                else int(max(agg["mapv"])),
            }
        )
    return rows


def detect_trends(
    history: List[Dict],
    min_points: int = 6,
    rss_growth_kb: int = 1024,
    fd_growth: int = 8,
    lag_growth: int = 3,
) -> List[Dict]:
    """History-ring trend detectors (informational — they annotate doctor
    output, they do not flip verdicts):

    - ``rss_monotonic_growth``: a node's resident set never decreased
      across the window and grew by >= ``rss_growth_kb``.
    - ``fd_growth``: open fd count never decreased and grew by >=
      ``fd_growth``.
    - ``observer_lag_widening``: an observer's lag gauge never decreased
      and widened by >= ``lag_growth`` batches.
    """
    if len(history) < min_points:
        return []
    window = list(history)[-max(min_points, 2):]
    labels = set()
    for entry in window:
        labels.update(entry["nodes"])
    findings: List[Dict] = []

    def series(label: str, field: str) -> List[float]:
        out = []
        for entry in window:
            node = entry["nodes"].get(label)
            if node is None:
                return []  # gaps: skip this label entirely
            value = node.get(field)
            if value is None:
                return []
            out.append(float(value))
        return out

    def metric_series(label: str, name: str) -> List[float]:
        out = []
        for entry in window:
            node = entry["nodes"].get(label)
            if node is None:
                return []
            values = _metric_values(node.get("metrics") or {}, name)
            if not values:
                return []
            out.append(max(values))
        return out

    def monotone_grew(values: List[float], growth: float) -> bool:
        if len(values) < min_points:
            return False
        if any(b < a for a, b in zip(values, values[1:])):
            return False
        return values[-1] - values[0] >= growth

    for label in sorted(labels):
        rss = series(label, "rss_kb")
        if monotone_grew(rss, rss_growth_kb):
            findings.append(
                {
                    "node": label,
                    "kind": "rss_monotonic_growth",
                    "detail": f"rss {rss[0]:.0f} -> {rss[-1]:.0f} kB over "
                              f"{len(rss)} samples",
                }
            )
        fds = series(label, "open_fds")
        if monotone_grew(fds, fd_growth):
            findings.append(
                {
                    "node": label,
                    "kind": "fd_growth",
                    "detail": f"open fds {fds[0]:.0f} -> {fds[-1]:.0f} over "
                              f"{len(fds)} samples",
                }
            )
        lag = metric_series(label, "observer_lag_batches")
        if monotone_grew(lag, lag_growth):
            findings.append(
                {
                    "node": label,
                    "kind": "observer_lag_widening",
                    "detail": f"lag {lag[0]:.0f} -> {lag[-1]:.0f} batches "
                              f"over {len(lag)} samples",
                }
            )
    return findings


def load_fleet(fleet_dir) -> Dict:
    """Read a collector output directory: ``{"latest": ..., "history":
    [...], "trace": {...}}`` with missing files as empty values."""
    root = Path(fleet_dir)
    out = {"latest": {}, "history": [], "trace": {}}
    for key, name in (
        ("latest", "latest.json"),
        ("history", "history.json"),
        ("trace", "trace.json"),
    ):
        path = root / name
        if path.exists():
            try:
                out[key] = json.loads(path.read_text())
            except ValueError:
                pass
    return out


def trace_timeline(trace_doc: Dict, trace_id_hex: str) -> List[Dict]:
    """Every event in a merged trace carrying the given trace id, sorted
    by aligned timestamp — the per-request causal timeline."""
    matches = []
    for ev in trace_doc.get("traceEvents", []):
        args = ev.get("args") or {}
        if args.get("trace") == trace_id_hex or trace_id_hex in (
            (args.get("traces") or {}).values()
        ):
            matches.append(ev)
    matches.sort(key=lambda e: e.get("ts", 0.0))
    return matches
