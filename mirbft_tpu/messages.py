"""L0 wire/state schema: consensus messages, network state, and WAL entries.

TPU-native rebuild of the reference's protobuf schema
(``/root/reference/protos/msgs/msgs.proto``).  We use frozen dataclasses with a
canonical binary codec (``mirbft_tpu.wire``) instead of protobuf: the codec is
deterministic (required because epoch-change digests are computed over
serialized message content on every node), dependency-free, and keeps message
construction allocation-light on the host side so the hot loop feeds the TPU
hash batcher without marshaling overhead.

Message vocabulary parity (reference ``msgs.proto:189-207``): 15 message
variants, 8 persistent WAL entry kinds, NetworkState/Config/Client, and
Reconfiguration variants.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Tuple, Union

if sys.version_info >= (3, 11):
    # voteplane's split cache holds weakrefs to MsgBatch envelopes, which a
    # slots dataclass only supports via 3.11's weakref_slot.
    _weakrefable_dataclass = dataclass(frozen=True, slots=True, weakref_slot=True)
else:
    # 3.10 has no weakref_slot: forgo slots so __weakref__ exists.
    _weakrefable_dataclass = dataclass(frozen=True)

# ---------------------------------------------------------------------------
# Network state (consensused configuration).  Reference: msgs.proto:18-111.
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class NetworkConfig:
    """Consensused protocol parameters (reference msgs.proto:19-73)."""

    nodes: Tuple[int, ...]
    checkpoint_interval: int
    max_epoch_length: int
    number_of_buckets: int
    f: int


@dataclass(frozen=True, slots=True)
class ClientState:
    """Per-client request-window state (reference msgs.proto:75-105)."""

    id: int
    width: int
    width_consumed_last_checkpoint: int
    low_watermark: int
    committed_mask: bytes


@dataclass(frozen=True, slots=True)
class ReconfigNewClient:
    id: int
    width: int


@dataclass(frozen=True, slots=True)
class ReconfigRemoveClient:
    id: int


@dataclass(frozen=True, slots=True)
class ReconfigNewConfig:
    config: NetworkConfig


@dataclass(frozen=True, slots=True)
class ReconfigTransferClient:
    """Admit a client mid-stream at an explicit low watermark.

    Used by elastic resharding (docs/SHARDING.md): when a merge moves a
    client back into its parent group, the parent must start the client's
    window at one past the highest request the child committed —
    ``ReconfigNewClient`` (watermark 0) would re-open already-committed
    request numbers and break exactly-once under client retries.
    """

    id: int
    width: int
    low_watermark: int


Reconfiguration = Union[
    ReconfigNewClient,
    ReconfigRemoveClient,
    ReconfigNewConfig,
    ReconfigTransferClient,
]


@dataclass(frozen=True, slots=True)
class NetworkState:
    """Reference msgs.proto:18-111 (``reconfigured`` bool intentionally omitted:
    the reference marks it "TODO, do we need this?" and never reads it)."""

    config: NetworkConfig
    clients: Tuple[ClientState, ...]
    pending_reconfigurations: Tuple[Reconfiguration, ...] = ()


# ---------------------------------------------------------------------------
# Requests and acks.
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class RequestAck:
    """Digest-attestation for (client_id, req_no) (reference msgs.proto:241-245)."""

    client_id: int
    req_no: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class Request:
    client_id: int
    req_no: int
    data: bytes


# ---------------------------------------------------------------------------
# Epoch configuration / view-change payloads.
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class EpochConfig:
    """Reference msgs.proto:321-328."""

    number: int
    leaders: Tuple[int, ...]
    planned_expiration: int


@dataclass(frozen=True, slots=True)
class CheckpointMsg:
    """Checkpoint attestation message (reference msgs.proto:266-269)."""

    seq_no: int
    value: bytes


@dataclass(frozen=True, slots=True)
class EpochChangeSetEntry:
    """P-set / Q-set entry (reference msgs.proto:285-289)."""

    epoch: int
    seq_no: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class EpochChange:
    """PBFT view-change message, Mir-adapted (reference msgs.proto:275-299)."""

    new_epoch: int
    checkpoints: Tuple[CheckpointMsg, ...]
    p_set: Tuple[EpochChangeSetEntry, ...]
    q_set: Tuple[EpochChangeSetEntry, ...]


@dataclass(frozen=True, slots=True)
class EpochChangeAck:
    """Reference msgs.proto:305-314."""

    originator: int
    epoch_change: EpochChange


@dataclass(frozen=True, slots=True)
class NewEpochConfig:
    """Reference msgs.proto:330-340."""

    config: EpochConfig
    starting_checkpoint: CheckpointMsg
    final_preprepares: Tuple[bytes, ...]


@dataclass(frozen=True, slots=True)
class RemoteEpochChange:
    node_id: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class NewEpoch:
    """NewView analogue; config Bracha-broadcast (reference msgs.proto:342-362)."""

    new_config: NewEpochConfig
    epoch_changes: Tuple[RemoteEpochChange, ...]


# ---------------------------------------------------------------------------
# The 15 consensus message variants (reference msgs.proto:189-207).
# Variants that share a payload type in the proto oneof (fetch_request /
# request_ack are both msgs.RequestAck; new_epoch_echo / new_epoch_ready are
# both NewEpochConfig) get distinct wrapper classes so dispatch is by type.
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class Preprepare:
    seq_no: int
    epoch: int
    batch: Tuple[RequestAck, ...]


@dataclass(frozen=True, slots=True)
class Prepare:
    seq_no: int
    epoch: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class Commit:
    seq_no: int
    epoch: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class Suspect:
    epoch: int


@dataclass(frozen=True, slots=True)
class NewEpochEcho:
    config: NewEpochConfig


@dataclass(frozen=True, slots=True)
class NewEpochReady:
    config: NewEpochConfig


@dataclass(frozen=True, slots=True)
class FetchBatch:
    seq_no: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class ForwardBatch:
    seq_no: int
    request_acks: Tuple[RequestAck, ...]
    digest: bytes


@dataclass(frozen=True, slots=True)
class FetchRequest:
    ack: RequestAck


@dataclass(frozen=True, slots=True)
class ForwardRequest:
    request_ack: RequestAck
    request_data: bytes


@dataclass(frozen=True, slots=True)
class AckMsg:
    """Broadcast request acknowledgement (proto oneof field ``request_ack``)."""

    ack: RequestAck


@_weakrefable_dataclass
class MsgBatch:
    """Transport envelope: a sequence of consensus messages from one sender
    to the same targets, delivered atomically.  Nesting is not allowed.

    Processing order: the receiver applies the envelope's Prepare/Commit
    votes first (in order), then the remaining messages (in order) — see
    ``machine.StateMachine.step``.  Relative to per-message delivery this is
    merely a different (still deterministic) interleaving, which the
    protocol must tolerate from any asynchronous network anyway.

    Extension over the reference, whose Link sends every protocol message as
    its own transmission.  Consensus traffic is many tiny messages — at N
    replicas each sequence costs O(N²) Prepares/Commits and each epoch change
    O(N³) EpochChangeAcks — so aggregating everything a replica emits to the
    same destination in one processing iteration amortizes per-message
    transport and event dispatch."""

    msgs: Tuple["Msg", ...]


@dataclass(frozen=True, slots=True)
class AckBatch:
    """Aggregated request acknowledgements: semantically identical to sending
    each contained ack as its own ``AckMsg`` to the same targets, in order.

    Extension over the reference, which broadcasts one message per ack
    (``client_hash_disseminator.go:878-895``).  The ack flood is the
    throughput-dominant traffic class — O(N²) messages per request across the
    cluster — so aggregating the acks a replica generates in one step
    amortizes per-message transport and dispatch cost over the whole batch
    (the Mir paper itself batches dissemination)."""

    acks: Tuple[RequestAck, ...]


Msg = Union[
    Preprepare,
    Prepare,
    Commit,
    CheckpointMsg,
    Suspect,
    EpochChange,
    EpochChangeAck,
    NewEpoch,
    NewEpochEcho,
    NewEpochReady,
    FetchBatch,
    ForwardBatch,
    FetchRequest,
    ForwardRequest,
    AckMsg,
    AckBatch,
    MsgBatch,
]


# ---------------------------------------------------------------------------
# Persistent WAL entries (8 kinds; reference msgs.proto:127-186).
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class QEntry:
    """Persisted before a batch is preprepared (reference msgs.proto:157-164)."""

    seq_no: int
    digest: bytes
    requests: Tuple[RequestAck, ...]


@dataclass(frozen=True, slots=True)
class PEntry:
    """Persisted before a batch is prepared (reference msgs.proto:166-171)."""

    seq_no: int
    digest: bytes


@dataclass(frozen=True, slots=True)
class CEntry:
    """Persisted before a Checkpoint message is sent (reference msgs.proto:173-179)."""

    seq_no: int
    checkpoint_value: bytes
    network_state: NetworkState


@dataclass(frozen=True, slots=True)
class NEntry:
    """New sequence-window allocation marker (reference msgs.proto:141-146)."""

    seq_no: int
    epoch_config: EpochConfig


@dataclass(frozen=True, slots=True)
class FEntry:
    """Graceful epoch-end marker (reference msgs.proto:148-150)."""

    ends_epoch_config: EpochConfig


@dataclass(frozen=True, slots=True)
class ECEntry:
    """Epoch-change-sent marker; halts truncation (reference msgs.proto:152-155)."""

    epoch_number: int


@dataclass(frozen=True, slots=True)
class TEntry:
    """State-transfer-requested marker (reference msgs.proto:157-160)."""

    seq_no: int
    value: bytes


# Suspect doubles as the eighth persistent kind (reference msgs.proto:127-139).
Persistent = Union[QEntry, PEntry, CEntry, NEntry, FEntry, ECEntry, TEntry, Suspect]
