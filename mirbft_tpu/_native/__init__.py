"""Native (C++) hot-path planes, built on first import.

The consensus state machine stays in Python (branchy protocol logic — see
SURVEY.md §7), but the per-message vote-accumulation hot loops run O(N²)
times per request cluster-wide and dominate wall-clock at 64+ replicas, so
they are implemented natively.  Rules of engagement:

* Pure-Python equivalents remain in ``mirbft_tpu/statemachine/`` and are the
  semantic reference; differential tests assert byte-identical behavior.
* The extension is optional: if no toolchain is available (or
  ``MIRBFT_TPU_NATIVE=0``), everything runs pure-Python.
* Built with a direct ``g++`` invocation (no setuptools machinery, no
  pybind11 — neither is guaranteed in the image); the .so is cached next to
  the source and rebuilt when the source is newer.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

available = False
core = None
fast_available = False
fast = None

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "ackplane.cpp")
_SO = os.path.join(_HERE, "_core.so")
_FAST_SRC = os.path.join(_HERE, "fastengine.cpp")
_FAST_SO = os.path.join(_HERE, "_fast.so")


def _build(src: str, so: str) -> bool:
    include = sysconfig.get_paths()["include"]
    tmp = so + ".tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-I", include, src, "-o", tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=300
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False
    os.replace(tmp, so)  # atomic: concurrent builders race benignly
    return True


def _load_one(src: str, so: str, modname: str):
    """Build (if stale) and import one extension; returns the module or None."""
    try:
        needs_build = (not os.path.exists(so)) or (
            os.path.getmtime(src) > os.path.getmtime(so)
        )
    except OSError:
        needs_build = True
    if needs_build and not _build(src, so):
        return None
    import importlib

    try:
        return importlib.import_module(f"{__name__}.{modname}")
    except ImportError:
        # A stale ABI-incompatible artifact: rebuild once.
        if not _build(src, so):
            return None
        try:
            return importlib.import_module(f"{__name__}.{modname}")
        except ImportError:
            return None


def _load() -> None:
    global available, core
    if os.environ.get("MIRBFT_TPU_NATIVE", "1") == "0":
        return
    core = _load_one(_SRC, _SO, "_core")
    available = core is not None


_fast_attempted = False


def load_fast():
    """Build/load the fast-engine extension on first use (lazy: a cold
    compile of fastengine.cpp takes ~35 s, which plain package importers —
    tests of unrelated modules, the graft entry compile check — should not
    pay).  Returns the module or None."""
    global fast, fast_available, _fast_attempted
    if _fast_attempted:
        return fast
    _fast_attempted = True
    if os.environ.get("MIRBFT_TPU_NATIVE", "1") == "0":
        return None
    fast = _load_one(_FAST_SRC, _FAST_SO, "_fast")
    fast_available = fast is not None
    return fast


_load()
