"""Native (C++) hot-path planes, built on first import.

The consensus state machine stays in Python (branchy protocol logic — see
SURVEY.md §7), but the per-message vote-accumulation hot loops run O(N²)
times per request cluster-wide and dominate wall-clock at 64+ replicas, so
they are implemented natively.  Rules of engagement:

* Pure-Python equivalents remain in ``mirbft_tpu/statemachine/`` and are the
  semantic reference; differential tests assert byte-identical behavior.
* The extension is optional: if no toolchain is available (or
  ``MIRBFT_TPU_NATIVE=0``), everything runs pure-Python.
* Built with a direct ``g++`` invocation (no setuptools machinery, no
  pybind11 — neither is guaranteed in the image); the .so is cached next to
  the source and rebuilt when the source is newer.

Sanitizer lane: with ``MIRBFT_TPU_SANITIZE=address[,undefined]`` set, both
extensions build with the requested ``-fsanitize=`` instrumentation into
``_native/sanitized/`` and load from there, so the whole native plane —
including the PDES differential tests — runs against instrumented code.
The hosting python is not ASan-built, so the caller must put the sanitizer
runtime first in the process (``LD_PRELOAD``); ``sanitizer_preload()``
below names the library, and ``tools/build_native.py --sanitize=...``
prints a ready-to-paste invocation (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
from typing import Optional, Sequence, Tuple

available = False
core = None
fast_available = False
fast = None

_HERE = os.path.dirname(__file__)
_SAN_DIR = os.path.join(_HERE, "sanitized")
_SRC = os.path.join(_HERE, "ackplane.cpp")
_SO = os.path.join(_HERE, "_core.so")
_FAST_SRC = os.path.join(_HERE, "fastengine.cpp")
_FAST_SO = os.path.join(_HERE, "_fast.so")

SANITIZERS = ("address", "undefined")


def sanitizers_from_env() -> Tuple[str, ...]:
    """The ``MIRBFT_TPU_SANITIZE`` selection, validated."""
    raw = os.environ.get("MIRBFT_TPU_SANITIZE", "")
    selected = tuple(s.strip() for s in raw.split(",") if s.strip())
    unknown = set(selected) - set(SANITIZERS)
    if unknown:
        raise ValueError(
            f"MIRBFT_TPU_SANITIZE names unknown sanitizers {sorted(unknown)}; "
            f"supported: {', '.join(SANITIZERS)}"
        )
    return selected


def _flags(sanitizers: Sequence[str] = ()) -> list:
    flags = ["-std=c++17", "-shared", "-fPIC", "-pthread"]
    if sanitizers:
        # -O1 keeps stack traces honest; frame pointers make them cheap.
        flags += [
            "-O1",
            "-g",
            "-fno-omit-frame-pointer",
            f"-fsanitize={','.join(sanitizers)}",
        ]
    else:
        flags.append("-O2")
    return flags


def sanitizer_preload(sanitizers: Sequence[str]) -> Optional[str]:
    """The runtime library a non-instrumented python must LD_PRELOAD to
    host an instrumented extension (ASan insists on being loaded first;
    libubsan rides along as an ordinary dependency of the .so)."""
    if "address" not in sanitizers:
        return None
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            check=True,
            capture_output=True,
            text=True,
            timeout=30,
        ).stdout.strip()
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    return out if os.path.isabs(out) else None


def sanitized_so_path(so: str) -> str:
    return os.path.join(_SAN_DIR, os.path.basename(so))


def _build(src: str, so: str, sanitizers: Sequence[str] = ()) -> bool:
    include = sysconfig.get_paths()["include"]
    os.makedirs(os.path.dirname(so), exist_ok=True)
    tmp = so + ".tmp"
    cmd = ["g++", *_flags(sanitizers), "-I", include, src, "-o", tmp]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=600
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False
    os.replace(tmp, so)  # atomic: concurrent builders race benignly
    return True


def _import_so(modname: str, so: str):
    """Import an extension module, from the package directory (normal
    import) or from an arbitrary path (sanitized artifacts — the PyInit
    symbol comes from the last dotted component, so the qualified name
    must keep the ``_core``/``_fast`` tail)."""
    import importlib
    import importlib.util

    qualname = f"{__name__}.{modname}"
    if os.path.dirname(so) == _HERE:
        return importlib.import_module(qualname)
    spec = importlib.util.spec_from_file_location(qualname, so)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {so}")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    sys.modules[qualname] = module
    return module


def _load_one(
    src: str, so: str, modname: str, sanitizers: Sequence[str] = ()
):
    """Build (if stale) and import one extension; returns the module or None."""
    try:
        needs_build = (not os.path.exists(so)) or (
            os.path.getmtime(src) > os.path.getmtime(so)
        )
    except OSError:
        needs_build = True
    if needs_build and not _build(src, so, sanitizers):
        return None
    try:
        return _import_so(modname, so)
    except ImportError:
        # A stale ABI-incompatible artifact: rebuild once.
        if not _build(src, so, sanitizers):
            return None
        try:
            return _import_so(modname, so)
        except ImportError:
            return None


def build_sanitized(
    sanitizers: Sequence[str], force: bool = False
) -> dict:
    """Build both extensions with instrumentation into
    ``_native/sanitized/``; returns {modname: so_path or None}."""
    out = {}
    for src, so, modname in (
        (_SRC, _SO, "_core"),
        (_FAST_SRC, _FAST_SO, "_fast"),
    ):
        target = sanitized_so_path(so)
        stale = force or (not os.path.exists(target)) or (
            os.path.getmtime(src) > os.path.getmtime(target)
        )
        if stale and not _build(src, target, sanitizers):
            out[modname] = None
        else:
            out[modname] = target
    return out


def _load() -> None:
    global available, core
    if os.environ.get("MIRBFT_TPU_NATIVE", "1") == "0":
        return
    sanitizers = sanitizers_from_env()
    so = sanitized_so_path(_SO) if sanitizers else _SO
    core = _load_one(_SRC, so, "_core", sanitizers)
    available = core is not None


_fast_attempted = False


def load_fast():
    """Build/load the fast-engine extension on first use (lazy: a cold
    compile of fastengine.cpp takes ~35 s, which plain package importers —
    tests of unrelated modules, the graft entry compile check — should not
    pay).  Returns the module or None."""
    global fast, fast_available, _fast_attempted
    if _fast_attempted:
        return fast
    _fast_attempted = True
    if os.environ.get("MIRBFT_TPU_NATIVE", "1") == "0":
        return None
    sanitizers = sanitizers_from_env()
    so = sanitized_so_path(_FAST_SO) if sanitizers else _FAST_SO
    fast = _load_one(_FAST_SRC, so, "_fast", sanitizers)
    fast_available = fast is not None
    return fast


_load()
