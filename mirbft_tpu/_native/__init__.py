"""Native (C++) hot-path planes, built on first import.

The consensus state machine stays in Python (branchy protocol logic — see
SURVEY.md §7), but the per-message vote-accumulation hot loops run O(N²)
times per request cluster-wide and dominate wall-clock at 64+ replicas, so
they are implemented natively.  Rules of engagement:

* Pure-Python equivalents remain in ``mirbft_tpu/statemachine/`` and are the
  semantic reference; differential tests assert byte-identical behavior.
* The extension is optional: if no toolchain is available (or
  ``MIRBFT_TPU_NATIVE=0``), everything runs pure-Python.
* Built with a direct ``g++`` invocation (no setuptools machinery, no
  pybind11 — neither is guaranteed in the image); the .so is cached next to
  the source and rebuilt when the source is newer.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

available = False
core = None

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "ackplane.cpp")
_SO = os.path.join(_HERE, "_core.so")


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    tmp = _SO + ".tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        "-I", include, _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False
    os.replace(tmp, _SO)  # atomic: concurrent builders race benignly
    return True


def _load() -> None:
    global available, core
    if os.environ.get("MIRBFT_TPU_NATIVE", "1") == "0":
        return
    try:
        needs_build = (not os.path.exists(_SO)) or (
            os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
    except OSError:
        needs_build = True
    if needs_build and not _build():
        return
    try:
        from . import _core as _core_mod  # type: ignore
    except ImportError:
        # A stale ABI-incompatible artifact: rebuild once.
        if not _build():
            return
        try:
            from . import _core as _core_mod  # type: ignore
        except ImportError:
            return
    core = _core_mod
    available = True


_load()
