// Native fast-path cluster engine: a C++ twin of the Python testengine
// (mirbft_tpu/testengine/{recorder,queue}.py + the state machine under
// mirbft_tpu/statemachine/) for the protocol's green envelope.
//
// Purpose (docs/PERFORMANCE.md §5 "Roadmap to 100k", step 1): the simulated
// 64-replica cluster is host-bound in the Python interpreter at ~44 us per
// replica-request with no single hot loop left.  This engine moves the WHOLE
// steady-state simulation — event queue, scheduling, and every green-path
// protocol component — into C++, leaving Python in charge of configuration,
// device-crypto waves, and everything outside the envelope.
//
// Equivalence contract (enforced by tests/test_fastengine.py):
//   The engine is a BIT-IDENTICAL twin of the Python engine on supported
//   configs: same simulation step counts, same fake-time, same per-node app
//   hash chains, same checkpoint sequence/values, same epoch numbers, same
//   committed-request maps.  Every method below is a faithful transcription
//   of its Python counterpart (cited by file/class); any divergence is a bug.
//
// Supported envelope (outside it, construction or stepping raises
// RuntimeError and callers fall back to the Python engine; the single
// source of truth is docs/FastEngine.md):
//   * <= 256 nodes (4-word replica bitmasks), dense ids 0..n-1
//   * all five DSL mangler actions (drop/jitter/duplicate/delay/
//     crash-and-restart) under For/Until/After with the full matcher set,
//     via a CPython-compatible MT19937 stream (PyRandom above), plus the
//     send-side structured DropMessages
//   * restarts (crash-and-restart WAL recovery, mid-epoch resume) and
//     state transfer (incl. app-level failure injection + retry backoff)
//   * signed-request mode via precomputed verdicts (the device auth plane
//     verifies envelopes; the engine consumes the verdict bitmap)
//   * reconfiguration at checkpoint boundaries (add/remove client, new
//     config changing bucket count / max epoch length), incl. crashes
//     across the FEntry boundary — nodes, f, and checkpoint interval
//     must stay unchanged
//   * still outside: reconfiguration changing nodes/f/checkpoint-interval;
//     device-paced modes combined with a consume-time (generic) mangler;
//     defer_unready crypto
//
// Device crypto: protocol digests are SHA-256 over the same bytes either
// way, so the engine hashes inline (host) and mirrors every wave-eligible
// message (same rule as testengine/crypto.py::_host_fast's complement) into
// a wave log; the Python wrapper dispatches those waves to the TPU hasher
// asynchronously during the run and verifies the device digests match.
//
// CPython C API only (no pybind11 in the image) — same build scheme as
// ackplane.cpp.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <x86intrin.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <queue>
#include <set>
#include <shared_mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using std::deque;
using std::map;
using std::set;
using std::shared_ptr;
using std::string;
using std::vector;
using i32 = int32_t;
using i64 = int64_t;
using u8 = uint8_t;
using u32 = uint32_t;
using u64 = uint64_t;

// Process-wide profiling counters (0 ackbatch, 1 votes, 2 fixpoint,
// 3 coalesce): cumulative across all engines — never dangle, safe under
// concurrent engines (relaxed atomics; profiling only).
std::atomic<u64> g_parts[6] = {};

// CPython-compatible Mersenne Twister (MT19937, Matsumoto & Nishimura's
// public reference algorithm with init_by_array seeding — the exact scheme
// CPython's random.Random uses for int seeds).  The generic manglers draw
// one 62-bit value per first-touch event consumption, exactly like the
// Python engine's ``rand.getrandbits(62)`` (testengine/queue.py), so the
// random streams — and with them jitter/duplicate/percent decisions — are
// bit-identical across engines.
struct PyRandom {
    u32 mt[624];
    int mti = 625;

    void init_genrand(u32 s) {
        mt[0] = s;
        for (mti = 1; mti < 624; mti++)
            mt[mti] = 1812433253u * (mt[mti - 1] ^ (mt[mti - 1] >> 30)) +
                      (u32)mti;
    }

    void init_by_array(const std::vector<u32> &key) {
        init_genrand(19650218u);
        int i = 1, j = 0;
        int k = 624 > (int)key.size() ? 624 : (int)key.size();
        for (; k; k--) {
            mt[i] = (mt[i] ^ ((mt[i - 1] ^ (mt[i - 1] >> 30)) * 1664525u)) +
                    key[(size_t)j] + (u32)j;
            i++;
            j++;
            if (i >= 624) {
                mt[0] = mt[623];
                i = 1;
            }
            if (j >= (int)key.size()) j = 0;
        }
        for (k = 623; k; k--) {
            mt[i] =
                (mt[i] ^ ((mt[i - 1] ^ (mt[i - 1] >> 30)) * 1566083941u)) -
                (u32)i;
            i++;
            if (i >= 624) {
                mt[0] = mt[623];
                i = 1;
            }
        }
        mt[0] = 0x80000000u;
        mti = 624;
    }

    // CPython random_seed(int): the absolute seed split into 32-bit words,
    // least-significant first; seed 0 keys on [0].
    void seed_from_u64(u64 n) {
        std::vector<u32> key;
        if (n == 0) key.push_back(0);
        while (n) {
            key.push_back((u32)(n & 0xffffffffu));
            n >>= 32;
        }
        init_by_array(key);
    }

    u32 genrand() {
        static const u32 mag01[2] = {0u, 0x9908b0dfu};
        u32 y;
        if (mti >= 624) {
            int kk;
            for (kk = 0; kk < 624 - 397; kk++) {
                y = (mt[kk] & 0x80000000u) | (mt[kk + 1] & 0x7fffffffu);
                mt[kk] = mt[kk + 397] ^ (y >> 1) ^ mag01[y & 1u];
            }
            for (; kk < 623; kk++) {
                y = (mt[kk] & 0x80000000u) | (mt[kk + 1] & 0x7fffffffu);
                mt[kk] = mt[kk + (397 - 624)] ^ (y >> 1) ^ mag01[y & 1u];
            }
            y = (mt[623] & 0x80000000u) | (mt[0] & 0x7fffffffu);
            mt[623] = mt[396] ^ (y >> 1) ^ mag01[y & 1u];
            mti = 0;
        }
        y = mt[mti++];
        y ^= (y >> 11);
        y ^= (y << 7) & 0x9d2c5680u;
        y ^= (y << 15) & 0xefc60000u;
        y ^= (y >> 18);
        return y;
    }

    // CPython getrandbits(62): two words, least-significant first, the
    // second shifted down to its remaining 30 bits.
    u64 getrandbits62() {
        u32 lo = genrand();
        u32 hi = genrand() >> 2;
        return (u64)lo | ((u64)hi << 32);
    }
};

struct EngineError : std::runtime_error {
    explicit EngineError(const string &what) : std::runtime_error(what) {}
};

// ---------------------------------------------------------------------------
// SHA-256 (streaming; standard FIPS 180-4 implementation).
// ---------------------------------------------------------------------------

struct Sha256 {
    u32 h[8];
    u64 len = 0;
    u8 buf[64];
    size_t buflen = 0;

    Sha256() { reset(); }

    void reset() {
        static const u32 iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                  0xa54ff53a, 0x510e527f, 0x9b05688c,
                                  0x1f83d9ab, 0x5be0cd19};
        std::memcpy(h, iv, sizeof(iv));
        len = 0;
        buflen = 0;
    }

    static u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }

    void block(const u8 *p) {
        static const u32 K[64] = {
            0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
            0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
            0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
            0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
            0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
            0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
            0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
            0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
            0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
            0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
            0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
            0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
            0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
        u32 w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (u32(p[i * 4]) << 24) | (u32(p[i * 4 + 1]) << 16) |
                   (u32(p[i * 4 + 2]) << 8) | u32(p[i * 4 + 3]);
        for (int i = 16; i < 64; i++) {
            u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        u32 a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
            g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            u32 ch = (e & f) ^ (~e & g);
            u32 t1 = hh + S1 + ch + K[i] + w[i];
            u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            u32 maj = (a & b) ^ (a & c) ^ (b & c);
            u32 t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const void *data, size_t n) {
        const u8 *p = (const u8 *)data;
        len += n;
        if (buflen) {
            size_t take = std::min(n, 64 - buflen);
            std::memcpy(buf + buflen, p, take);
            buflen += take;
            p += take;
            n -= take;
            if (buflen == 64) {
                block(buf);
                buflen = 0;
            }
        }
        while (n >= 64) {
            block(p);
            p += 64;
            n -= 64;
        }
        if (n) {
            std::memcpy(buf, p, n);
            buflen = n;
        }
    }

    void update(const string &s) { update(s.data(), s.size()); }

    // Non-destructive finalize (Python hashlib .digest() semantics).
    string digest() const {
        Sha256 c = *this;
        u64 bits = c.len * 8;
        u8 pad = 0x80;
        c.update(&pad, 1);
        u8 zero = 0;
        while (c.buflen != 56) c.update(&zero, 1);
        u8 lb[8];
        for (int i = 0; i < 8; i++) lb[i] = (u8)(bits >> (56 - 8 * i));
        c.update(lb, 8);
        string out(32, '\0');
        for (int i = 0; i < 8; i++) {
            out[i * 4] = (char)(c.h[i] >> 24);
            out[i * 4 + 1] = (char)(c.h[i] >> 16);
            out[i * 4 + 2] = (char)(c.h[i] >> 8);
            out[i * 4 + 3] = (char)(c.h[i]);
        }
        return out;
    }
};

string sha256(const string &data) {
    Sha256 h;
    h.update(data);
    return h.digest();
}

// ---------------------------------------------------------------------------
// Byte-string interner: digests / checkpoint values / payloads by id.
// id 0 is always the empty string (the null digest).
// ---------------------------------------------------------------------------

struct Interner {
    // deque: element addresses are stable under growth, so get() may hand
    // out references that stay valid across later put()s — including, in
    // threaded PDES runs, references taken under the shared lock and used
    // after it is released.
    std::deque<string> vals;
    std::unordered_map<string, i32> ids;
    // Set only for the duration of a THREADED PdES run; serial runs (and
    // the sequential engine) stay lock-free.
    std::shared_mutex *mu = nullptr;

    Interner() { vals.push_back(string()); ids.emplace(string(), 0); }

    i32 put(const string &s) {
        if (mu) {
            std::unique_lock<std::shared_mutex> lk(*mu);
            return put_unlocked(s);
        }
        return put_unlocked(s);
    }

    i32 put_unlocked(const string &s) {
        auto it = ids.find(s);
        if (it != ids.end()) return it->second;
        i32 id = (i32)vals.size();
        vals.push_back(s);
        ids.emplace(s, id);
        return id;
    }

    const string &get(i32 id) const {
        if (mu) {
            std::shared_lock<std::shared_mutex> lk(*mu);
            return vals[(size_t)id];
        }
        return vals[(size_t)id];
    }
};

// ---------------------------------------------------------------------------
// Schema structs (mirbft_tpu/messages.py).  Digests and opaque byte values
// are interner ids.
// ---------------------------------------------------------------------------

struct AckS {
    i64 client;
    i64 reqno;
    i32 dig;
    bool operator==(const AckS &o) const {
        return client == o.client && reqno == o.reqno && dig == o.dig;
    }
    bool operator<(const AckS &o) const {
        if (client != o.client) return client < o.client;
        if (reqno != o.reqno) return reqno < o.reqno;
        return dig < o.dig;
    }
};

struct AckHash {
    size_t operator()(const AckS &a) const {
        u64 x = (u64)a.client * 0x9e3779b97f4a7c15ULL;
        x ^= (u64)a.reqno + 0x9e3779b97f4a7c15ULL + (x << 6) + (x >> 2);
        x ^= (u64)(u32)a.dig + (x << 6) + (x >> 2);
        return (size_t)x;
    }
};

struct ClientStateS {
    i64 id, width, wclc, lw;
    string mask;
    bool operator==(const ClientStateS &o) const {
        return id == o.id && width == o.width && wclc == o.wclc &&
               lw == o.lw && mask == o.mask;
    }
};

struct NetConfigS {
    vector<i32> nodes;
    i64 ci, mel, nb, f;
    bool operator==(const NetConfigS &o) const {
        return nodes == o.nodes && ci == o.ci && mel == o.mel &&
               nb == o.nb && f == o.f;
    }
};
using NetCfgP = shared_ptr<const NetConfigS>;

// Reconfiguration variants (messages.py ReconfigNewClient/RemoveClient/
// NewConfig).  Engine envelope: a NewConfig may change number_of_buckets
// and max_epoch_length only — the node set, f, and checkpoint_interval are
// fixed engine-wide (enforced at construction; anything else falls back to
// the Python engine).
struct ReconfigS {
    enum RT : u8 { NewClient, RemoveClient, NewConfig } t;
    i64 id = 0, width = 0;  // NewClient / RemoveClient
    NetCfgP config;         // NewConfig
};

struct NetStateS {
    NetCfgP config;  // always set (the active consensused config)
    vector<ClientStateS> clients;
    vector<ReconfigS> pending;  // pending_reconfigurations
};
using NetStateP = shared_ptr<const NetStateS>;

struct EpochCfgS {
    i64 number;
    vector<i32> leaders;
    i64 planned_expiration;
    bool operator==(const EpochCfgS &o) const {
        return number == o.number && leaders == o.leaders &&
               planned_expiration == o.planned_expiration;
    }
};

struct NewEpochCfgS {
    EpochCfgS config;
    i64 cp_seq;
    i32 cp_value;
    vector<i32> final_preprepares;
    bool operator==(const NewEpochCfgS &o) const {
        return config == o.config && cp_seq == o.cp_seq &&
               cp_value == o.cp_value &&
               final_preprepares == o.final_preprepares;
    }
};
using NewEpochCfgP = shared_ptr<const NewEpochCfgS>;

struct ECSetEntryS {
    i64 epoch, seq;
    i32 dig;
};

struct EpochChangeS {
    i64 new_epoch;
    vector<std::pair<i64, i32>> checkpoints;  // (seq_no, value id)
    vector<ECSetEntryS> p_set, q_set;
    // Hash-data caches (lazily built; shared by every receiver of the same
    // broadcast EC object).  At 128+ nodes the hash data spans thousands of
    // parts per message — rebuilding it per ack was the dominant cost of
    // cascaded view changes.
    mutable string hash_joined_cache;  // plain concat (the digest preimage)
    mutable string hash_key_cache;     // length-prefixed join (memo key)
    mutable bool hash_cache_done = false;
};
using EpochChangeP = shared_ptr<const EpochChangeS>;

enum class MT : u8 {
    Preprepare, Prepare, Commit, Checkpoint, Suspect,
    EpochChange, EpochChangeAck, NewEpoch, NewEpochEcho, NewEpochReady,
    FetchBatch, ForwardBatch, FetchRequest, AckMsg, AckBatch, MsgBatch,
};

struct MsgS;
using MsgP = shared_ptr<const MsgS>;

struct MsgS {
    MT t;
    i64 seq = 0, epoch = 0;
    i32 dig = 0;              // Prepare/Commit digest, Checkpoint value, Fetch*/Forward* digest
    vector<AckS> acks;        // Preprepare batch / AckBatch / ForwardBatch; AckMsg+FetchRequest use acks[0]
    EpochChangeP ec;          // EpochChange / EpochChangeAck
    i32 originator = 0;       // EpochChangeAck
    NewEpochCfgP necfg;       // NewEpoch / Echo / Ready
    vector<std::pair<i32, i32>> remote_changes;  // NewEpoch (node_id, digest)
    vector<MsgP> inner;       // MsgBatch
    mutable i64 wire_size_cache = -1;
    // Ack-wave ledger registration id (AckBatch/AckMsg broadcast sends
    // only; -1 = unregistered, consumed via the classic per-ack path).
    mutable i64 wave_id = -1;
};

// QEntry / PEntry and the persisted-entry union (messages.py Persistent).
struct QEntryS {
    i64 seq;
    i32 dig;
    vector<AckS> reqs;
};
using QEntryP = shared_ptr<const QEntryS>;

enum class PET : u8 { Q, P, C, N, F, EC, Suspect, T };

struct PersistEntS {
    PET t;
    QEntryP q;                 // Q
    i64 seq = 0;               // P / C / N / T
    i32 dig = 0;               // P digest / C value / T value
    NetStateP netstate;        // C
    EpochCfgS epoch_config;    // N / F
    i64 num = 0;               // EC epoch_number / Suspect epoch
};
using PersistEntP = shared_ptr<const PersistEntS>;

// ---------------------------------------------------------------------------
// Wire codec (encode only) — must match mirbft_tpu/wire.py byte-for-byte:
// uvarint tags from _REGISTRY_ORDER, fields in dataclass declaration order.
// Used for (a) checkpoint snapshot values, which embed
// wire.encode(NetworkState), and (b) msg_size() buffer accounting.
// ---------------------------------------------------------------------------

enum WireTag : u32 {
    TAG_NetworkConfig = 0, TAG_ClientState = 1, TAG_ReconfigNewClient = 2,
    TAG_ReconfigRemoveClient = 3, TAG_ReconfigNewConfig = 4,
    TAG_NetworkState = 5,
    TAG_RequestAck = 6, TAG_EpochConfig = 8, TAG_CheckpointMsg = 9,
    TAG_EpochChangeSetEntry = 10, TAG_EpochChange = 11,
    TAG_EpochChangeAck = 12, TAG_NewEpochConfig = 13,
    TAG_RemoteEpochChange = 14, TAG_NewEpoch = 15, TAG_Preprepare = 16,
    TAG_Prepare = 17, TAG_Commit = 18, TAG_Suspect = 19,
    TAG_NewEpochEcho = 20, TAG_NewEpochReady = 21, TAG_FetchBatch = 22,
    TAG_ForwardBatch = 23, TAG_FetchRequest = 24, TAG_AckMsg = 26,
    TAG_AckBatch = 60, TAG_MsgBatch = 61,
};

void enc_uv(string &buf, u64 v) {
    while (true) {
        u8 b = v & 0x7f;
        v >>= 7;
        if (v) buf.push_back((char)(b | 0x80));
        else { buf.push_back((char)b); return; }
    }
}

void enc_bytes(string &buf, const string &s) {
    enc_uv(buf, s.size());
    buf.append(s);
}

struct Wire {
    const Interner *in;

    void net_config(string &buf, const NetConfigS &c) const {
        enc_uv(buf, TAG_NetworkConfig);
        enc_uv(buf, c.nodes.size());
        for (i32 n : c.nodes) enc_uv(buf, (u64)n);
        enc_uv(buf, (u64)c.ci);
        enc_uv(buf, (u64)c.mel);
        enc_uv(buf, (u64)c.nb);
        enc_uv(buf, (u64)c.f);
    }

    void client_state(string &buf, const ClientStateS &c) const {
        enc_uv(buf, TAG_ClientState);
        enc_uv(buf, (u64)c.id);
        enc_uv(buf, (u64)c.width);
        enc_uv(buf, (u64)c.wclc);
        enc_uv(buf, (u64)c.lw);
        enc_bytes(buf, c.mask);
    }

    void net_state(string &buf, const NetStateS &s) const {
        enc_uv(buf, TAG_NetworkState);
        net_config(buf, *s.config);
        enc_uv(buf, s.clients.size());
        for (const auto &c : s.clients) client_state(buf, c);
        enc_uv(buf, s.pending.size());
        for (const auto &r : s.pending) {
            if (r.t == ReconfigS::NewClient) {
                enc_uv(buf, TAG_ReconfigNewClient);
                enc_uv(buf, (u64)r.id);
                enc_uv(buf, (u64)r.width);
            } else if (r.t == ReconfigS::RemoveClient) {
                enc_uv(buf, TAG_ReconfigRemoveClient);
                enc_uv(buf, (u64)r.id);
            } else {
                enc_uv(buf, TAG_ReconfigNewConfig);
                net_config(buf, *r.config);
            }
        }
    }

    void ack(string &buf, const AckS &a) const {
        enc_uv(buf, TAG_RequestAck);
        enc_uv(buf, (u64)a.client);
        enc_uv(buf, (u64)a.reqno);
        enc_bytes(buf, in->get(a.dig));
    }

    void epoch_config(string &buf, const EpochCfgS &c) const {
        enc_uv(buf, TAG_EpochConfig);
        enc_uv(buf, (u64)c.number);
        enc_uv(buf, c.leaders.size());
        for (i32 n : c.leaders) enc_uv(buf, (u64)n);
        enc_uv(buf, (u64)c.planned_expiration);
    }

    void checkpoint_msg(string &buf, i64 seq, i32 value) const {
        enc_uv(buf, TAG_CheckpointMsg);
        enc_uv(buf, (u64)seq);
        enc_bytes(buf, in->get(value));
    }

    void ec_set_entry(string &buf, const ECSetEntryS &e) const {
        enc_uv(buf, TAG_EpochChangeSetEntry);
        enc_uv(buf, (u64)e.epoch);
        enc_uv(buf, (u64)e.seq);
        enc_bytes(buf, in->get(e.dig));
    }

    void epoch_change(string &buf, const EpochChangeS &e) const {
        enc_uv(buf, TAG_EpochChange);
        enc_uv(buf, (u64)e.new_epoch);
        enc_uv(buf, e.checkpoints.size());
        for (const auto &cp : e.checkpoints) checkpoint_msg(buf, cp.first, cp.second);
        enc_uv(buf, e.p_set.size());
        for (const auto &p : e.p_set) ec_set_entry(buf, p);
        enc_uv(buf, e.q_set.size());
        for (const auto &q : e.q_set) ec_set_entry(buf, q);
    }

    void new_epoch_config(string &buf, const NewEpochCfgS &c) const {
        enc_uv(buf, TAG_NewEpochConfig);
        epoch_config(buf, c.config);
        checkpoint_msg(buf, c.cp_seq, c.cp_value);
        enc_uv(buf, c.final_preprepares.size());
        for (i32 d : c.final_preprepares) enc_bytes(buf, in->get(d));
    }

    void msg(string &buf, const MsgS &m) const {
        switch (m.t) {
            case MT::Preprepare:
                enc_uv(buf, TAG_Preprepare);
                enc_uv(buf, (u64)m.seq);
                enc_uv(buf, (u64)m.epoch);
                enc_uv(buf, m.acks.size());
                for (const auto &a : m.acks) ack(buf, a);
                break;
            case MT::Prepare:
            case MT::Commit:
                enc_uv(buf, m.t == MT::Prepare ? TAG_Prepare : TAG_Commit);
                enc_uv(buf, (u64)m.seq);
                enc_uv(buf, (u64)m.epoch);
                enc_bytes(buf, in->get(m.dig));
                break;
            case MT::Checkpoint:
                checkpoint_msg(buf, m.seq, m.dig);
                break;
            case MT::Suspect:
                enc_uv(buf, TAG_Suspect);
                enc_uv(buf, (u64)m.epoch);
                break;
            case MT::EpochChange:
                epoch_change(buf, *m.ec);
                break;
            case MT::EpochChangeAck:
                enc_uv(buf, TAG_EpochChangeAck);
                enc_uv(buf, (u64)m.originator);
                epoch_change(buf, *m.ec);
                break;
            case MT::NewEpoch:
                enc_uv(buf, TAG_NewEpoch);
                new_epoch_config(buf, *m.necfg);
                enc_uv(buf, m.remote_changes.size());
                for (const auto &rc : m.remote_changes) {
                    enc_uv(buf, TAG_RemoteEpochChange);
                    enc_uv(buf, (u64)rc.first);
                    enc_bytes(buf, in->get(rc.second));
                }
                break;
            case MT::NewEpochEcho:
            case MT::NewEpochReady:
                enc_uv(buf, m.t == MT::NewEpochEcho ? TAG_NewEpochEcho
                                                    : TAG_NewEpochReady);
                new_epoch_config(buf, *m.necfg);
                break;
            case MT::FetchBatch:
                enc_uv(buf, TAG_FetchBatch);
                enc_uv(buf, (u64)m.seq);
                enc_bytes(buf, in->get(m.dig));
                break;
            case MT::ForwardBatch:
                enc_uv(buf, TAG_ForwardBatch);
                enc_uv(buf, (u64)m.seq);
                enc_uv(buf, m.acks.size());
                for (const auto &a : m.acks) ack(buf, a);
                enc_bytes(buf, in->get(m.dig));
                break;
            case MT::FetchRequest:
                enc_uv(buf, TAG_FetchRequest);
                ack(buf, m.acks[0]);
                break;
            case MT::AckMsg:
                enc_uv(buf, TAG_AckMsg);
                ack(buf, m.acks[0]);
                break;
            case MT::AckBatch:
                enc_uv(buf, TAG_AckBatch);
                enc_uv(buf, m.acks.size());
                for (const auto &a : m.acks) ack(buf, a);
                break;
            case MT::MsgBatch:
                enc_uv(buf, TAG_MsgBatch);
                enc_uv(buf, m.inner.size());
                for (const auto &im : m.inner) msg(buf, *im);
                break;
        }
    }

    i64 msg_size(const MsgS &m) const {
        if (m.wire_size_cache >= 0) return m.wire_size_cache;
        string buf;
        msg(buf, m);
        m.wire_size_cache = (i64)buf.size();
        return m.wire_size_cache;
    }
};

// ---------------------------------------------------------------------------
// Hash origins, actions, events (mirbft_tpu/state.py).
// ---------------------------------------------------------------------------

enum class OT : u8 { Batch, EpochChange, VerifyBatch };

struct HashOriginS {
    OT t;
    i32 source = 0;
    i64 epoch = 0;   // Batch
    i64 seq = 0;     // Batch / VerifyBatch
    vector<AckS> request_acks;  // Batch / VerifyBatch
    i32 origin = 0;             // EpochChange: originating node
    EpochChangeP ec;            // EpochChange
    i32 expected_digest = 0;    // VerifyBatch
};

struct HashReqS {
    vector<string> parts;
    HashOriginS origin;
    // Deep-scan memo (device-authoritative pauses): joined key + state so
    // repeated pauses never re-join or re-probe an action
    // (0 = unjoined, 1 = joined, 2 = settled: host-floor or supplied).
    mutable string scan_join;
    mutable u8 scan_state = 0;
};
using HashReqP = shared_ptr<const HashReqS>;

enum class AT : u8 {
    Send, Hash, Persist, Truncate, Commit, Checkpoint,
    AllocatedRequest, CorrectRequest, ForwardRequest, StateApplied,
    StateTransfer,  // a = seq_no, b = checkpoint value interner id
};

using Targets = shared_ptr<const vector<i32>>;

// One type-erased payload pointer per action (exactly one payload kind is
// ever set per action type), keeping the struct small enough that the
// pervasive batch moves/concats are cheap.
struct ActionS {
    AT t;
    AckS ack{0, 0, 0};          // CorrectRequest / ForwardRequest
    i64 a = 0;                  // Persist/Truncate index; Checkpoint/StateApplied seq; AllocatedRequest client
    i64 b = 0;                  // AllocatedRequest reqno; StateTransfer value id
    Targets targets;            // Send / ForwardRequest
    NetCfgP cfg;                // Checkpoint: the post-checkpoint config
    shared_ptr<const void> payload;  // per-kind (see accessors)

    // kind-checked accessors (type safety rests on the AT tag)
    MsgP msg() const { return std::static_pointer_cast<const MsgS>(payload); }
    const MsgS *msg_raw() const {
        return static_cast<const MsgS *>(payload.get());
    }
    HashReqP hash() const {
        return std::static_pointer_cast<const HashReqS>(payload);
    }
    PersistEntP entry() const {
        return std::static_pointer_cast<const PersistEntS>(payload);
    }
    QEntryP qentry() const {
        return std::static_pointer_cast<const QEntryS>(payload);
    }
    shared_ptr<const vector<ClientStateS>> cstates() const {
        return std::static_pointer_cast<const vector<ClientStateS>>(payload);
    }
    NetStateP netstate() const {
        return std::static_pointer_cast<const NetStateS>(payload);
    }
};

using Actions = vector<ActionS>;

enum class ET : u8 {
    InitialParameters, LoadPersistedEntry, LoadCompleted,
    HashResult, CheckpointResult, RequestPersisted,
    Step, TickElapsed, ActionsReceived,
    StateTransferComplete,  // a = seq, digest = value id, payload = netstate
    StateTransferFailed,    // a = seq, digest = value id
};

// Same slimming as ActionS: one type-erased payload per event.
struct EventS {
    ET t;
    i32 digest = 0;    // HashResult digest; CheckpointResult value; Step source
    i64 a = 0;         // LoadPersistedEntry index; CheckpointResult seq
    AckS ack{0, 0, 0};  // RequestPersisted
    shared_ptr<const void> payload;  // entry / origin / netstate / msg

    PersistEntP entry() const {
        return std::static_pointer_cast<const PersistEntS>(payload);
    }
    shared_ptr<const HashOriginS> origin() const {
        return std::static_pointer_cast<const HashOriginS>(payload);
    }
    NetStateP netstate() const {
        return std::static_pointer_cast<const NetStateS>(payload);
    }
    MsgP msg() const { return std::static_pointer_cast<const MsgS>(payload); }
};

using Events = vector<EventS>;

// ---------------------------------------------------------------------------
// Simulation event queue (testengine/queue.py; no mangler in the envelope).
// ---------------------------------------------------------------------------

struct InitParms {
    i32 id;
    i64 batch_size, heartbeat_ticks, suspect_ticks, new_epoch_timeout_ticks,
        buffer_size;
    // This node consumes the ack ledger's canonical streams only if it was
    // live from the start (a late-started or restarted node misses stream
    // prefixes).
    bool led_classic = false;
};

enum class SK : u8 {
    Initialize, MsgReceived, ClientProposal, Tick,
    ProcessWal, ProcessNet, ProcessHash, ProcessClient, ProcessApp,
    ProcessReqStore, ProcessResult,
};

struct SimEv {
    i64 time;
    i64 ctr;
    // Birth time (PDES runs only; docs/PERFORMANCE.md §7.1).  The
    // sequential engine orders same-time events by a global insertion
    // counter; a partitioned run cannot assign that counter online, but
    // the SAME total order is reproduced by the key (time, bt, ctr) where
    // ``bt`` is the simulated time the event was INSERTED and ``ctr`` is
    // its rank in the global insertion sequence at that birth time
    // (insertions happen in global processing order, which is
    // time-monotone, so (bt, rank-at-bt) increases exactly like the
    // sequential counter).  Ranks are provisional (partition-local,
    // order-preserving) during a window and finalized at the barrier
    // replay.  Sequential runs keep bt == 0 and ctr == counter++, which
    // is the identical order.
    i64 bt = 0;
    SK kind;
    i32 target;
    i32 src = 0;
    MsgP msg;
    i64 client = 0, reqno = 0;
    i32 data = 0;                        // payload interner id (proposal)
    shared_ptr<Actions> actions;         // Process{Wal,Net,Hash,Client,App}
    shared_ptr<Events> events;           // Process{ReqStore,Result}
    // Generic-mangler state: an event already touched by the mangler is
    // delivered as-is on next pop (the Python engine's _mangled id-pin).
    bool mangled = false;
    // Restart parameters carried by a crash-and-restart Initialize event
    // (null on the genesis Initialize, which uses the node's config).
    shared_ptr<const InitParms> init;
};

struct SimEvCmp {
    bool operator()(const SimEv &a, const SimEv &b) const {
        if (a.time != b.time) return a.time > b.time;
        if (a.bt != b.bt) return a.bt > b.bt;
        return a.ctr > b.ctr;
    }
};

// ---------------------------------------------------------------------------
// Generic mangler (testengine/manglers.py compiled by fastengine.py): one
// filter conjunction under a For/Until/After combinator, driving one of the
// five reference actions.  Message-scoped predicates use the same envelope
// expansion as the Python DSL (any bundled message satisfying all of them
// matches; of_type(AckMsg) also matches AckBatch).
// ---------------------------------------------------------------------------

// Epoch/seq extraction mirrors manglers.py _msg_epoch/_msg_seq_no.
inline bool mangler_msg_epoch(const MsgS &m, i64 *out) {
    switch (m.t) {
        case MT::Preprepare:
        case MT::Prepare:
        case MT::Commit:
        case MT::Suspect:
            *out = m.epoch;
            return true;
        case MT::EpochChange:
        case MT::EpochChangeAck:
            *out = m.ec->new_epoch;
            return true;
        case MT::NewEpoch:
        case MT::NewEpochEcho:
        case MT::NewEpochReady:
            *out = m.necfg->config.number;
            return true;
        default:
            return false;
    }
}

inline bool mangler_msg_seq(const MsgS &m, i64 *out) {
    switch (m.t) {
        case MT::Preprepare:
        case MT::Prepare:
        case MT::Commit:
        case MT::Checkpoint:
        case MT::FetchBatch:
        case MT::ForwardBatch:
            *out = m.seq;
            return true;
        default:
            return false;
    }
}

struct MPredD {
    enum K : u8 {
        Msgs, NodeStartup, ClientProposalEv, FromSelf, FromNodes, ToNodes,
        AtPercent, WithSequence, WithEpoch, OfType, FromClient,
    } k;
    vector<i64> ids;    // FromNodes / ToNodes
    i64 value = 0;      // AtPercent / WithSequence / WithEpoch / FromClient
    u32 type_mask = 0;  // OfType: bit per MT value

    bool msg_scoped() const {
        return k == WithSequence || k == WithEpoch || k == OfType;
    }

    bool event_match(u64 r, const SimEv &e) const {
        switch (k) {
            case Msgs:
                return e.kind == SK::MsgReceived;
            case NodeStartup:
                return e.kind == SK::Initialize;
            case ClientProposalEv:
                return e.kind == SK::ClientProposal;
            case FromSelf:
                return e.kind == SK::MsgReceived && e.src == e.target;
            case FromNodes: {
                if (e.kind != SK::MsgReceived || e.src == e.target)
                    return false;
                for (i64 id : ids)
                    if (id == e.src) return true;
                return false;
            }
            case ToNodes: {
                for (i64 id : ids)
                    if (id == e.target) return true;
                return false;
            }
            case AtPercent:
                return (i64)(r % 100) <= value;
            case FromClient:
                return e.kind == SK::ClientProposal && e.client == value;
            default:
                throw EngineError("msg-scoped predicate in event position");
        }
    }

    bool msg_match(const MsgS &m) const {
        switch (k) {
            case WithSequence: {
                i64 seq;
                return mangler_msg_seq(m, &seq) && seq == value;
            }
            case WithEpoch: {
                i64 epoch;
                return mangler_msg_epoch(m, &epoch) && epoch == value;
            }
            case OfType: {
                if (type_mask & (1u << (u32)m.t)) return true;
                // AckBatch is the batched transport form of AckMsg.
                return m.t == MT::AckBatch &&
                       (type_mask & (1u << (u32)MT::AckMsg));
            }
            default:
                throw EngineError("event-scoped predicate in msg position");
        }
    }
};

struct ManglerG {
    enum W : u8 { WFor, WUntil, WAfter } wrap = WFor;
    bool latch = false;
    vector<MPredD> preds;
    enum A : u8 { Drop, Jitter, Duplicate, Delay, CrashRestart } action;
    i64 value = 0;  // jitter/duplicate max, delay amount, crash restart delay
    InitParms restart_parms{};
    PyRandom rng;

    // Does every msg-scoped predicate hold on some single message in the
    // envelope (manglers.py Conditional.matches)?
    bool msg_candidates_match(const MsgS &m,
                              const vector<const MPredD *> &mp) const {
        bool all_ok = true;
        for (const MPredD *p : mp)
            if (!p->msg_match(m)) {
                all_ok = false;
                break;
            }
        if (all_ok) return true;
        if (m.t == MT::MsgBatch)
            for (const auto &inner : m.inner)
                if (msg_candidates_match(*inner, mp)) return true;
        return false;
    }

    bool base_match(u64 r, const SimEv &e) const {
        vector<const MPredD *> msg_preds;
        for (const auto &p : preds) {
            if (p.msg_scoped()) msg_preds.push_back(&p);
            else if (!p.event_match(r, e)) return false;
        }
        if (msg_preds.empty()) return true;
        if (e.kind != SK::MsgReceived) return false;
        return msg_candidates_match(*e.msg, msg_preds);
    }

    bool applies(u64 r, const SimEv &e) {
        if (wrap == WFor) return base_match(r, e);
        if (wrap == WUntil) {
            if (latch || base_match(r, e)) {
                latch = true;
                return false;
            }
            return true;
        }
        // WAfter
        if (latch || base_match(r, e)) {
            latch = true;
            return true;
        }
        return false;
    }
};

struct EventQueue {
    vector<SimEv> heap;
    i64 counter = 0;
    i64 fake_time = 0;
    std::unique_ptr<ManglerG> mangler;  // null = no consume-time mangler
    // Birth-key stamping mode (see SimEv::bt): SEQ is the classic global
    // counter (bt pinned to 0 — today's order, zero change); PDES stamps
    // (bt = insertion fake_time, ctr = *prov++) with a partition-local
    // provisional rank finalized at the window barrier; TAIL stamps
    // (bt = fake_time, ctr = counter++) for the exact-stop sequential
    // tail, whose births never share a bt with window-born events.
    enum Stamp : u8 { SEQ = 0, PDES = 1, TAIL = 2 };
    u8 stamp_mode = SEQ;
    i64 *prov = nullptr;  // PDES provisional rank source (partition-owned)

    size_t size() const { return heap.size(); }

    void insert(SimEv ev) {
        if (ev.time < fake_time) throw EngineError("attempted to modify the past");
        if (stamp_mode == SEQ) {
            ev.bt = 0;
            ev.ctr = counter++;
        } else if (stamp_mode == PDES) {
            ev.bt = fake_time;
            ev.ctr = (*prov)++;
        } else {
            ev.bt = fake_time;
            ev.ctr = counter++;
        }
        heap.push_back(std::move(ev));
        std::push_heap(heap.begin(), heap.end(), SimEvCmp());
    }

    // Insert an event whose (bt, ctr) birth key is already final (barrier
    // delivery of cross-partition messages; heap-merge for the tail).
    void insert_stamped(SimEv ev) {
        heap.push_back(std::move(ev));
        std::push_heap(heap.begin(), heap.end(), SimEvCmp());
    }

    SimEv pop() {
        std::pop_heap(heap.begin(), heap.end(), SimEvCmp());
        SimEv ev = std::move(heap.back());
        heap.pop_back();
        return ev;
    }

    SimEv consume() {
        // First-touch mangling (testengine/queue.py consume): draw one
        // random per unmangled pop, apply the mangler, reinsert its results
        // (each with a fresh FIFO counter — even a pass-through moves to
        // the back of its timestamp group, exactly like the Python engine),
        // and loop.  Mangled events are delivered as-is.
        while (true) {
            if (heap.empty())
                throw EngineError("event queue drained to empty");
            SimEv ev = pop();
            if (!mangler || ev.mangled) {
                fake_time = ev.time;
                return ev;
            }
            u64 r = mangler->rng.getrandbits62();
            if (!mangler->applies(r, ev)) {
                ev.mangled = true;
                insert(std::move(ev));
                continue;
            }
            switch (mangler->action) {
                case ManglerG::Drop:
                    continue;
                case ManglerG::Jitter:
                    ev.time += (i64)(r % (u64)mangler->value);
                    ev.mangled = true;
                    insert(std::move(ev));
                    continue;
                case ManglerG::Duplicate: {
                    SimEv clone = ev;  // shallow: payload pointers shared
                    clone.time += (i64)(r % (u64)mangler->value);
                    ev.mangled = true;
                    clone.mangled = true;
                    insert(std::move(ev));
                    insert(std::move(clone));
                    continue;
                }
                case ManglerG::Delay:
                    ev.time += mangler->value;
                    // remangle: stays unmangled, may be delayed again
                    insert(std::move(ev));
                    continue;
                case ManglerG::CrashRestart: {
                    i64 when = ev.time + mangler->value;
                    ev.mangled = true;
                    insert(std::move(ev));
                    SimEv restart;
                    restart.time = when;
                    restart.kind = SK::Initialize;
                    restart.target = mangler->restart_parms.id;
                    restart.init = std::make_shared<const InitParms>(
                        mangler->restart_parms);
                    restart.mangled = true;
                    insert(std::move(restart));
                    continue;
                }
            }
        }
    }

    void remove_events_for(i32 target) {
        heap.erase(std::remove_if(heap.begin(), heap.end(),
                                  [target](const SimEv &e) {
                                      return e.target == target;
                                  }),
                   heap.end());
        std::make_heap(heap.begin(), heap.end(), SimEvCmp());
    }
};

// ---------------------------------------------------------------------------
// Quorums / bucket math (statemachine/stateless.py).
// ---------------------------------------------------------------------------

// ---------------------------------------------------------------------------
// PDES partition (docs/PERFORMANCE.md §4/§7.1): conservative parallel
// discrete-event simulation over the link-latency lookahead.  Replicas are
// partitioned across workers; each window [T, T+L) is processed partition-
// locally (cross-partition messages cannot arrive inside it, because every
// inter-node delivery pays link_latency >= L), and the barrier replays the
// window's processing order to (a) finalize birth-key ranks, (b) deliver
// cross-partition sends, (c) fold stats and drain-predicate flips in exact
// global order.  Bit-identity contract: identical steps, fake-time, and
// per-node state to the sequential engine (tests/test_fastengine.py).
// ---------------------------------------------------------------------------

struct AckShard;  // per-partition ack-ledger overlay (defined with AckLedger)

struct Partition {
    i32 id = 0;
    EventQueue q;
    // Ledger-on runs: provisional ack-wave registrations made this window
    // (folded into the global ledger in replay order at the barrier).
    std::unique_ptr<AckShard> shard;
    i64 prov_counter = 0;  // provisional birth ranks (monotone, never reset)
    i64 window_start = 0;  // sim-time start of the current window
    i64 prov_base = 0;     // prov_counter at window start (resolve-map base)
    vector<SimEv> outbox;  // cross-partition sends made this window

    // One entry per event processed this window, in partition-local order
    // (which equals global order restricted to this partition).
    struct PLogE {
        i64 time;        // processing time
        i64 bt;          // processed event's birth time
        i64 rank;        // its rank (provisional iff prov)
        i64 prov_start;  // prov counter before processing: the event's
                         // births are prov ids [prov_start, prov_start+births)
        u32 births;
        u8 prov;
    };
    vector<PLogE> plog;

    // Drain-predicate transition candidates (kind 0 = client satisfied,
    // kind 1 = node became drain-ready), resolved/deduped at the barrier.
    struct Flip {
        u32 at;  // plog index of the causing event
        u8 kind;
        i64 id;
    };
    vector<Flip> flips;

    // Queue purges caused by an Initialize processed this window
    // (remove_events_for drops in-flight messages to the booting node).
    // The partition-local removal handles this partition's queue; the
    // barrier uses these markers to drop same-window cross-partition
    // sends to the node whose birth precedes the Initialize globally.
    struct Purge {
        u32 at;   // plog index of the Initialize event
        i32 node;
    };
    vector<Purge> purges;

    // Window stats, folded into the engine at each barrier.
    i64 steps = 0;
    i64 committed_ops = 0;
    u64 crypto_ns = 0;
    u64 work_cycles = 0;
    // Per-node work attribution for traffic-aware repartitioning (indexed
    // by node id; folded into Engine::node_load at each barrier).
    vector<u64> node_cycles;
    // Partition-local hash memos (content-keyed; results content-equal
    // across partitions, so locality only costs duplicate hashing).
    std::unordered_map<string, i32> host_memo;
    std::unordered_map<string, i32> wave_memo;
    string error;  // threaded-mode exception capture
};

struct PdesResult {
    i64 steps = 0;      // exact global step count (flip step or stop_steps)
    i64 fake_time = 0;  // exact simulated time at that step
    i64 flip_step = -1;
    i64 flip_time = -1;
    bool done = false;
    bool timed_out = false;
    i64 windows = 0;
    u64 barrier_cycles = 0;
    u64 barrier_ns = 0;  // steady-clock barrier time (pdes_barrier_seconds)
    u64 sum_part_cycles = 0;
    u64 max_part_cycles = 0;
    i64 tail_steps = 0;
    i64 repartitions = 0;  // traffic-aware rebalances taken at barriers
    i64 lookahead = 0;     // conservative window width W (sim units)
    bool ledger_on = false;  // ack ledger was live (sharded) during the run
};

struct Quorums {
    i64 n, f;
    i64 iq() const { return (n + f + 2) / 2; }
    i64 wq() const { return f + 1; }
};

// is_committed (stateless.py:100): MSB-first committed mask, exact-width window.
bool is_committed(i64 req_no, const ClientStateS &cs) {
    i64 offset = req_no - cs.lw;
    if (offset < 0) return true;
    if (offset >= cs.width) return false;
    size_t byte_index = (size_t)(offset >> 3);
    if (byte_index >= cs.mask.size()) return false;
    return (u8(cs.mask[byte_index]) & (0x80u >> (offset & 7))) != 0;
}

string u64be(u64 v) {
    string s(8, '\0');
    for (int i = 0; i < 8; i++) s[i] = (char)(v >> (56 - 8 * i));
    return s;
}

// Flatten an EpochChange into its canonical hash parts
// (stateless.py epoch_change_hash_data).
vector<string> ec_hash_data(const Interner &in, const EpochChangeS &ec) {
    vector<string> out;
    out.push_back(u64be((u64)ec.new_epoch));
    for (const auto &cp : ec.checkpoints) {
        out.push_back(u64be((u64)cp.first));
        out.push_back(in.get(cp.second));
    }
    for (const auto &e : ec.p_set) {
        out.push_back(u64be((u64)e.epoch));
        out.push_back(u64be((u64)e.seq));
        out.push_back(in.get(e.dig));
    }
    for (const auto &e : ec.q_set) {
        out.push_back(u64be((u64)e.epoch));
        out.push_back(u64be((u64)e.seq));
        out.push_back(in.get(e.dig));
    }
    return out;
}

string join_with_lengths(const vector<string> &parts) {
    string key;
    for (const auto &p : parts) {
        enc_uv(key, p.size());
        key.append(p);
    }
    return key;
}

// ---------------------------------------------------------------------------
// Shared engine context.
// ---------------------------------------------------------------------------

struct AckLedger;  // defined below (cluster-shared ack-wave canon)

struct Ctx {
    Interner intern;
    Wire wire{nullptr};
    NetConfigS cfg;   // the INITIAL network config
    NetCfgP cfg_p;    // shared pointer to the same (for NetState linkage)
    vector<ClientStateS> init_clients;
    i64 iq, wq;
    // Shared broadcast target set: most sends address every node, and the
    // per-send 64-int vector alloc+copy was a measurable share of the run.
    Targets bcast;
    AckLedger *ack_ledger = nullptr;  // null = ledger disabled

    void finish_init() {
        wire.in = &intern;
        cfg_p = std::make_shared<const NetConfigS>(cfg);
        Quorums q{(i64)cfg.nodes.size(), cfg.f};
        iq = q.iq();
        wq = q.wq();
        bcast = std::make_shared<vector<i32>>(cfg.nodes);
    }
};

// Action builder helpers (statemachine/actions.py fluent constructors).
ActionS act_send(Targets targets, MsgP msg) {
    ActionS a; a.t = AT::Send; a.targets = std::move(targets);
    a.payload = std::move(msg); return a;
}
ActionS act_send(vector<i32> targets, MsgP msg) {
    return act_send(std::make_shared<const vector<i32>>(std::move(targets)),
                    std::move(msg));
}
ActionS act_hash(vector<string> parts, HashOriginS origin) {
    ActionS a; a.t = AT::Hash;
    auto hr = std::make_shared<HashReqS>();
    hr->parts = std::move(parts);
    hr->origin = std::move(origin);
    a.payload = std::move(hr); return a;
}
ActionS act_persist(i64 index, PersistEntP entry) {
    ActionS a; a.t = AT::Persist; a.a = index; a.payload = std::move(entry); return a;
}
ActionS act_truncate(i64 index) {
    ActionS a; a.t = AT::Truncate; a.a = index; return a;
}
ActionS act_commit(QEntryP q) {
    ActionS a; a.t = AT::Commit; a.payload = std::move(q); return a;
}
ActionS act_checkpoint(i64 seq, NetCfgP cfg,
                       shared_ptr<const vector<ClientStateS>> cs) {
    ActionS a;
    a.t = AT::Checkpoint;
    a.a = seq;
    a.cfg = std::move(cfg);
    a.payload = std::move(cs);
    return a;
}
ActionS act_allocate(i64 client, i64 reqno) {
    ActionS a; a.t = AT::AllocatedRequest; a.a = client; a.b = reqno; return a;
}
ActionS act_correct(AckS ack) {
    ActionS a; a.t = AT::CorrectRequest; a.ack = ack; return a;
}
ActionS act_forward(vector<i32> targets, AckS ack) {
    ActionS a; a.t = AT::ForwardRequest;
    a.targets = std::make_shared<const vector<i32>>(std::move(targets));
    a.ack = ack; return a;
}
ActionS act_state_transfer(i64 seq, i32 value) {
    ActionS a;
    a.t = AT::StateTransfer;
    a.a = seq;
    a.b = value;
    return a;
}
ActionS act_state_applied(i64 seq, NetStateP ns) {
    ActionS a; a.t = AT::StateApplied; a.a = seq; a.payload = std::move(ns); return a;
}

void concat(Actions &into, Actions &&from) {
    for (auto &a : from) into.push_back(std::move(a));
}

// Replica bitmask over up to 256 nodes (4 u64 words; BASELINE config 5 is
// a 256-replica network).  Bit index == dense node id.
struct Mask {
    u64 w[4] = {0, 0, 0, 0};
    bool test(i64 i) const {
        return (w[(size_t)(i >> 6)] >> (i & 63)) & 1;
    }
    void set(i64 i) { w[(size_t)(i >> 6)] |= 1ull << (i & 63); }
    void clearbit(i64 i) { w[(size_t)(i >> 6)] &= ~(1ull << (i & 63)); }
    // Per-bit atomic variants for masks shared across PDES partition
    // threads (each node only ever flips its own bit, but bits of the
    // same word belong to different threads).
    void set_atomic(i64 i) {
        __atomic_fetch_or(&w[(size_t)(i >> 6)], 1ull << (i & 63),
                          __ATOMIC_RELAXED);
    }
    void clear_atomic(i64 i) {
        __atomic_fetch_and(&w[(size_t)(i >> 6)], ~(1ull << (i & 63)),
                           __ATOMIC_RELAXED);
    }
    i64 count() const {
        return __builtin_popcountll(w[0]) + __builtin_popcountll(w[1]) +
               __builtin_popcountll(w[2]) + __builtin_popcountll(w[3]);
    }
    bool any() const { return (w[0] | w[1] | w[2] | w[3]) != 0; }
    bool operator==(const Mask &o) const {
        return w[0] == o.w[0] && w[1] == o.w[1] && w[2] == o.w[2] &&
               w[3] == o.w[3];
    }
    bool operator!=(const Mask &o) const { return !(*this == o); }
};

// Fill both EC hash caches in one pass (see EpochChangeS).
void ec_fill_hash_cache(const Interner &in, const EpochChangeS &ec) {
    if (ec.hash_cache_done) return;
    vector<string> parts = ec_hash_data(in, ec);
    size_t total = 0;
    for (const auto &p : parts) total += p.size();
    ec.hash_joined_cache.reserve(total);
    ec.hash_key_cache.reserve(total + parts.size() * 9);
    for (const auto &p : parts) {
        ec.hash_joined_cache.append(p);
        enc_uv(ec.hash_key_cache, (u64)p.size());
        ec.hash_key_cache.append(p);
    }
    ec.hash_cache_done = true;
}

vector<i32> mask_to_nodes(const Mask &mask) {
    vector<i32> out;
    for (i32 i = 0; i < 256; i++)
        if (mask.test(i)) out.push_back(i);
    return out;
}

// Message constructors.
MsgP mk_prepare(i64 seq, i64 epoch, i32 dig) {
    auto m = std::make_shared<MsgS>(); m->t = MT::Prepare; m->seq = seq;
    m->epoch = epoch; m->dig = dig; return m;
}
MsgP mk_commit(i64 seq, i64 epoch, i32 dig) {
    auto m = std::make_shared<MsgS>(); m->t = MT::Commit; m->seq = seq;
    m->epoch = epoch; m->dig = dig; return m;
}
MsgP mk_preprepare(i64 seq, i64 epoch, vector<AckS> batch) {
    auto m = std::make_shared<MsgS>(); m->t = MT::Preprepare; m->seq = seq;
    m->epoch = epoch; m->acks = std::move(batch); return m;
}
MsgP mk_ack_msg(AckS a) {
    auto m = std::make_shared<MsgS>(); m->t = MT::AckMsg; m->acks.push_back(a);
    return m;
}
MsgP mk_ack_batch(vector<AckS> acks) {
    auto m = std::make_shared<MsgS>(); m->t = MT::AckBatch;
    m->acks = std::move(acks); return m;
}
MsgP mk_checkpoint_msg(i64 seq, i32 value) {
    auto m = std::make_shared<MsgS>(); m->t = MT::Checkpoint; m->seq = seq;
    m->dig = value; return m;
}
MsgP mk_suspect(i64 epoch) {
    auto m = std::make_shared<MsgS>(); m->t = MT::Suspect; m->epoch = epoch;
    return m;
}
MsgP mk_fetch_request(AckS a) {
    auto m = std::make_shared<MsgS>(); m->t = MT::FetchRequest;
    m->acks.push_back(a); return m;
}
MsgP mk_fetch_batch(i64 seq, i32 dig) {
    auto m = std::make_shared<MsgS>(); m->t = MT::FetchBatch; m->seq = seq;
    m->dig = dig; return m;
}
MsgP mk_forward_batch(i64 seq, vector<AckS> acks, i32 dig) {
    auto m = std::make_shared<MsgS>(); m->t = MT::ForwardBatch; m->seq = seq;
    m->acks = std::move(acks); m->dig = dig; return m;
}

// ---------------------------------------------------------------------------
// Persisted log (statemachine/persisted.py).
// ---------------------------------------------------------------------------

struct PersistedLog {
    i64 next_index = 0;
    vector<std::pair<i64, PersistEntP>> entries;

    void append_initial_load(i64 index, PersistEntP entry) {
        if (!entries.empty()) {
            if (next_index != index)
                throw EngineError("WAL indexes out of order");
        } else {
            next_index = index;
        }
        entries.emplace_back(index, std::move(entry));
        next_index = index + 1;
    }

    Actions append(PersistEntP entry) {
        if (entries.empty())
            throw EngineError("appending to an unseeded log");
        i64 index = next_index;
        entries.emplace_back(index, entry);
        next_index += 1;
        Actions a;
        a.push_back(act_persist(index, std::move(entry)));
        return a;
    }

    Actions truncate(i64 low_watermark) {
        for (size_t pos = 0; pos < entries.size(); pos++) {
            const auto &e = *entries[pos].second;
            if (e.t == PET::C) {
                if (e.seq < low_watermark) continue;
            } else if (e.t == PET::N) {
                if (e.seq <= low_watermark) continue;
            } else {
                continue;
            }
            if (pos == 0) break;
            i64 index = entries[pos].first;
            entries.erase(entries.begin(), entries.begin() + (std::ptrdiff_t)pos);
            Actions a;
            a.push_back(act_truncate(index));
            return a;
        }
        return Actions();
    }

    EpochChangeP construct_epoch_change(i64 new_epoch) const {
        // Pass 1: count PEntries per sequence so only the last one is kept.
        std::unordered_map<i64, i64> p_counts;
        bool have_epoch = false;
        i64 log_epoch = 0;
        for (const auto &pr : entries) {
            if (have_epoch && log_epoch >= new_epoch) break;
            const auto &e = *pr.second;
            if (e.t == PET::P) {
                p_counts[e.seq] += 1;
            } else if (e.t == PET::N) {
                log_epoch = e.epoch_config.number;
                have_epoch = true;
            } else if (e.t == PET::F) {
                log_epoch = e.epoch_config.number;
                have_epoch = true;
            }
        }
        auto ec = std::make_shared<EpochChangeS>();
        ec->new_epoch = new_epoch;
        have_epoch = false;
        log_epoch = 0;
        for (const auto &pr : entries) {
            if (have_epoch && log_epoch >= new_epoch) break;
            const auto &e = *pr.second;
            if (e.t == PET::P) {
                i64 &remaining = p_counts[e.seq];
                if (remaining != 1) {
                    remaining -= 1;
                    continue;
                }
                ec->p_set.push_back(ECSetEntryS{log_epoch, e.seq, e.dig});
            } else if (e.t == PET::Q) {
                ec->q_set.push_back(
                    ECSetEntryS{log_epoch, e.q->seq, e.q->dig});
            } else if (e.t == PET::N || e.t == PET::F) {
                log_epoch = e.epoch_config.number;
                have_epoch = true;
            } else if (e.t == PET::C) {
                ec->checkpoints.emplace_back(e.seq, e.dig);
            }
        }
        return ec;
    }
};

PersistEntP pe_q(QEntryP q) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::Q; e->q = std::move(q); return e;
}
PersistEntP pe_p(i64 seq, i32 dig) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::P; e->seq = seq; e->dig = dig; return e;
}
PersistEntP pe_c(i64 seq, i32 value, NetStateP ns) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::C; e->seq = seq;
    e->dig = value; e->netstate = std::move(ns); return e;
}
PersistEntP pe_n(i64 seq, EpochCfgS cfg) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::N; e->seq = seq;
    e->epoch_config = std::move(cfg); return e;
}
PersistEntP pe_f(EpochCfgS cfg) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::F;
    e->epoch_config = std::move(cfg); return e;
}
PersistEntP pe_ec(i64 num) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::EC; e->num = num; return e;
}
PersistEntP pe_t(i64 seq, i32 value) {
    auto e = std::make_shared<PersistEntS>();
    e->t = PET::T;
    e->seq = seq;
    e->dig = value;
    return e;
}
PersistEntP pe_suspect(i64 epoch) {
    auto e = std::make_shared<PersistEntS>(); e->t = PET::Suspect; e->num = epoch; return e;
}

// ---------------------------------------------------------------------------
// Message buffers (statemachine/msgbuffers.py).
// ---------------------------------------------------------------------------

enum class Applyable : u8 { PAST = 0, CURRENT = 1, FUTURE = 2, INVALID = 3 };

struct NodeBuffer {
    i64 total_size = 0;
    i64 buffer_size;  // my_config.buffer_size
    bool over_capacity() const { return total_size > buffer_size; }
};

struct MsgBuffer {
    deque<std::pair<MsgP, i64>> buffer;
    NodeBuffer *nb = nullptr;
    i64 *group = nullptr;
    const Wire *wire = nullptr;

    void store(MsgP msg) {
        while (nb->over_capacity() && !buffer.empty()) {
            auto old = buffer.front();
            buffer.pop_front();
            if (group) (*group)--;
            nb->total_size -= old.second;
        }
        i64 size = wire->msg_size(*msg);
        buffer.emplace_back(std::move(msg), size);
        if (group) (*group)++;
        nb->total_size += size;
    }

    // next/iterate compact the deque in ONE pass instead of erasing from
    // the middle per removed entry: erase-at-i on a deque is O(n), which
    // turned big-buffer drains (cascading view changes buffer enormous
    // message piles) into O(n^2) wall time.  Kept entries preserve their
    // relative order and apply_fn-appended entries are still visited, so
    // behavior is identical to the erase-based loop.
    template <typename F>
    MsgP next(F &&filter_fn) {
        size_t kept = 0;
        MsgP found;
        size_t i = 0;
        for (; i < buffer.size(); i++) {
            MsgP msg = buffer[i].first;
            i64 size = buffer[i].second;
            Applyable verdict = filter_fn(*msg);
            if (verdict == Applyable::FUTURE) {
                if (kept != i) buffer[kept] = std::move(buffer[i]);
                kept++;
                continue;
            }
            if (group) (*group)--;
            nb->total_size -= size;
            if (verdict == Applyable::CURRENT) {
                found = std::move(msg);
                i++;
                break;
            }
        }
        for (; i < buffer.size(); i++, kept++)
            if (kept != i) buffer[kept] = std::move(buffer[i]);
        buffer.resize(kept);
        return found;
    }

    template <typename F, typename A>
    void iterate(F &&filter_fn, A &&apply_fn) {
        size_t kept = 0;
        for (size_t i = 0; i < buffer.size(); i++) {
            MsgP msg = buffer[i].first;
            i64 size = buffer[i].second;
            Applyable verdict = filter_fn(*msg);
            if (verdict == Applyable::FUTURE) {
                if (kept != i) buffer[kept] = std::move(buffer[i]);
                kept++;
                continue;
            }
            if (group) (*group)--;
            nb->total_size -= size;
            if (verdict == Applyable::CURRENT) apply_fn(std::move(msg));
        }
        buffer.resize(kept);
    }

    bool empty() const { return buffer.empty(); }
    size_t size() const { return buffer.size(); }
};

// Per-node registry of per-peer buffers (NodeBuffers).
struct NodeBuffers {
    std::map<i32, NodeBuffer> node_map;
    i64 buffer_size;

    NodeBuffer *node_buffer(i32 source) {
        auto it = node_map.find(source);
        if (it == node_map.end()) {
            it = node_map.emplace(source, NodeBuffer{0, buffer_size}).first;
        }
        return &it->second;
    }
};

// ---------------------------------------------------------------------------
// Checkpoint agreement tracking (statemachine/checkpoints.py).
// ---------------------------------------------------------------------------

struct Checkpoint {
    i64 seq_no;
    i32 my_id;
    const Ctx *ctx;
    // (value, supporters) insertion-ordered.
    vector<std::pair<i32, vector<i32>>> values;
    i32 committed_value = -1;  // -1 = None
    i32 my_value = -1;
    bool stable = false;

    void apply_checkpoint_msg(i32 source, i32 value) {
        vector<i32> *supporters = nullptr;
        for (auto &pr : values)
            if (pr.first == value) { supporters = &pr.second; break; }
        if (!supporters) {
            values.emplace_back(value, vector<i32>());
            supporters = &values.back().second;
        }
        for (i32 s : *supporters)
            if (s == source) return;  // dedup double-votes (hardening)
        supporters->push_back(source);
        i64 agreements = (i64)supporters->size();

        if (agreements == ctx->wq) committed_value = value;
        if (source == my_id) my_value = value;

        if (my_value >= 0 && committed_value >= 0 && !stable) {
            if (value != committed_value)
                throw EngineError("my checkpoint disagrees with the committed network view");
            if (agreements >= ctx->iq) stable = true;
        }
    }
};

struct CheckpointState_ { enum V { IDLE = 0, GARBAGE_COLLECTABLE = 1 }; };

struct CheckpointTracker {
    int state = CheckpointState_::IDLE;
    PersistedLog *persisted;
    NodeBuffers *node_buffers;
    InitParms my_config;
    const Ctx *ctx;
    std::map<i32, i64> highest_checkpoints;
    std::map<i64, shared_ptr<Checkpoint>> checkpoint_map;
    vector<shared_ptr<Checkpoint>> active_checkpoints;
    std::map<i32, MsgBuffer> msg_buffers;
    bool have_config = false;
    NetCfgP net_cfg;  // from the first CEntry's network state (Python twin)
    // Mid-epoch catch-up trigger (checkpoints.py catch_up_target,
    // docs/Divergences.md #13): seq < 0 = unset.
    i64 catch_up_seq = -1;
    i32 catch_up_value = -1;

    shared_ptr<Checkpoint> checkpoint(i64 seq_no) {
        auto it = checkpoint_map.find(seq_no);
        if (it != checkpoint_map.end()) return it->second;
        auto cp = std::make_shared<Checkpoint>();
        cp->seq_no = seq_no;
        cp->my_id = my_config.id;
        cp->ctx = ctx;
        checkpoint_map.emplace(seq_no, cp);
        return cp;
    }

    i64 high_watermark() const { return active_checkpoints.back()->seq_no; }
    i64 low_watermark() const { return active_checkpoints.front()->seq_no; }

    Applyable filter(const MsgS &msg) const {
        if (msg.seq < active_checkpoints.front()->seq_no) return Applyable::PAST;
        if (msg.seq > high_watermark()) return Applyable::FUTURE;
        return Applyable::CURRENT;
    }

    void reinitialize() {
        auto old_checkpoint_map = std::move(checkpoint_map);
        auto old_msg_buffers = std::move(msg_buffers);

        highest_checkpoints.clear();
        checkpoint_map.clear();
        active_checkpoints.clear();
        msg_buffers.clear();
        have_config = false;
        catch_up_seq = -1;
        catch_up_value = -1;

        for (const auto &pr : persisted->entries) {
            if (pr.second->t != PET::C) continue;
            if (!have_config) {
                have_config = true;
                net_cfg = pr.second->netstate->config;
            }
            auto cp = checkpoint(pr.second->seq);
            cp->apply_checkpoint_msg(my_config.id, pr.second->dig);
            active_checkpoints.push_back(cp);
        }
        if (active_checkpoints.empty())
            throw EngineError("log must contain a CEntry");
        active_checkpoints[0]->stable = true;

        for (i32 node : ctx->cfg.nodes) {
            auto it = old_msg_buffers.find(node);
            if (it != old_msg_buffers.end()) {
                msg_buffers.emplace(node, std::move(it->second));
            } else {
                MsgBuffer mb;
                mb.nb = node_buffers->node_buffer(node);
                mb.wire = &ctx->wire;
                msg_buffers.emplace(node, std::move(mb));
            }
        }

        // Re-apply remembered agreements (commutative).
        for (const auto &pr : old_checkpoint_map) {
            if (pr.first < low_watermark()) continue;
            for (const auto &val : pr.second->values)
                for (i32 node : val.second)
                    apply_checkpoint_msg(node, pr.first, val.first);
        }
        garbage_collect();
    }

    void step(i32 source, MsgP msg) {
        Applyable verdict = filter(*msg);
        if (verdict == Applyable::PAST) return;
        if (verdict == Applyable::FUTURE) msg_buffers.at(source).store(msg);
        apply_checkpoint_msg(source, msg->seq, msg->dig);
    }

    i64 garbage_collect() {
        size_t highest_stable_idx = 0;
        for (size_t i = 0; i < active_checkpoints.size(); i++) {
            if (!active_checkpoints[i]->stable) break;
            highest_stable_idx = i;
        }
        for (size_t i = 0; i < highest_stable_idx; i++)
            checkpoint_map.erase(active_checkpoints[i]->seq_no);
        active_checkpoints.erase(active_checkpoints.begin(),
                                 active_checkpoints.begin() +
                                     (std::ptrdiff_t)highest_stable_idx);

        while (active_checkpoints.size() < 3) {
            i64 next_seq = high_watermark() + net_cfg->ci;
            active_checkpoints.push_back(checkpoint(next_seq));
        }

        for (i32 node : ctx->cfg.nodes) {
            auto &mb = msg_buffers.at(node);
            mb.iterate([this](const MsgS &m) { return filter(m); },
                       [this, node](MsgP m) {
                           apply_checkpoint_msg(node, m->seq, m->dig);
                       });
        }
        state = CheckpointState_::IDLE;
        return active_checkpoints[0]->seq_no;
    }

    void apply_checkpoint_msg(i32 source, i64 seq_no, i32 value) {
        bool above_high = seq_no > high_watermark();
        if (above_high) {
            auto it = highest_checkpoints.find(source);
            if (it == highest_checkpoints.end() || seq_no > it->second)
                highest_checkpoints[source] = seq_no;
            // No early return: above-window agreements keep accumulating
            // so the catch-up trigger can reach f+1 on a value even when
            // sources' first reports straddle different seq_nos
            // (checkpoints.py twin; Divergences.md #13).
        }
        auto cp = checkpoint(seq_no);
        cp->apply_checkpoint_msg(source, value);

        if (above_high && cp->committed_value >= 0) {
            // Weak quorum attests a checkpoint beyond every tracked
            // window: arm the mid-epoch catch-up transfer
            // (docs/Divergences.md #13; checkpoints.py twin).
            if (catch_up_seq < 0 || seq_no > catch_up_seq) {
                catch_up_seq = seq_no;
                catch_up_value = cp->committed_value;
            }
        }

        if (cp->stable && seq_no > low_watermark() && !above_high) {
            state = CheckpointState_::GARBAGE_COLLECTABLE;
            return;
        }
        if (!above_high) return;

        std::set<i64> referenced;
        for (const auto &acp : active_checkpoints) referenced.insert(acp->seq_no);
        for (const auto &pr : highest_checkpoints) referenced.insert(pr.second);
        for (auto it = checkpoint_map.begin(); it != checkpoint_map.end();) {
            if (!referenced.count(it->first)) it = checkpoint_map.erase(it);
            else ++it;
        }
    }
};

// ---------------------------------------------------------------------------
// Ready / available lists (statemachine/client_tracker.py).
// ---------------------------------------------------------------------------

struct ClientReqNoD;  // disseminator's per-(client, req_no) record
using CRNP = shared_ptr<ClientReqNoD>;

template <typename T>
struct AppendList {
    deque<T> consumed;
    deque<T> pending;

    void reset_iterator() {
        for (auto &v : pending) consumed.push_back(std::move(v));
        pending = std::move(consumed);
        consumed.clear();
    }
    bool has_next() const { return !pending.empty(); }
    T next() {
        T v = std::move(pending.front());
        pending.pop_front();
        consumed.push_back(v);
        return v;
    }
    void push_back(T v) { pending.push_back(std::move(v)); }

    template <typename F>
    void garbage_collect(F &&should_remove) {
        deque<T> nc, np;
        for (auto &v : consumed)
            if (!should_remove(v)) nc.push_back(std::move(v));
        for (auto &v : pending)
            if (!should_remove(v)) np.push_back(std::move(v));
        consumed = std::move(nc);
        pending = std::move(np);
    }
};

struct ClientTracker {
    InitParms my_config;
    shared_ptr<AppendList<CRNP>> ready_list;
    shared_ptr<AppendList<AckS>> available_list;
    vector<ClientStateS> client_states;

    void reinitialize(const NetStateS &ns) {
        client_states = ns.clients;
        available_list = std::make_shared<AppendList<AckS>>();
        ready_list = std::make_shared<AppendList<CRNP>>();
    }

    void add_ready(CRNP crn) { ready_list->push_back(std::move(crn)); }
    void add_available(AckS ack) { available_list->push_back(ack); }

    // allocate(): GC both lists against post-checkpoint client states.
    void allocate(const NetStateS &state);
};

// ---------------------------------------------------------------------------
// Batch tracker (statemachine/batch_tracker.py).
// ---------------------------------------------------------------------------

struct BatchRec {
    std::set<i64> observed_for;
    vector<AckS> request_acks;
};

struct BatchTracker {
    std::map<i32, BatchRec> batches_by_digest;
    std::map<i32, vector<i64>> fetch_in_flight;
    PersistedLog *persisted;

    void reinitialize() {
        batches_by_digest.clear();
        fetch_in_flight.clear();
        for (const auto &pr : persisted->entries)
            if (pr.second->t == PET::Q)
                add_batch(pr.second->q->seq, pr.second->q->dig,
                          pr.second->q->reqs);
    }

    void truncate(i64 seq_no) {
        for (auto it = batches_by_digest.begin();
             it != batches_by_digest.end();) {
            auto &b = it->second;
            std::set<i64> keep;
            for (i64 s : b.observed_for)
                if (s >= seq_no) keep.insert(s);
            b.observed_for = std::move(keep);
            if (b.observed_for.empty()) it = batches_by_digest.erase(it);
            else ++it;
        }
    }

    void add_batch(i64 seq_no, i32 digest, const vector<AckS> &request_acks) {
        auto it = batches_by_digest.find(digest);
        if (it == batches_by_digest.end()) {
            it = batches_by_digest.emplace(digest, BatchRec{{}, request_acks})
                     .first;
        }
        it->second.observed_for.insert(seq_no);
        auto fit = fetch_in_flight.find(digest);
        if (fit != fetch_in_flight.end()) {
            for (i64 s : fit->second) it->second.observed_for.insert(s);
            fetch_in_flight.erase(fit);
        }
    }

    Actions fetch_batch(i64 seq_no, i32 digest, vector<i32> sources) {
        auto it = fetch_in_flight.find(digest);
        if (it != fetch_in_flight.end())
            for (i64 s : it->second)
                if (s == seq_no) return Actions();
        fetch_in_flight[digest].push_back(seq_no);
        Actions a;
        a.push_back(act_send(std::move(sources), mk_fetch_batch(seq_no, digest)));
        return a;
    }

    Actions reply_fetch_batch(i32 source, i64 seq_no, i32 digest) {
        auto it = batches_by_digest.find(digest);
        if (it == batches_by_digest.end()) return Actions();
        Actions a;
        a.push_back(act_send({source},
                             mk_forward_batch(seq_no, it->second.request_acks,
                                              digest)));
        return a;
    }

    Actions apply_forward_batch_msg(i32 source, i64 seq_no, i32 digest,
                                    const vector<AckS> &request_acks,
                                    const Interner &in) {
        if (!fetch_in_flight.count(digest)) return Actions();
        vector<string> parts;
        for (const auto &a : request_acks) parts.push_back(in.get(a.dig));
        HashOriginS origin;
        origin.t = OT::VerifyBatch;
        origin.source = source;
        origin.seq = seq_no;
        origin.request_acks = request_acks;
        origin.expected_digest = digest;
        Actions acts;
        acts.push_back(act_hash(std::move(parts), std::move(origin)));
        return acts;
    }

    void apply_verify_batch_hash_result(i32 digest, const HashOriginS &origin) {
        if (origin.expected_digest != digest)
            throw EngineError("forwarded batch hash mismatch (byzantine forwarder)");
        auto it = fetch_in_flight.find(digest);
        if (it == fetch_in_flight.end()) return;
        vector<i64> in_flight = std::move(it->second);
        fetch_in_flight.erase(it);
        auto bit = batches_by_digest.find(digest);
        if (bit == batches_by_digest.end())
            bit = batches_by_digest
                      .emplace(digest, BatchRec{{}, origin.request_acks})
                      .first;
        for (i64 s : in_flight) bit->second.observed_for.insert(s);
    }

    bool has_fetch_in_flight() const { return !fetch_in_flight.empty(); }
    const BatchRec *get_batch(i32 digest) const {
        auto it = batches_by_digest.find(digest);
        return it == batches_by_digest.end() ? nullptr : &it->second;
    }
    BatchRec *get_batch_mut(i32 digest) {
        auto it = batches_by_digest.find(digest);
        return it == batches_by_digest.end() ? nullptr : &it->second;
    }
};

// ---------------------------------------------------------------------------
// Cluster-shared ack-wave ledger.
//
// The O(N²) collapse (round-3 headline work): every AckBatch broadcast is
// applied by all N receivers to near-identical per-(client, req_no) vote
// state.  Instead of replaying the per-ack mask arithmetic N times, the
// engine applies each broadcast ONCE to a canonical per-client record set
// at SEND time (send order == arrival order under the engine envelope's
// uniform link latency — the queue breaks time ties by insertion sequence,
// so every receiver consumes broadcasts in registration order).  Receivers
// then consume each wave segment as a cursor bump plus a replay of the
// precomputed quorum-crossing candidates; all non-crossing acks cost the
// receiver nothing.
//
// Receiver-side asymmetries are handled exactly:
//   * own-ack early application (a node applies its own acks via the
//     self-send short-circuit before the wave's canonical position):
//     per-(client, receiver) `own_early` position sets shift crossing
//     counts by +1 for pending own bits (the `adj` term);
//   * window skew (PAST acks skip, FUTURE acks buffer classically and the
//     record goes copy-on-divergence for that receiver until it retires
//     from the window — divergence is per-record and self-healing);
//   * every non-green entry point (buffered replays, force-acks during
//     epoch fetch, attention/fetch ticks, fetch-request replies)
//     materializes the receiver's private record from the canonical logs
//     first and proceeds on the classic path.
// Per-receiver maps that downstream components read (weak/strong/my
// request maps, committed flags, attention, resend state) are maintained
// classically at the receiver's own instants, so the Proposer/ClientTracker
// interfaces are unchanged and exact.
//
// Reference semantics preserved: client_hash_disseminator.go:806-876 (the
// ack accumulation rules this plane replays canonically).
// ---------------------------------------------------------------------------

// The host-fast floor shared by hash_parts and check_ready (mirrors
// crypto.py::_host_fast's complement): single parts under 512 B stay on
// the host; everything else is wave-eligible device content.
inline bool hash_is_host_floor(const vector<string> &parts) {
    return parts.size() == 1 && parts[0].size() < 512;
}

struct WaveTouch {
    i64 req_no;
    i32 dig;      // digest interner id
    u32 post;     // canonical agreement count after this touch (NEW/DUP)
    u8 kind;      // 0=NEW bit, 1=DUP (same-digest revote), 2=REJECT/no-op
    bool candidate;  // post (or post+1 for adj receivers) can cross a quorum
};

struct WaveSeg {
    i64 client;
    void *canon = nullptr;  // CanonClient* (set at registration; map nodes
                            // have stable addresses)
    u8 src;
    i64 min_reqno, max_reqno;
    u32 ack_start, ack_end;  // slice of the registered msg's acks vector
    vector<WaveTouch> touches;      // in batch order
    vector<u32> candidates;         // indexes into touches
};

struct WaveReg {
    MsgP msg;                 // keeps the acks alive for classic fallback
    u32 pos;                  // global stream position (the wave id)
    i64 min_any, max_any;     // req_no bounds across all segments
    vector<WaveSeg> segs;     // in batch (client-ascending) order
    vector<u32> candidate_segs;  // seg indexes with a non-empty candidate set
    // Lazily-built per-ack singleton msgs shared by every receiver that
    // buffers the ack as FUTURE (saves an alloc + wire-size computation
    // per receiver per ack).
    mutable vector<MsgP> single_msgs;

    const MsgP &single(size_t k) const {
        if (single_msgs.empty()) single_msgs.resize(msg->acks.size());
        if (!single_msgs[k]) single_msgs[k] = mk_ack_msg(msg->acks[k]);
        return single_msgs[k];
    }
};

// Per-receiver cursor over the global ack-wave stream.  Every broadcast
// wave is consumed by every live receiver in registration order; own waves
// (self-send short-circuit) are consumed early and absorbed when the
// cursor reaches their position.
struct LedView {
    u32 version = 0;
    vector<u32> own_early;

    bool consumed(u32 pos) const {
        if (pos < version) return true;
        for (u32 p : own_early)
            if (p == pos) return true;
        return false;
    }
    void absorb() {
        bool moved = true;
        while (moved && !own_early.empty()) {
            moved = false;
            for (size_t i = 0; i < own_early.size(); i++)
                if (own_early[i] == version) {
                    own_early.erase(own_early.begin() + (std::ptrdiff_t)i);
                    version += 1;
                    moved = true;
                    break;
                }
        }
    }
};

struct CanonDig {
    i32 dig;
    Mask mask;
    // (stream position, source) per added bit, in canonical order.
    vector<std::pair<u32, u8>> add_log;

    i32 pos_of(u8 src) const {  // -1 if src never added its bit
        for (const auto &pr : add_log)
            if (pr.second == src) return (i32)pr.first;
        return -1;
    }
};

struct CanonRec {
    i64 req_no;
    Mask non_null;
    vector<std::pair<u32, u8>> nn_log;  // (position, source) per non-null bit
    vector<CanonDig> digs;              // canonical first-sight order
    Mask diverged;                      // receivers on private record state

    CanonDig *find(i32 dig) {
        for (auto &d : digs)
            if (d.dig == dig) return &d;
        return nullptr;
    }
    CanonDig &find_or_create(i32 dig) {
        CanonDig *d = find(dig);
        if (d) return *d;
        digs.push_back(CanonDig{dig});
        return digs.back();
    }
};

struct CanonClient {
    i64 base = -1;            // lowest req_no with a record (set on first touch)
    deque<CanonRec> recs;

    CanonRec *rec(i64 req_no) {
        if (base < 0 || req_no < base) return nullptr;
        i64 off = req_no - base;
        if (off >= (i64)recs.size()) return nullptr;
        return &recs[(size_t)off];
    }
    CanonRec &rec_or_create(i64 req_no) {
        if (base < 0) {
            base = req_no;
            recs.emplace_back();
            recs.back().req_no = req_no;
            return recs.back();
        }
        while (req_no < base) {  // extend downward (defensive; base is the
            recs.emplace_front();  // first-touched req_no, usually 0)
            base -= 1;
            recs.front().req_no = base;
        }
        while ((i64)recs.size() <= req_no - base) {
            recs.emplace_back();
            recs.back().req_no = base + (i64)recs.size() - 1;
        }
        return recs[(size_t)(req_no - base)];
    }
};

struct AckLedger {
    i64 wq, sq;
    deque<WaveReg> waves;  // window [wave_base, wave_base + size)
    u32 wave_base = 0;
    std::map<i64, CanonClient> clients;

    // find-first: under PDES every client is pre-registered at setup, so
    // the concurrent-window path is a pure lookup (operator[]'s insert
    // machinery would be a structural race across partition threads).
    CanonClient &client(i64 id) {
        auto it = clients.find(id);
        if (it != clients.end()) return it->second;
        return clients[id];
    }

    const WaveReg &wave(i64 wave_id) const {
        return waves[(size_t)((u32)wave_id - wave_base)];
    }

    // Bound ledger memory: waves every live receiver's cursor has passed
    // will never be consumed again (buffered replays use fresh singleton
    // msgs), and canonical records below every receiver's low watermark
    // have retired.  Called periodically by the engine.
    void prune(u32 min_version, const std::map<i64, i64> &min_lw) {
        while (wave_base < min_version && !waves.empty()) {
            waves.pop_front();
            wave_base += 1;
        }
        for (auto &pr : clients) {
            auto it = min_lw.find(pr.first);
            if (it == min_lw.end()) continue;
            CanonClient &cc = pr.second;
            while (cc.base >= 0 && cc.base < it->second && !cc.recs.empty() &&
                   !cc.recs.front().diverged.any()) {
                cc.recs.pop_front();
                cc.base += 1;
            }
        }
    }

    bool is_candidate_count(i64 post) const {
        return post == wq - 1 || post == wq || post == sq - 1 || post == sq;
    }

    // Register one broadcast ack msg: apply it to the canonical state
    // (mirroring ClientD::ack_run's accept/dup/reject rules exactly) and
    // record per-touch outcomes for receiver-side replay.
    void register_msg(const MsgP &m, i32 source) {
        if (m->wave_id >= 0) return;
        WaveReg reg;
        reg.msg = m;
        reg.pos = wave_base + (u32)waves.size();
        reg.min_any = INT64_MAX;
        reg.max_any = INT64_MIN;
        const vector<AckS> &acks = m->acks;
        size_t i = 0;
        while (i < acks.size()) {
            i64 client_id = acks[i].client;
            CanonClient &cc = client(client_id);
            WaveSeg seg;
            seg.client = client_id;
            seg.canon = &cc;
            seg.src = (u8)source;
            seg.ack_start = (u32)i;
            seg.min_reqno = acks[i].reqno;
            seg.max_reqno = acks[i].reqno;
            while (i < acks.size() && acks[i].client == client_id) {
                const AckS &a = acks[i];
                if (a.reqno < seg.min_reqno) seg.min_reqno = a.reqno;
                if (a.reqno > seg.max_reqno) seg.max_reqno = a.reqno;
                CanonRec &R = cc.rec_or_create(a.reqno);
                WaveTouch t;
                t.req_no = a.reqno;
                t.dig = a.dig;
                t.post = 0;
                t.candidate = false;
                if (a.dig != 0 && R.non_null.test(source)) {
                    // Source already voted non-null: only a same-digest
                    // revote proceeds (as a DUP); otherwise the vote is
                    // rejected (at most creating an empty candidate entry).
                    CanonDig *ex = R.find(a.dig);
                    if (!ex || !ex->mask.test(source)) {
                        if (!ex) R.digs.push_back(CanonDig{a.dig});
                        t.kind = 2;  // REJECT: no receiver-visible effect
                    } else {
                        t.kind = 1;  // DUP
                        t.post = (u32)ex->mask.count();
                        t.candidate = is_candidate_count((i64)t.post);
                    }
                } else {
                    if (a.dig != 0) {
                        if (!R.non_null.test(source)) {
                            R.non_null.set(source);
                            R.nn_log.emplace_back(reg.pos, (u8)source);
                        }
                    }
                    CanonDig &D = R.find_or_create(a.dig);
                    if (D.mask.test(source)) {
                        t.kind = 1;  // DUP (null revote or same-digest)
                        t.post = (u32)D.mask.count();
                        t.candidate = is_candidate_count((i64)t.post);
                    } else {
                        D.mask.set(source);
                        D.add_log.emplace_back(reg.pos, (u8)source);
                        t.kind = 0;  // NEW
                        t.post = (u32)D.mask.count();
                        t.candidate = is_candidate_count((i64)t.post);
                    }
                }
                if (t.candidate)
                    seg.candidates.push_back((u32)seg.touches.size());
                seg.touches.push_back(t);
                i++;
            }
            seg.ack_end = (u32)i;
            if (seg.min_reqno < reg.min_any) reg.min_any = seg.min_reqno;
            if (seg.max_reqno > reg.max_any) reg.max_any = seg.max_reqno;
            if (!seg.candidates.empty())
                reg.candidate_segs.push_back((u32)reg.segs.size());
            reg.segs.push_back(std::move(seg));
        }
        m->wave_id = (i64)reg.pos;
        waves.push_back(std::move(reg));
    }
};

// ---------------------------------------------------------------------------
// PDES ack-ledger sharding.  Under PDES the global registration order of a
// window's broadcasts is only known at the barrier, so each partition
// registers its own sends into a private overlay with PROVISIONAL wave
// positions (high bit set — never `< version`, so LedView::consumed treats
// them as own-early membership checks).  Only the SENDER consumes a
// provisional wave (the self-send short-circuit, same step): with the
// ledger live the window width is min over ALL directed links, so every
// arrival of a window-sent wave lands in a later window — after the
// barrier has folded the shard into the global ledger in exact replay
// order and remapped the sender's early-consumed position to the final
// one.  The overlay therefore only has to compose with the sender's own
// consumed set; other partitions' same-window registrations are invisible
// by construction, exactly as they are unconsumed in the sequential run.
// ---------------------------------------------------------------------------

constexpr u32 LED_PROV_BIT = 0x80000000u;

struct ShardDig {
    i32 dig;
    Mask mask;                           // this window's new bits only
    vector<std::pair<u32, u8>> add_log;  // provisional positions
};

struct ShardRec {
    Mask non_null;                       // this window's new non-null bits
    vector<std::pair<u32, u8>> nn_log;
    vector<ShardDig> digs;

    ShardDig *find(i32 dig) {
        for (auto &d : digs)
            if (d.dig == dig) return &d;
        return nullptr;
    }
    const ShardDig *find(i32 dig) const {
        for (const auto &d : digs)
            if (d.dig == dig) return &d;
        return nullptr;
    }
};

struct AckShard {
    AckLedger *global = nullptr;
    std::map<std::pair<i64, i64>, ShardRec> recs;  // (client, req_no)
    struct ShardWave {
        WaveReg reg;   // reg.pos is provisional (LED_PROV_BIT | index)
        u32 plog_at;   // partition plog index of the sending step
        i32 src;       // sender node id (fold re-registers + remaps)
    };
    deque<ShardWave> waves;  // deque: reg references stay stable
    size_t foldi = 0;        // barrier fold cursor

    ShardRec *rec(i64 client, i64 req_no) {
        auto it = recs.find({client, req_no});
        return it == recs.end() ? nullptr : &it->second;
    }
    const ShardRec *rec(i64 client, i64 req_no) const {
        auto it = recs.find({client, req_no});
        return it == recs.end() ? nullptr : &it->second;
    }

    void clear() {
        recs.clear();
        waves.clear();
        foldi = 0;
    }

    // Mirror of AckLedger::register_msg against the COMPOSED state
    // (frozen global ledger + this partition's overlay).  kind is exact
    // (it depends only on the source's own bits, which live globally or
    // in this overlay); post/candidate are best-effort and unused — the
    // sender's own-path consumption recounts from the composed add logs,
    // and arrivals only ever consume the fold-time global registration.
    void register_msg_lite(const MsgP &m, i32 source, u32 plog_at) {
        if (m->wave_id >= 0) return;
        ShardWave sw;
        sw.plog_at = plog_at;
        sw.src = source;
        WaveReg &reg = sw.reg;
        reg.msg = m;
        reg.pos = LED_PROV_BIT | (u32)waves.size();
        reg.min_any = INT64_MAX;
        reg.max_any = INT64_MIN;
        const vector<AckS> &acks = m->acks;
        size_t i = 0;
        while (i < acks.size()) {
            i64 client_id = acks[i].client;
            auto cit = global->clients.find(client_id);
            if (cit == global->clients.end())
                throw EngineError("pdes ledger: client not pre-registered");
            CanonClient &cc = cit->second;
            WaveSeg seg;
            seg.client = client_id;
            seg.canon = &cc;
            seg.src = (u8)source;
            seg.ack_start = (u32)i;
            seg.min_reqno = acks[i].reqno;
            seg.max_reqno = acks[i].reqno;
            while (i < acks.size() && acks[i].client == client_id) {
                const AckS &a = acks[i];
                if (a.reqno < seg.min_reqno) seg.min_reqno = a.reqno;
                if (a.reqno > seg.max_reqno) seg.max_reqno = a.reqno;
                CanonRec *RG = cc.rec(a.reqno);  // read-only (frozen)
                ShardRec &S = recs[{client_id, a.reqno}];
                WaveTouch t;
                t.req_no = a.reqno;
                t.dig = a.dig;
                t.post = 0;
                t.candidate = false;
                bool nn_src = (RG && RG->non_null.test(source)) ||
                              S.non_null.test(source);
                CanonDig *DG = RG ? RG->find(a.dig) : nullptr;
                ShardDig *DS = S.find(a.dig);
                bool have_bit = (DG && DG->mask.test(source)) ||
                                (DS && DS->mask.test(source));
                if (a.dig != 0 && nn_src) {
                    if (!have_bit) {
                        if (!DG && !DS) S.digs.push_back(ShardDig{a.dig});
                        t.kind = 2;  // REJECT
                    } else {
                        t.kind = 1;  // DUP
                        t.post = (u32)((DG ? DG->mask.count() : 0) +
                                       (DS ? DS->mask.count() : 0));
                        t.candidate = global->is_candidate_count((i64)t.post);
                    }
                } else {
                    if (a.dig != 0 && !nn_src) {
                        S.non_null.set(source);
                        S.nn_log.emplace_back(reg.pos, (u8)source);
                    }
                    if (have_bit) {
                        t.kind = 1;  // DUP (null revote or same-digest)
                    } else {
                        if (!DS) {
                            S.digs.push_back(ShardDig{a.dig});
                            DS = &S.digs.back();
                        }
                        DS->mask.set(source);
                        DS->add_log.emplace_back(reg.pos, (u8)source);
                        t.kind = 0;  // NEW
                    }
                    t.post = (u32)((DG ? DG->mask.count() : 0) +
                                   (DS ? DS->mask.count() : 0));
                    t.candidate = global->is_candidate_count((i64)t.post);
                }
                if (t.candidate)
                    seg.candidates.push_back((u32)seg.touches.size());
                seg.touches.push_back(t);
                i++;
            }
            seg.ack_end = (u32)i;
            if (seg.min_reqno < reg.min_any) reg.min_any = seg.min_reqno;
            if (seg.max_reqno > reg.max_any) reg.max_any = seg.max_reqno;
            if (!seg.candidates.empty())
                reg.candidate_segs.push_back((u32)reg.segs.size());
            reg.segs.push_back(std::move(seg));
        }
        m->wave_id = (i64)reg.pos;
        waves.push_back(std::move(sw));
    }
};

// ---------------------------------------------------------------------------
// Client request dissemination (statemachine/disseminator.py).
// Vote masks are 4-word Masks (engine envelope: <= 256 nodes).
// ---------------------------------------------------------------------------

constexpr i64 CORRECT_FETCH_TICKS = 4;
constexpr i64 FETCH_TIMEOUT_TICKS = 4;
constexpr i64 ACK_RESEND_TICKS = 20;

struct ClientRequestD {
    AckS ack;
    Mask agreements;
    bool stored = false;
    bool fetching = false;
    i64 ticks_fetching = 0;
    i64 ticks_correct = 0;

    Actions fetch() {
        if (fetching) return Actions();
        fetching = true;
        ticks_fetching = 0;
        Actions a;
        a.push_back(act_send(mask_to_nodes(agreements), mk_fetch_request(ack)));
        return a;
    }
};
using CRP = shared_ptr<ClientRequestD>;

// Small insertion-ordered map digest-id -> value (1-2 entries typical).
template <typename V>
struct SmallDigMap {
    vector<std::pair<i32, V>> items;
    V *get(i32 k) {
        for (auto &pr : items)
            if (pr.first == k) return &pr.second;
        return nullptr;
    }
    const V *get(i32 k) const {
        for (const auto &pr : items)
            if (pr.first == k) return &pr.second;
        return nullptr;
    }
    V &put(i32 k, V v) {
        for (auto &pr : items)
            if (pr.first == k) { pr.second = std::move(v); return pr.second; }
        items.emplace_back(k, std::move(v));
        return items.back().second;
    }
    bool contains(i32 k) const { return get(k) != nullptr; }
    size_t size() const { return items.size(); }
    bool empty() const { return items.empty(); }
};

struct ClientReqNoD {
    i64 client_id, req_no;
    i64 valid_after_seq_no;
    Mask non_null_voters;
    SmallDigMap<CRP> requests;         // all observed candidates
    SmallDigMap<CRP> weak_requests;    // correct
    SmallDigMap<CRP> strong_requests;  // proposable
    SmallDigMap<CRP> my_requests;      // locally persisted
    bool committed = false;
    i64 acks_sent = 0;
    i32 acked_digest = -1;  // -1 = None
    i64 resend_nonce = 0;
    // Digests this receiver has self-applied its own ack for (ledger `adj`
    // bookkeeping; 1 entry normally, 2 after a null promotion).
    vector<i32> self_acked;

    CRP client_req(const AckS &ack) {
        CRP *existing = requests.get(ack.dig);
        if (existing) return *existing;
        auto cr = std::make_shared<ClientRequestD>();
        cr->ack = ack;
        requests.put(ack.dig, cr);
        return cr;
    }

    void apply_new_request(const AckS &ack) {
        if (my_requests.contains(ack.dig)) return;
        CRP req = client_req(ack);
        req->stored = true;
        my_requests.put(ack.dig, req);
    }

    // generate_ack() -> (has_ack, ack) (disseminator.py:215-232).
    bool generate_ack(AckS *out) {
        if (my_requests.empty()) return false;
        if (my_requests.size() == 1) {
            acks_sent = 1;
            CRP req = my_requests.items[0].second;
            acked_digest = req->ack.dig;
            *out = req->ack;
            return true;
        }
        AckS null_ack{client_id, req_no, 0};
        CRP null_req = client_req(null_ack);
        null_req->stored = true;
        my_requests.put(0, null_req);
        acks_sent = 1;
        acked_digest = 0;
        *out = null_ack;
        return true;
    }

    bool needs_attention() const {
        const auto &wr = weak_requests;
        if (wr.empty()) return false;
        if (wr.size() == 1) {
            const CRP &req = wr.items[0].second;
            if (req->fetching) return true;
            return !req->stored;
        }
        if (!my_requests.contains(0)) return true;  // null promotion pending
        for (const auto &pr : wr.items)
            if (pr.second->fetching) return true;
        return false;
    }

    // attention_tick (disseminator.py:270-318); returns promoted.
    bool attention_tick(Actions &actions, const Targets &nodes,
                        const Interner &intern) {
        bool promoted = false;
        if (!my_requests.contains(0) && weak_requests.size() > 1) {
            AckS null_ack{client_id, req_no, 0};
            CRP null_req = client_req(null_ack);
            null_req->stored = true;
            my_requests.put(0, null_req);
            acks_sent = 1;
            acked_digest = 0;
            promoted = true;
            actions.push_back(act_send(nodes, mk_ack_msg(null_ack)));
            actions.push_back(act_correct(null_ack));
        }
        if (weak_requests.size() == 1) {
            CRP req = weak_requests.items[0].second;
            if (!req->stored && !req->fetching) {
                if (req->ticks_correct <= CORRECT_FETCH_TICKS)
                    req->ticks_correct += 1;
                else
                    concat(actions, req->fetch());
            }
        }
        vector<CRP> to_fetch;
        for (auto &pr : weak_requests.items) {
            CRP &req = pr.second;
            if (!req->fetching) continue;
            if (req->ticks_fetching <= FETCH_TIMEOUT_TICKS) {
                req->ticks_fetching += 1;
                continue;
            }
            req->fetching = false;
            to_fetch.push_back(req);
        }
        if (!to_fetch.empty()) {
            // Python: to_fetch.sort(key=digest bytes, reverse=True).
            std::stable_sort(to_fetch.begin(), to_fetch.end(),
                             [&intern](const CRP &a, const CRP &b) {
                                 return intern.get(a->ack.dig) >
                                        intern.get(b->ack.dig);
                             });
            for (auto &req : to_fetch) concat(actions, req->fetch());
        }
        return promoted;
    }
};

struct ClientD {
    const Ctx *ctx = nullptr;
    InitParms my_config;
    ClientTracker *client_tracker = nullptr;
    ClientStateS client_state;
    bool has_state = false;
    i64 high_watermark = 0;
    i64 next_ready_mark = 0;
    i64 next_ack_mark = 0;
    // Dense window [win_base, win_base+win.size()-1] — Python's insertion-
    // ordered dict over an ascending contiguous window.
    i64 win_base = 0;
    deque<CRNP> win;
    i64 tick_count = 0;
    std::set<i64> attention;
    std::map<i64, vector<std::pair<i64, i64>>> resend_schedule;
    i64 resend_seq = 0;
    i64 weak_quorum = 0, strong_quorum = 0;
    // Ack-ledger consumption state (see AckLedger): the receiver's global
    // stream cursor lives on the Disseminator (LedView); this client holds
    // only its classic flag and shared-counter hooks.
    const LedView *led_view = nullptr;
    i64 *led_diverged_total = nullptr;
    i64 *led_classic_count = nullptr;
    // PDES: the owning partition's ledger overlay (slot on the
    // Disseminator, re-pointed every step; null outside PDES windows).
    AckShard *const *led_shard_slot = nullptr;
    bool led_classic = false;
    i64 led_diverged = 0;

    const AckShard *led_shard() const {
        return led_shard_slot ? *led_shard_slot : nullptr;
    }

    // Quorum bookkeeping used during a changed-config rebuild
    // (disseminator.py:234-246 _apply_request_ack).
    void apply_request_ack(ClientReqNoD &crn, i32 source, const AckS &a) {
        if (a.dig != 0) crn.non_null_voters.set(source);
        CRP req = crn.client_req(a);
        req->agreements.set(source);
        i64 count = req->agreements.count();
        if (count < weak_quorum) return;
        crn.weak_requests.put(a.dig, req);
        if (count < strong_quorum) return;
        crn.strong_requests.put(a.dig, req);
    }

    // disseminator.py:162-198 (ClientReqNo.reinitialize, config changed):
    // re-derive quorum sets from remembered agreements, iterating old
    // candidates in sorted-digest-bytes order (the rebuild both reorders
    // the candidate maps and constructs fresh ClientRequests, dropping
    // fetch state; `stored` carries over into fresh my_requests).
    void crn_rebuild(ClientReqNoD &crn) {
        auto old_items = std::move(crn.requests.items);
        crn.requests.items.clear();
        crn.non_null_voters = Mask();
        crn.weak_requests.items.clear();
        crn.strong_requests.items.clear();
        crn.my_requests.items.clear();
        std::stable_sort(old_items.begin(), old_items.end(),
                         [this](const std::pair<i32, CRP> &a,
                                const std::pair<i32, CRP> &b) {
                             return ctx->intern.get(a.first) <
                                    ctx->intern.get(b.first);
                         });
        for (const auto &pr : old_items) {
            const CRP &old_req = pr.second;
            for (i32 node : ctx->cfg.nodes)
                if (old_req->agreements.test(node))
                    apply_request_ack(crn, node, old_req->ack);
            if (old_req->stored) {
                CRP new_req = crn.client_req(old_req->ack);
                new_req->stored = true;
                crn.my_requests.put(pr.first, new_req);
            }
        }
    }

    CRNP win_get(i64 req_no) const {
        i64 off = req_no - win_base;
        if (off < 0 || off >= (i64)win.size()) return nullptr;
        return win[(size_t)off];
    }

    CRNP req_no_of(i64 req_no) {
        CRNP crn = win_get(req_no);
        if (!crn) throw EngineError("client should have req_no");
        return crn;
    }

    bool in_watermarks(i64 req_no) const {
        return client_state.lw <= req_no && req_no <= high_watermark;
    }

    Actions reinitialize(i64 seq_no, i64 client_id,
                         const ClientStateS &state, bool reconfiguring,
                         bool same_config, i64 ci) {
        Actions actions;
        weak_quorum = ctx->wq;
        strong_quorum = ctx->iq;
        if (!same_config)
            // A changed config invalidates the ledger's canonical view of
            // this client (quorum-set rebuild reorders candidate maps):
            // materialize private state and consume classically from here.
            led_fallback_all_classic();
        led_classic = led_classic || my_config.led_classic;
        deque<CRNP> old_win = std::move(win);
        i64 old_base = win_base;
        win.clear();
        // Records dropped below the new low watermark retire their
        // divergence marks (self-healing: fresh records start fast).
        if (!old_win.empty())
            for (i64 rn = old_base; rn < state.lw &&
                                    rn < old_base + (i64)old_win.size();
                 rn++)
                led_release(rn);

        i64 intermediate_high = state.lw + state.width - state.wclc - 1;
        client_state = state;
        has_state = true;
        high_watermark =
            !reconfiguring ? state.lw + state.width - 1 : intermediate_high;
        next_ready_mark = state.lw;
        if (next_ack_mark < state.lw) next_ack_mark = state.lw;

        win_base = state.lw;
        // Config never changes within the engine envelope (same_config=True
        // after the first call; the first call has no prior req_nos at all).
        for (i64 rn = state.lw; rn <= high_watermark; rn++) {
            CRNP crn;
            i64 old_off = rn - old_base;
            if (old_off >= 0 && old_off < (i64)old_win.size() &&
                !old_win.empty()) {
                crn = old_win[(size_t)old_off];
                if (same_config) {
                    // Graceful rotation under an unchanged config: identity
                    // on vote state; only per-candidate fetch state resets.
                    for (auto &pr : crn->requests.items) {
                        pr.second->fetching = false;
                        pr.second->ticks_fetching = 0;
                        pr.second->ticks_correct = 0;
                    }
                } else {
                    crn_rebuild(*crn);
                }
            } else {
                i64 valid_after =
                    rn > intermediate_high ? seq_no + ci : seq_no;
                crn = std::make_shared<ClientReqNoD>();
                crn->client_id = client_id;
                crn->req_no = rn;
                crn->valid_after_seq_no = valid_after;
                actions.push_back(act_allocate(client_id, rn));
            }
            crn->committed = is_committed(rn, state);
            win.push_back(std::move(crn));
        }
        attention.clear();
        for (const auto &crn : win)
            if (!crn->committed && crn->needs_attention())
                attention.insert(crn->req_no);
        advance_ready();
        return actions;
    }

    Actions allocate(i64 seq_no, const ClientStateS &state, bool reconfiguring,
                     i64 ci) {
        Actions actions;
        i64 intermediate_high = state.lw + state.width - state.wclc - 1;
        if (intermediate_high != high_watermark)
            throw EngineError("new intermediate high watermark mismatch");
        i64 new_high =
            !reconfiguring ? state.lw + state.width - 1 : intermediate_high;

        if (state.lw > next_ready_mark) next_ready_mark = state.lw;
        if (state.lw > next_ack_mark) next_ack_mark = state.lw;

        // Drop window prefix below the new low watermark.
        while (!win.empty() && win_base != state.lw) {
            led_release(win_base);
            win.pop_front();
            win_base += 1;
        }
        if (win.empty()) win_base = state.lw;
        for (i64 rn = state.lw; rn <= high_watermark; rn++)
            if (is_committed(rn, state)) req_no_of(rn)->committed = true;

        client_state = state;

        i64 valid_after = seq_no + ci;
        for (i64 rn = intermediate_high + 1; rn <= new_high; rn++) {
            actions.push_back(act_allocate(state.id, rn));
            auto crn = std::make_shared<ClientReqNoD>();
            crn->client_id = state.id;
            crn->req_no = rn;
            crn->valid_after_seq_no = valid_after;
            win.push_back(std::move(crn));
        }
        high_watermark = new_high;
        advance_ready();
        return actions;
    }

    // --- ack-ledger consumption (see AckLedger above) -------------------

    bool led_enabled() const {
        return ctx->ack_ledger != nullptr && !led_classic;
    }

    // Reconstruct this receiver's private per-record vote state from the
    // canonical logs (consumed prefix + own-early positions), then mark
    // the record diverged so every later touch goes the classic path.
    void led_ensure_private(ClientReqNoD &crn) {
        if (!led_enabled()) return;
        CanonClient &cc = ctx->ack_ledger->client(client_state.id);
        CanonRec &R = cc.rec_or_create(crn.req_no);
        if (R.diverged.test(my_config.id)) return;
        const AckShard *sh = led_shard();
        const ShardRec *S = sh ? sh->rec(client_state.id, crn.req_no) : nullptr;
        Mask nn;
        for (const auto &pr : R.nn_log)
            if (led_view->consumed(pr.first)) nn.set(pr.second);
        if (S)
            for (const auto &pr : S->nn_log)
                if (led_view->consumed(pr.first)) nn.set(pr.second);
        crn.non_null_voters = nn;
        for (const auto &D : R.digs) {
            CRP cr = crn.client_req(AckS{crn.client_id, crn.req_no, D.dig});
            Mask m;
            for (const auto &pr : D.add_log)
                if (led_view->consumed(pr.first)) m.set(pr.second);
            if (S)
                if (const ShardDig *DS = S->find(D.dig))
                    for (const auto &pr : DS->add_log)
                        if (led_view->consumed(pr.first)) m.set(pr.second);
            cr->agreements = m;
        }
        if (S)
            for (const auto &DS : S->digs) {
                // Digests first seen this window (canonically AFTER every
                // frozen global dig, so appending preserves sight order).
                bool in_global = false;
                for (const auto &D : R.digs)
                    if (D.dig == DS.dig) in_global = true;
                if (in_global) continue;
                CRP cr =
                    crn.client_req(AckS{crn.client_id, crn.req_no, DS.dig});
                Mask m;
                for (const auto &pr : DS.add_log)
                    if (led_view->consumed(pr.first)) m.set(pr.second);
                cr->agreements = m;
            }
        R.diverged.set_atomic(my_config.id);
        led_diverged += 1;
        if (led_diverged_total) *led_diverged_total += 1;
    }

    void led_release(i64 req_no) {
        if (!led_enabled()) return;
        CanonClient &cc = ctx->ack_ledger->client(client_state.id);
        CanonRec *R = cc.rec(req_no);
        if (R && R->diverged.test(my_config.id)) {
            R->diverged.clear_atomic(my_config.id);
            led_diverged -= 1;
            if (led_diverged_total) *led_diverged_total -= 1;
        }
    }

    // After a window roll replayed this receiver's buffered FUTURE acks,
    // a diverged record whose masks exactly match the canonical view is
    // aligned again — clear the mark so it rides the fast path.  Records
    // diverged for other reasons (force-acks, missing buffered acks) fail
    // the comparison and stay private.  Private fetch/tick state on the
    // CRPs is orthogonal to alignment (the fast path never touches it).
    void led_try_realign() {
        if (!led_enabled() || led_diverged == 0) return;
        CanonClient &cc = ctx->ack_ledger->client(client_state.id);
        const AckShard *sh = led_shard();
        for (const auto &crnp : win) {
            ClientReqNoD &crn = *crnp;
            CanonRec *R = cc.rec(crn.req_no);
            if (!R || !R->diverged.test(my_config.id)) continue;
            const ShardRec *S =
                sh ? sh->rec(client_state.id, crn.req_no) : nullptr;
            Mask nn;
            for (const auto &pr : R->nn_log)
                if (led_view->consumed(pr.first)) nn.set(pr.second);
            if (S)
                for (const auto &pr : S->nn_log)
                    if (led_view->consumed(pr.first)) nn.set(pr.second);
            if (crn.non_null_voters != nn) continue;
            bool equal = true;
            for (const auto &D : R->digs) {
                Mask m;
                for (const auto &pr : D.add_log)
                    if (led_view->consumed(pr.first)) m.set(pr.second);
                if (S)
                    if (const ShardDig *DS = S->find(D.dig))
                        for (const auto &pr : DS->add_log)
                            if (led_view->consumed(pr.first))
                                m.set(pr.second);
                CRP *cr = crn.requests.get(D.dig);
                Mask actual = cr ? (*cr)->agreements : Mask();
                if (actual != m) { equal = false; break; }
            }
            if (equal && S)
                for (const auto &DS : S->digs) {
                    if (R->find(DS.dig)) continue;
                    Mask m;
                    for (const auto &pr : DS.add_log)
                        if (led_view->consumed(pr.first)) m.set(pr.second);
                    CRP *cr = crn.requests.get(DS.dig);
                    Mask actual = cr ? (*cr)->agreements : Mask();
                    if (actual != m) { equal = false; break; }
                }
            if (!equal) continue;
            R->diverged.clear_atomic(my_config.id);
            led_diverged -= 1;
            if (led_diverged_total) *led_diverged_total -= 1;
            if (led_diverged == 0) break;
        }
    }

    // Materialize every in-window record and consume classically forever
    // (safety valve for conditions the fast path does not model).
    void led_fallback_all_classic() {
        if (led_enabled())
            for (const auto &crnp : win) led_ensure_private(*crnp);
        if (!led_classic && led_classic_count) *led_classic_count += 1;
        led_classic = true;
    }

    // Quorum-crossing replay for one candidate touch consumed as an
    // arrival (seg.src != me).  Mirrors ack_run's per-ack body for counts
    // at the quorum edges; all other counts have no receiver-visible
    // effect.  `adj` shifts the canonical count when our own bit for this
    // digest was self-applied early and its canonical position is still
    // ahead of this touch.
    void led_candidate(CanonRec &R, const WaveTouch &t, u32 seg_pos,
                       const AckS &a, Actions &actions) {
        if (t.kind == 2) return;  // canonically rejected: no effect
        CRNP crnp = win_get(t.req_no);
        if (!crnp) throw EngineError("ledger candidate outside window");
        ClientReqNoD &crn = *crnp;
        i64 adj = 0;
        if (!crn.self_acked.empty()) {
            for (i32 d : crn.self_acked)
                if (d == t.dig) {
                    CanonDig *D = R.find(t.dig);
                    i32 p = D ? D->pos_of((u8)my_config.id) : -1;
                    if (p < 0 || (u32)p > seg_pos) adj = 1;
                    break;
                }
        }
        i64 c_r = (i64)t.post + adj;
        if (c_r == weak_quorum) {
            CRP cr = crn.client_req(a);
            crn.weak_requests.put(t.dig, cr);
            if (!cr->stored) actions.push_back(act_correct(a));
            update_attention(crn);
            if (cr->stored) client_tracker->add_available(a);
        }
        if (c_r == strong_quorum) {
            CRP cr = crn.client_req(a);
            crn.strong_requests.put(t.dig, cr);
            advance_ready();
        }
    }

    // Own-segment touch (self-send short-circuit): applied early, before
    // the touch's canonical position is reached by arrivals.  The count on
    // our view derives from the add log restricted to our consumed set
    // plus this touch itself.
    void led_own_touch(CanonClient &cc, u32 wave_pos, const WaveTouch &t,
                       const AckS &a, Actions &actions) {
        if (client_state.lw > t.req_no) return;  // PAST
        if (high_watermark < t.req_no)
            throw EngineError("own ack beyond own high watermark");
        CanonRec &R = cc.rec_or_create(t.req_no);
        if (R.diverged.test(my_config.id)) {
            ack_into(actions, my_config.id, a, false);
            return;
        }
        CRNP crnp = win_get(t.req_no);
        if (!crnp) throw EngineError("own ack outside window");
        ClientReqNoD &crn = *crnp;
        if (t.kind == 2) {
            // Conflicting own revote, canonically rejected — classic would
            // reject identically (our non-null bit was self-applied).
            crn.client_req(a);
            return;
        }
        if (t.kind == 0) {
            bool known = false;
            for (i32 d : crn.self_acked)
                if (d == t.dig) known = true;
            if (!known) crn.self_acked.push_back(t.dig);
        }
        CanonDig *D = R.find(t.dig);
        i64 cnt = 0;
        if (D) {
            for (const auto &pr : D->add_log) {
                bool cons = led_view->consumed(pr.first);
                if (!cons && pr.first == wave_pos &&
                    pr.second == (u8)my_config.id)
                    cons = true;  // the bit this touch applies
                if (cons) cnt += 1;
            }
        }
        // PDES: this window's own bits (including the one this touch
        // applies, at its provisional position) live only in the overlay.
        if (const AckShard *sh = led_shard())
            if (const ShardRec *S = sh->rec(client_state.id, t.req_no))
                if (const ShardDig *DS = S->find(t.dig))
                    for (const auto &pr : DS->add_log) {
                        bool cons = led_view->consumed(pr.first);
                        if (!cons && pr.first == wave_pos &&
                            pr.second == (u8)my_config.id)
                            cons = true;
                        if (cons) cnt += 1;
                    }
        i64 c_r = cnt;
        if (c_r < weak_quorum) return;
        bool newly = c_r == weak_quorum;
        CRP cr = crn.client_req(a);
        if (newly) {
            crn.weak_requests.put(t.dig, cr);
            if (!cr->stored) actions.push_back(act_correct(a));
            update_attention(crn);
        }
        if (cr->stored) client_tracker->add_available(a);  // source == me
        if (c_r == strong_quorum) {
            crn.strong_requests.put(t.dig, cr);
            advance_ready();
        }
    }

    // Exact per-touch walk of one segment (used when the wave-level fast
    // preconditions fail: window straddling or diverged records).
    template <typename BufferStore>
    void led_seg_slow(const WaveSeg &seg, u32 wave_pos,
                      const vector<AckS> &acks, Actions &actions,
                      BufferStore &&buffer_store) {
        CanonClient &cc = *(CanonClient *)seg.canon;
        if (led_diverged == 0) {
            // No private records: only candidates and the FUTURE suffix
            // matter (touches are reqno-ascending within a segment —
            // coalesce_sends sorts batches by (client, reqno)).
            for (u32 ci : seg.candidates) {
                const WaveTouch &t = seg.touches[ci];
                if (t.req_no < client_state.lw || t.req_no > high_watermark)
                    continue;
                CanonRec *R = cc.rec(t.req_no);
                led_candidate(*R, t, wave_pos, acks[seg.ack_start + ci],
                              actions);
            }
            if (seg.max_reqno > high_watermark) {
                size_t k = seg.touches.size();
                while (k > 0 && seg.touches[k - 1].req_no > high_watermark)
                    k--;
                for (; k < seg.touches.size(); k++) {
                    const WaveTouch &t = seg.touches[k];
                    if (t.req_no <= high_watermark) continue;  // unsorted guard
                    buffer_store(seg.ack_start + k);
                    CanonRec &R = cc.rec_or_create(t.req_no);
                    if (!R.diverged.test(my_config.id)) {
                        R.diverged.set(my_config.id);
                        led_diverged += 1;
                        if (led_diverged_total) *led_diverged_total += 1;
                    }
                }
            }
            return;
        }
        for (size_t k = 0; k < seg.touches.size(); k++) {
            const WaveTouch &t = seg.touches[k];
            const AckS &a = acks[seg.ack_start + k];
            if (client_state.lw > t.req_no) continue;  // PAST: no effect
            if (high_watermark < t.req_no) {
                // FUTURE: buffer classically; the record rides private
                // state for us from here (it has never been in our
                // window, so fresh classic state is exact).
                buffer_store(seg.ack_start + k);
                CanonRec &R = cc.rec_or_create(t.req_no);
                if (!R.diverged.test(my_config.id)) {
                    R.diverged.set_atomic(my_config.id);
                    led_diverged += 1;
                    if (led_diverged_total) *led_diverged_total += 1;
                }
                continue;
            }
            CanonRec *R = cc.rec(t.req_no);
            if (R && R->diverged.test(my_config.id)) {
                ack_into(actions, (i32)seg.src, a, false);
                continue;
            }
            if (t.candidate && R)
                led_candidate(*R, t, wave_pos, a, actions);
        }
    }

    // ack_into (disseminator.py:488-539) — the per-ack hot path.
    CRP ack_into(Actions &actions, i32 source, const AckS &ack,
                 bool force = false) {
        CRNP crnp = win_get(ack.reqno);
        if (!crnp) throw EngineError("ack outside watermarks");
        led_ensure_private(*crnp);
        ClientReqNoD &crn = *crnp;

        if (ack.dig != 0 && !force) {
            CRP *existing = crn.requests.get(ack.dig);
            bool already_voted_this =
                existing && (*existing)->agreements.test(source);
            if (crn.non_null_voters.test(source) && !already_voted_this)
                return crn.client_req(ack);
        }
        if (ack.dig != 0) crn.non_null_voters.set(source);

        CRP cr = crn.client_req(ack);
        if (source == my_config.id && !cr->agreements.test(source)) {
            bool known = false;
            for (i32 d : crn.self_acked)
                if (d == ack.dig) known = true;
            if (!known) crn.self_acked.push_back(ack.dig);
        }
        cr->agreements.set(source);
        i64 agreement_count = cr->agreements.count();

        bool newly_correct = agreement_count == weak_quorum;
        if (newly_correct) {
            crn.weak_requests.put(ack.dig, cr);
            if (!cr->stored) actions.push_back(act_correct(ack));
            update_attention(crn);
        }
        if (cr->stored &&
            (newly_correct ||
             (agreement_count >= weak_quorum && source == my_config.id)))
            client_tracker->add_available(ack);
        if (agreement_count == strong_quorum) {
            crn.strong_requests.put(ack.dig, cr);
            advance_ready();
        }
        return cr;
    }

    // ack_run (disseminator.py:541-604): a run of in-window acks from one
    // source for this client starting at acks[start]; returns index after.
    size_t ack_run(Actions &actions, i32 source, const vector<AckS> &acks,
                   size_t start) {
        u64 bit = 1ull << source;
        i64 weak_q = weak_quorum, strong_q = strong_quorum;
        i64 low = client_state.lw, high = high_watermark;
        i64 client_id = acks[start].client;
        size_t n = acks.size();
        size_t i = start;
        while (i < n) {
            const AckS &ack = acks[i];
            if (ack.client != client_id) break;
            i64 req_no = ack.reqno;
            if (req_no < low || req_no > high) break;
            i++;
            i32 digest = ack.dig;
            ClientReqNoD &crn = *win[(size_t)(req_no - win_base)];
            CRP cr;
            if (digest != 0 && crn.non_null_voters.test(source)) {
                CRP *existing = crn.requests.get(digest);
                if (!existing) {
                    auto fresh = std::make_shared<ClientRequestD>();
                    fresh->ack = ack;
                    crn.requests.put(digest, fresh);
                    continue;
                }
                if (!(*existing)->agreements.test(source)) continue;
                cr = *existing;
            } else {
                if (digest != 0) crn.non_null_voters.set(source);
                CRP *existing = crn.requests.get(digest);
                if (existing) {
                    cr = *existing;
                } else {
                    cr = std::make_shared<ClientRequestD>();
                    cr->ack = ack;
                    crn.requests.put(digest, cr);
                }
            }
            cr->agreements.set(source);
            i64 count = cr->agreements.count();
            if (count < weak_q) continue;
            bool newly_correct = count == weak_q;
            if (newly_correct) {
                crn.weak_requests.put(digest, cr);
                if (!cr->stored) actions.push_back(act_correct(ack));
                update_attention(crn);
            }
            if (cr->stored && (newly_correct || source == my_config.id))
                client_tracker->add_available(ack);
            if (count == strong_q) {
                crn.strong_requests.put(digest, cr);
                advance_ready();
            }
        }
        return i;
    }

    void advance_ready() {
        for (i64 i = next_ready_mark; i <= high_watermark; i++) {
            if (i != next_ready_mark) return;
            CRNP crn = req_no_of(i);
            if (crn->committed) {
                next_ready_mark = i + 1;
                continue;
            }
            for (const auto &pr : crn->strong_requests.items) {
                if (!crn->my_requests.contains(pr.first)) continue;
                client_tracker->add_ready(crn);
                next_ready_mark = i + 1;
                break;
            }
        }
    }

    // Appends freshly generated acks to `acks` instead of broadcasting:
    // the disseminator's flush_acks coalesces acks across all dirty
    // clients into one AckBatch per event batch (mirrors the Python
    // Client.advance_acks / flush_acks split).
    void advance_acks(vector<AckS> &acks) {
        for (i64 i = next_ack_mark; i <= high_watermark; i++) {
            CRNP crn = req_no_of(i);
            AckS ack{0, 0, 0};
            if (!crn->generate_ack(&ack)) break;
            acks.push_back(ack);
            schedule_resend(*crn, tick_count + ACK_RESEND_TICKS + 1);
            update_attention(*crn);
            next_ack_mark = i + 1;
        }
    }

    void update_attention(ClientReqNoD &crn) {
        if (!crn.committed && crn.needs_attention())
            attention.insert(crn.req_no);
        else
            attention.erase(crn.req_no);
    }

    void schedule_resend(ClientReqNoD &crn, i64 due_tick) {
        resend_seq += 1;
        crn.resend_nonce = resend_seq;
        resend_schedule[due_tick].emplace_back(crn.req_no, crn.resend_nonce);
    }

    void apply_new_request(const AckS &ack) {
        CRNP crn = req_no_of(ack.reqno);
        crn->apply_new_request(ack);
        update_attention(*crn);
    }

    void note_fetching(const AckS &ack) {
        CRNP crn = win_get(ack.reqno);
        if (crn) update_attention(*crn);
    }

    void tick(Actions &actions, const Targets &nodes) {
        tick_count += 1;
        if (!attention.empty()) {
            // Python iterates sorted(attention) over a snapshot.
            vector<i64> snapshot(attention.begin(), attention.end());
            for (i64 rn : snapshot) {
                CRNP crn = win_get(rn);
                if (!crn || crn->committed) {
                    attention.erase(rn);
                    continue;
                }
                // attention_tick mutates per-candidate fetch state and
                // reads agreements (fetch targets): private-state ground.
                led_ensure_private(*crn);
                if (crn->attention_tick(actions, nodes, ctx->intern))
                    schedule_resend(*crn, tick_count + ACK_RESEND_TICKS);
                update_attention(*crn);
            }
        }
        vector<AckS> resend;
        auto due_it = resend_schedule.find(tick_count);
        if (due_it != resend_schedule.end()) {
            vector<std::pair<i64, i64>> due = std::move(due_it->second);
            resend_schedule.erase(due_it);
            for (const auto &pr : due) {
                CRNP crnp = win_get(pr.first);
                if (!crnp || crnp->committed || crnp->resend_nonce != pr.second)
                    continue;
                ClientReqNoD &crn = *crnp;
                CRP *req = crn.my_requests.get(crn.acked_digest);
                if (!req)
                    throw EngineError("sent an ack for a request we do not have");
                crn.acks_sent += 1;
                resend.push_back((*req)->ack);
                schedule_resend(crn,
                                tick_count + crn.acks_sent * ACK_RESEND_TICKS + 1);
            }
        }
        if (resend.size() == 1)
            actions.push_back(act_send(nodes, mk_ack_msg(resend[0])));
        else if (!resend.empty())
            actions.push_back(act_send(nodes, mk_ack_batch(std::move(resend))));
    }
};

struct Disseminator {
    const Ctx *ctx = nullptr;
    InitParms my_config;
    NodeBuffers *node_buffers = nullptr;
    ClientTracker *client_tracker = nullptr;
    NetCfgP network_config;  // the active consensused config
    i64 allocated_through = 0;
    bool initialized = false;
    vector<ClientStateS> client_states;
    std::map<i32, MsgBuffer> msg_buffers;
    std::map<i64, shared_ptr<ClientD>> clients;
    vector<ClientD *> client_dense;  // direct index for small dense ids
    std::set<i64> ack_dirty;
    // Ack-ledger receiver state: the global stream cursor plus the
    // aggregates that gate the wave-level fast path.
    LedView led_view;
    // PDES: the owning partition's ledger overlay, re-pointed by the
    // engine every step (null in sequential runs and barrier tails).
    AckShard *led_shard = nullptr;
    i64 led_diverged_total = 0;
    i64 led_classic_count = 0;
    i64 led_max_lw = 0;          // max client low watermark (PAST gate)
    i64 led_min_high = INT64_MAX;  // min client high watermark (FUTURE gate)

    void led_refresh_bounds() {
        led_max_lw = 0;
        led_min_high = INT64_MAX;
        led_classic_count = 0;
        for (const auto &pr : clients) {
            const ClientD &c = *pr.second;
            if (c.client_state.lw > led_max_lw) led_max_lw = c.client_state.lw;
            if (c.high_watermark < led_min_high) led_min_high = c.high_watermark;
            if (c.led_classic) led_classic_count += 1;
        }
    }

    ClientD *client(i64 client_id) {
        if ((u64)client_id < client_dense.size())
            return client_dense[(size_t)client_id];
        auto it = clients.find(client_id);
        return it == clients.end() ? nullptr : it->second.get();
    }

    void rebuild_dense() {
        client_dense.clear();
        i64 max_id = -1;
        for (const auto &pr : clients) max_id = std::max(max_id, pr.first);
        if (max_id < 0 || max_id >= 4096) return;
        client_dense.assign((size_t)max_id + 1, nullptr);
        for (const auto &pr : clients)
            client_dense[(size_t)pr.first] = pr.second.get();
    }

    Actions reinitialize(i64 seq_no, const NetStateS &network_state) {
        Actions actions;
        bool reconfiguring = !network_state.pending.empty();
        bool same_config =
            network_config && *network_config == *network_state.config;
        network_config = network_state.config;
        allocated_through = seq_no;

        auto old_clients = std::move(clients);
        clients.clear();
        client_states = network_state.clients;
        for (const auto &cs : client_states) {
            shared_ptr<ClientD> c;
            auto it = old_clients.find(cs.id);
            if (it != old_clients.end()) {
                c = it->second;
            } else {
                c = std::make_shared<ClientD>();
                c->ctx = ctx;
                c->my_config = my_config;
                c->client_tracker = client_tracker;
                c->led_view = &led_view;
                c->led_shard_slot = &led_shard;
                c->led_diverged_total = &led_diverged_total;
                c->led_classic_count = &led_classic_count;
            }
            clients.emplace(cs.id, c);
            concat(actions,
                   c->reinitialize(seq_no, cs.id, cs, reconfiguring,
                                   same_config, network_config->ci));
        }
        led_refresh_bounds();
        auto old_msg_buffers = std::move(msg_buffers);
        msg_buffers.clear();
        for (i32 node : ctx->cfg.nodes) {
            auto it = old_msg_buffers.find(node);
            if (it != old_msg_buffers.end()) {
                msg_buffers.emplace(node, std::move(it->second));
            } else {
                MsgBuffer mb;
                mb.nb = node_buffers->node_buffer(node);
                mb.wire = &ctx->wire;
                msg_buffers.emplace(node, std::move(mb));
            }
        }
        rebuild_dense();
        initialized = true;
        return actions;
    }

    Actions tick() {
        Actions actions;
        for (const auto &cs : client_states)
            clients.at(cs.id)->tick(actions, ctx->bcast);
        return actions;
    }

    Applyable filter(const MsgS &msg) {
        if (msg.t == MT::AckMsg) {
            const AckS &ack = msg.acks[0];
            ClientD *c = client(ack.client);
            if (!c) return Applyable::FUTURE;
            if (c->client_state.lw > ack.reqno) return Applyable::PAST;
            if (c->high_watermark < ack.reqno) return Applyable::FUTURE;
            return Applyable::CURRENT;
        }
        if (msg.t == MT::FetchRequest) return Applyable::CURRENT;
        throw EngineError("unexpected client message type");
    }

    // The classic per-ack classification loop over acks[i..end) — the
    // AckBatch arm of disseminator.py:1056-1085; also the fallback for
    // ledger segments outside the fast path's envelope.
    void classic_slice(Actions &actions, i32 source, const vector<AckS> &acks,
                       size_t i, size_t end) {
        while (i < end) {
            const AckS &ack = acks[i];
            ClientD *c = client(ack.client);
            if (!c) {
                msg_buffers.at(source).store(mk_ack_msg(ack));  // FUTURE
                i++;
                continue;
            }
            i64 req_no = ack.reqno;
            if (c->client_state.lw > req_no) {
                i++;
                continue;  // PAST
            }
            if (c->high_watermark < req_no) {
                msg_buffers.at(source).store(mk_ack_msg(ack));  // FUTURE
                i++;
                continue;
            }
            i = c->ack_run(actions, source, acks, i);
        }
    }

    Actions step(i32 source, const MsgP &msg) {
        if ((msg->t == MT::AckBatch || msg->t == MT::AckMsg) &&
            msg->wave_id >= 0 && ctx->ack_ledger != nullptr) {
            // Ledger wave consumption: ONE cursor bump per wave plus the
            // precomputed quorum-crossing candidates.  See AckLedger.
            u64 t0 = __rdtsc();
            Actions actions;
            const WaveReg *regp;
            if ((u64)msg->wave_id & (u64)LED_PROV_BIT) {
                // PDES provisional wave: only the sender's self-send
                // short-circuit may consume it (arrivals land post-fold
                // with the final id — the window is narrower than every
                // link by construction).
                if (source != my_config.id || !led_shard)
                    throw EngineError(
                        "pdes ledger: provisional wave outside sender");
                regp = &led_shard
                            ->waves[(size_t)((u32)msg->wave_id &
                                             ~LED_PROV_BIT)]
                            .reg;
            } else {
                regp = &ctx->ack_ledger->wave(msg->wave_id);
            }
            const WaveReg &reg = *regp;
            const vector<AckS> &acks = reg.msg->acks;
            auto buffer_store = [&](size_t ack_index) {
                msg_buffers.at(source).store(reg.single(ack_index));
            };
            if (source == my_config.id) {
                // Own wave, consumed early via the self-send short-circuit.
                for (const WaveSeg &seg : reg.segs) {
                    ClientD *c = client(seg.client);
                    if (!c) {
                        for (u32 k = seg.ack_start; k < seg.ack_end; k++)
                            buffer_store(k);
                        continue;
                    }
                    if (c->led_classic) {
                        classic_slice(actions, source, acks, seg.ack_start,
                                      seg.ack_end);
                        continue;
                    }
                    CanonClient &cc = *(CanonClient *)seg.canon;
                    for (size_t k = 0; k < seg.touches.size(); k++)
                        c->led_own_touch(cc, reg.pos, seg.touches[k],
                                         acks[seg.ack_start + k], actions);
                }
                if (led_view.version == reg.pos) {
                    led_view.version += 1;
                    led_view.absorb();
                } else {
                    led_view.own_early.push_back(reg.pos);
                }
                g_parts[0].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
                return actions;
            }
            // Arrival: the cursor must be exactly at this wave's position
            // (gaps can only be our own early-consumed waves).
            led_view.absorb();
            if (led_view.version != reg.pos) {
                // Outside the modeled envelope: switch every client to the
                // classic path, permanently (safe, exact).  The cursor
                // still advances so ledger pruning is not blocked.
                for (const auto &pr : clients)
                    pr.second->led_fallback_all_classic();
                led_refresh_bounds();
                if (reg.pos + 1 > led_view.version) {
                    led_view.version = reg.pos + 1;
                    led_view.own_early.clear();
                }
                classic_slice(actions, source, acks, 0, acks.size());
                g_parts[0].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
                return actions;
            }
            if (led_classic_count == 0 && led_diverged_total == 0 &&
                reg.max_any < led_min_high && reg.min_any >= led_max_lw) {
                // Steady-state: only quorum-crossing candidates cost work.
                for (u32 si : reg.candidate_segs) {
                    const WaveSeg &seg = reg.segs[si];
                    ClientD *c = client(seg.client);
                    CanonClient &cc = *(CanonClient *)seg.canon;
                    for (u32 ci : seg.candidates) {
                        const WaveTouch &t = seg.touches[ci];
                        CanonRec *R = cc.rec(t.req_no);
                        c->led_candidate(*R, t, reg.pos,
                                         acks[seg.ack_start + ci], actions);
                    }
                }
            } else {
                for (const WaveSeg &seg : reg.segs) {
                    ClientD *c = client(seg.client);
                    if (!c) {
                        for (u32 k = seg.ack_start; k < seg.ack_end; k++)
                            buffer_store(k);
                        continue;
                    }
                    if (c->led_classic) {
                        classic_slice(actions, source, acks, seg.ack_start,
                                      seg.ack_end);
                        continue;
                    }
                    // Per-segment gate: an in-window segment with no
                    // diverged records costs only its candidates.
                    if (c->led_diverged == 0 &&
                        c->client_state.lw <= seg.min_reqno &&
                        seg.max_reqno <= c->high_watermark) {
                        CanonClient &cc = *(CanonClient *)seg.canon;
                        for (u32 ci : seg.candidates) {
                            const WaveTouch &t = seg.touches[ci];
                            CanonRec *R = cc.rec(t.req_no);
                            c->led_candidate(*R, t, reg.pos,
                                             acks[seg.ack_start + ci],
                                             actions);
                        }
                        continue;
                    }
                    c->led_seg_slow(seg, reg.pos, acks, actions, buffer_store);
                }
            }
            led_view.version = reg.pos + 1;
            led_view.absorb();
            g_parts[0].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
            return actions;
        }
        if (msg->t == MT::AckBatch) {
            u64 t0 = __rdtsc();
            // Per-ack classification; in-window same-client runs go through
            // ack_run (the AckBatch arm of disseminator.py:1056-1085 — the
            // pure semantics the native plane replays).
            Actions actions;
            classic_slice(actions, source, msg->acks, 0, msg->acks.size());
            g_parts[0].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
            return actions;
        }
        Applyable verdict = filter(*msg);
        if (verdict == Applyable::PAST) return Actions();
        if (verdict == Applyable::FUTURE) {
            msg_buffers.at(source).store(msg);
            return Actions();
        }
        return apply_msg(source, msg);
    }

    Actions apply_msg(i32 source, const MsgP &msg) {
        if (msg->t == MT::AckMsg) {
            Actions actions;
            ack(actions, source, msg->acks[0], false);
            return actions;
        }
        if (msg->t == MT::FetchRequest) {
            const AckS &a = msg->acks[0];
            return reply_fetch_request(source, a);
        }
        throw EngineError("unexpected client message type");
    }

    Actions apply_new_request(const AckS &ack) {
        ClientD *c = client(ack.client);
        if (!c) return Actions();
        if (!c->in_watermarks(ack.reqno)) return Actions();
        c->apply_new_request(ack);
        ack_dirty.insert(ack.client);
        return Actions();
    }

    Actions flush_acks() {
        // All dirty clients' acks coalesce into ONE AckBatch per flush
        // (mirrors the Python flush_acks: one broadcast per event batch,
        // not one per client; receive arms classify per ack).
        if (ack_dirty.empty()) return Actions();
        Actions actions;
        vector<AckS> merged;
        for (i64 client_id : ack_dirty) {  // std::set: sorted like Python
            ClientD *c = client(client_id);
            if (c) c->advance_acks(merged);
        }
        ack_dirty.clear();
        if (merged.size() == 1)
            actions.push_back(act_send(ctx->bcast, mk_ack_msg(merged[0])));
        else if (!merged.empty())
            actions.push_back(
                act_send(ctx->bcast, mk_ack_batch(std::move(merged))));
        return actions;
    }

    Actions allocate(i64 seq_no, const NetStateS &network_state) {
        if (seq_no != network_state.config->ci + allocated_through)
            throw EngineError("unexpected skip in allocate");
        Actions actions;
        allocated_through = seq_no;
        bool reconfiguring = !network_state.pending.empty();
        for (const auto &cs : network_state.clients) {
            ClientD *c = client(cs.id);
            concat(actions,
                   c->allocate(seq_no, cs, reconfiguring, network_config->ci));
        }
        led_refresh_bounds();
        for (i32 node : ctx->cfg.nodes) {
            msg_buffers.at(node).iterate(
                [this](const MsgS &m) { return filter(m); },
                [this, node, &actions](MsgP m) {
                    concat(actions, apply_msg(node, m));
                });
        }
        if (ctx->ack_ledger != nullptr) {
            for (const auto &cs : network_state.clients)
                client(cs.id)->led_try_realign();
            led_refresh_bounds();
        }
        return actions;
    }

    Actions reply_fetch_request(i32 source, const AckS &a) {
        ClientD *c = client(a.client);
        if (!c || !c->in_watermarks(a.reqno)) return Actions();
        CRNP crn = c->req_no_of(a.reqno);
        c->led_ensure_private(*crn);  // reads agreements (our own bit)
        CRP *data = crn->requests.get(a.dig);
        if (!data || !(*data)->agreements.test(my_config.id))
            return Actions();
        Actions actions;
        actions.push_back(act_forward({source}, a));
        return actions;
    }

    CRP ack(Actions &actions, i32 source, const AckS &a, bool force) {
        ClientD *c = client(a.client);
        if (!c)
            throw EngineError("step filtering should delay reqs for non-existent clients");
        return c->ack_into(actions, source, a, force);
    }

    void note_fetching(const AckS &a) {
        ClientD *c = client(a.client);
        if (c) c->note_fetching(a);
    }
};

// ---------------------------------------------------------------------------
// Proposer (statemachine/proposer.py).
// ---------------------------------------------------------------------------

struct ProposalBucket {
    i64 bucket_id;
    i64 current_checkpoint;
    i64 checkpoint_interval;
    i64 request_count;
    vector<CRP> pending;
    deque<CRP> ready_list;
    deque<CRP> next_ready_list;

    void queue_request(i64 valid_after_seq_no, CRP cr) {
        if (current_checkpoint >= valid_after_seq_no) {
            ready_list.push_back(std::move(cr));
        } else {
            if (valid_after_seq_no != current_checkpoint + checkpoint_interval)
                throw EngineError(
                    "requests should never become ready beyond the next "
                    "checkpoint interval");
            next_ready_list.push_back(std::move(cr));
        }
    }

    void advance(i64 to_seq_no) {
        if (to_seq_no >= current_checkpoint + checkpoint_interval) {
            current_checkpoint += checkpoint_interval;
            for (auto &cr : next_ready_list) ready_list.push_back(std::move(cr));
            next_ready_list.clear();
        }
        while ((i64)pending.size() < request_count && !ready_list.empty()) {
            pending.push_back(std::move(ready_list.front()));
            ready_list.pop_front();
        }
    }

    bool has_outstanding(i64 for_seq_no) {
        advance(for_seq_no);
        return !pending.empty();
    }

    bool has_pending(i64 for_seq_no) {
        advance(for_seq_no);
        return !pending.empty() && (i64)pending.size() == request_count;
    }

    vector<CRP> next() {
        vector<CRP> result = std::move(pending);
        pending.clear();
        return result;
    }
};

struct Proposer {
    const Ctx *ctx;
    InitParms my_config;
    i64 nb;  // TOTAL bucket count under the active config
    std::map<i64, ProposalBucket> proposal_buckets;
    shared_ptr<AppendList<CRNP>> ready_iterator;

    Proposer(const Ctx *c, i64 base_checkpoint, InitParms mc,
             shared_ptr<AppendList<CRNP>> ready_list,
             const std::map<i64, i32> &buckets)
        : ctx(c), my_config(mc), nb((i64)buckets.size()) {
        for (const auto &pr : buckets) {
            if (pr.second != mc.id) continue;
            ProposalBucket b;
            b.bucket_id = pr.first;
            b.current_checkpoint = base_checkpoint;
            b.checkpoint_interval = c->cfg.ci;
            b.request_count = mc.batch_size;
            proposal_buckets.emplace(pr.first, std::move(b));
        }
        ready_list->reset_iterator();
        ready_iterator = std::move(ready_list);
    }

    void advance(i64 to_seq_no) {
        while (ready_iterator->has_next()) {
            CRNP crn = ready_iterator->next();
            if (crn->committed) continue;
            i64 bucket_id = (crn->client_id + crn->req_no) % nb;
            auto it = proposal_buckets.find(bucket_id);
            if (it == proposal_buckets.end()) continue;
            ProposalBucket &bucket = it->second;
            bucket.advance(to_seq_no);
            if (crn->strong_requests.size() > 1) {
                // Conflicting strong certs: prefer the null request.
                CRP *null_req = crn->strong_requests.get(0);
                if (!null_req)
                    throw EngineError(
                        "if multiple requests have quorum, one must be null");
                bucket.queue_request(crn->valid_after_seq_no, *null_req);
            } else {
                if (crn->strong_requests.size() != 1)
                    throw EngineError("exactly one strong request must exist");
                bucket.queue_request(crn->valid_after_seq_no,
                                     crn->strong_requests.items[0].second);
            }
        }
    }

    ProposalBucket *proposal_bucket(i64 bucket_id) {
        auto it = proposal_buckets.find(bucket_id);
        return it == proposal_buckets.end() ? nullptr : &it->second;
    }
};

// ---------------------------------------------------------------------------
// Commit state (statemachine/commitstate.py).
// ---------------------------------------------------------------------------

struct CommittingClient {
    ClientStateS last_state;
    vector<i64> committed;  // -1 = None, seq_no otherwise

    CommittingClient() = default;
    CommittingClient(i64 seq_no, const ClientStateS &cs) {
        committed.assign((size_t)cs.width, -1);
        i64 bits = 8 * (i64)cs.mask.size();
        for (i64 i = 0; i < bits; i++) {
            bool set = (u8(cs.mask[(size_t)(i >> 3)]) & (0x80u >> (i & 7))) != 0;
            if (set && i < (i64)committed.size()) committed[(size_t)i] = seq_no;
        }
        last_state = cs;
    }

    void mark_committed(i64 seq_no, i64 req_no) {
        if (req_no < last_state.lw) return;
        i64 offset = req_no - last_state.lw;
        if (offset >= (i64)committed.size()) {
            if (offset >= last_state.width)
                throw EngineError("commit beyond client window");
            committed.resize((size_t)(offset + 1), -1);
        }
        committed[(size_t)offset] = seq_no;
    }

    ClientStateS create_checkpoint_state() {
        ClientStateS old = last_state;
        i64 first_uncommitted = -1, last_committed = -1;
        bool have_fu = false, have_lc = false;
        for (i64 i = 0; i < old.width; i++) {
            i64 seq = i < (i64)committed.size() ? committed[(size_t)i] : -1;
            i64 req_no = old.lw + i;
            if (seq != -1) {
                last_committed = req_no;
                have_lc = true;
            } else if (!have_fu) {
                first_uncommitted = req_no;
                have_fu = true;
            }
        }
        if (!have_lc) {
            ClientStateS ns{old.id, old.width, 0, old.lw, string()};
            last_state = ns;
            return ns;
        }
        if (!have_fu) first_uncommitted = last_committed + 1;

        i64 width_consumed = first_uncommitted - old.lw;
        {
            vector<i64> next;
            for (i64 i = width_consumed; i < (i64)committed.size(); i++)
                next.push_back(committed[(size_t)i]);
            next.resize((size_t)(next.size() + old.width), -1);
            next.resize((size_t)old.width);
            committed = std::move(next);
        }

        string mask_bytes;
        if (last_committed != first_uncommitted) {
            i64 nbits = 8 * ((last_committed - first_uncommitted) / 8 + 1);
            mask_bytes.assign((size_t)(nbits / 8), '\0');
            for (i64 i = 0; i <= last_committed - first_uncommitted; i++) {
                if (committed[(size_t)i] == -1) continue;
                if (i == 0)
                    throw EngineError(
                        "the first uncommitted request cannot be committed");
                mask_bytes[(size_t)(i >> 3)] =
                    (char)(u8(mask_bytes[(size_t)(i >> 3)]) | (0x80u >> (i & 7)));
            }
        }
        ClientStateS ns{old.id, old.width, width_consumed, first_uncommitted,
                        mask_bytes};
        last_state = ns;
        return ns;
    }
};

struct CommitState {
    const Ctx *ctx = nullptr;
    PersistedLog *persisted = nullptr;
    std::map<i64, CommittingClient> committing_clients;
    i64 low_watermark = 0;
    i64 last_applied_commit = 0;
    i64 highest_commit = 0;
    i64 stop_at_seq_no = 0;
    NetStateP active_state;
    vector<QEntryP> lower_half_commits, upper_half_commits;
    bool checkpoint_pending = false;
    bool transferring = false;
    // Failed-transfer retry machinery (commitstate.py:221-229; completes
    // the reference's open edge, state_machine.go:210-212).
    i64 transfer_retry_in = 0;
    i64 transfer_retry_backoff = 0;
    bool have_retry_target = false;
    i64 retry_seq = 0;
    i32 retry_value = 0;

    Actions reinitialize() {
        const PersistEntS *last_c = nullptr, *last_t = nullptr;
        for (const auto &pr : persisted->entries) {
            if (pr.second->t == PET::C) last_c = pr.second.get();
            else if (pr.second->t == PET::T) last_t = pr.second.get();
        }
        if (!last_c) throw EngineError("log must contain a CEntry");

        active_state = last_c->netstate;
        low_watermark = last_c->seq;

        Actions actions;
        actions.push_back(act_state_applied(low_watermark, active_state));

        i64 ci = active_state->config->ci;
        if (active_state->pending.empty())
            stop_at_seq_no = last_c->seq + 2 * ci;
        else
            // Mid-reconfiguration: ordering halts at the next checkpoint,
            // which is where the pending reconfiguration will apply.
            stop_at_seq_no = low_watermark + ci;
        last_applied_commit = last_c->seq;
        highest_commit = last_c->seq;
        lower_half_commits.assign((size_t)ci, nullptr);
        upper_half_commits.assign((size_t)ci, nullptr);
        checkpoint_pending = false;

        committing_clients.clear();
        for (const auto &cs : active_state->clients)
            committing_clients.emplace(cs.id,
                                       CommittingClient(low_watermark, cs));

        transfer_retry_in = 0;
        transfer_retry_backoff = 0;
        have_retry_target = false;

        if (!last_t || last_c->seq >= last_t->seq) {
            transferring = false;
            return actions;
        }
        // Crashed mid-state-transfer: re-issue the transfer request.
        transferring = true;
        actions.push_back(act_state_transfer(last_t->seq, last_t->dig));
        return actions;
    }

    Actions transfer_to(i64 seq_no, i32 value) {
        if (transferring)
            throw EngineError("concurrent state transfers are not supported");
        transferring = true;
        Actions actions = persisted->append(pe_t(seq_no, value));
        actions.push_back(act_state_transfer(seq_no, value));
        return actions;
    }

    Actions apply_transfer_failed(i64 seq_no, i32 value) {
        // Stale failure from before a reinitialization — ignore.
        if (!transferring) return Actions();
        transfer_retry_backoff =
            transfer_retry_backoff == 0
                ? 1
                : std::min<i64>(transfer_retry_backoff * 2, 8);
        transfer_retry_in = transfer_retry_backoff;
        have_retry_target = true;
        retry_seq = seq_no;
        retry_value = value;
        return Actions();
    }

    Actions tick() {
        if (!have_retry_target) return Actions();
        transfer_retry_in -= 1;
        if (transfer_retry_in > 0) return Actions();
        have_retry_target = false;
        Actions actions;
        actions.push_back(act_state_transfer(retry_seq, retry_value));
        return actions;
    }

    Actions apply_checkpoint_result(i64 seq_no, i32 value, NetStateP ns) {
        i64 ci = active_state->config->ci;
        if (transferring) return Actions();
        if (seq_no != low_watermark + ci)
            throw EngineError("stale checkpoint result");
        bool completing_reconfiguration = !active_state->pending.empty();
        if (ns->pending.empty() && !completing_reconfiguration)
            stop_at_seq_no = seq_no + 2 * ci;
        // else: a reconfiguration is pending (don't order past the next
        // checkpoint) or this checkpoint just applied one (the epoch ends
        // here; the machine reinitializes under the new config).
        active_state = ns;
        lower_half_commits = std::move(upper_half_commits);
        upper_half_commits.assign((size_t)ci, nullptr);
        low_watermark = seq_no;
        checkpoint_pending = false;

        Actions actions = persisted->append(pe_c(seq_no, value, ns));
        actions.push_back(
            act_send(ctx->bcast, mk_checkpoint_msg(seq_no, value)));
        actions.push_back(act_state_applied(seq_no, ns));
        return actions;
    }

    std::pair<vector<QEntryP> *, size_t> slot(i64 seq_no, i64 ci) {
        bool upper = seq_no - low_watermark > ci;
        size_t offset = (size_t)((seq_no - (low_watermark + 1)) % ci);
        return {upper ? &upper_half_commits : &lower_half_commits, offset};
    }

    void commit(const QEntryP &q_entry) {
        if (transferring)
            throw EngineError("must never commit during state transfer");
        if (q_entry->seq > stop_at_seq_no)
            throw EngineError("commit seq exceeds stop");
        if (q_entry->seq <= low_watermark) return;
        if (highest_commit < q_entry->seq) {
            if (highest_commit + 1 != q_entry->seq)
                throw EngineError("out-of-order commit");
            highest_commit = q_entry->seq;
        }
        i64 ci = active_state->config->ci;
        auto [commits, offset] = slot(q_entry->seq, ci);
        QEntryP &existing = (*commits)[offset];
        if (existing) {
            if (existing->dig != q_entry->dig)
                throw EngineError("conflicting commit digests");
        } else {
            existing = q_entry;
        }
    }

    // drain() needs next_network_config; implemented after the helper below.
    Actions drain();
};

// next_network_config (commitstate.py:141-182): roll every client window
// forward, then apply any pending reconfigurations.
std::pair<NetCfgP, shared_ptr<const vector<ClientStateS>>>
next_network_config(const NetStateS &starting_state,
                    std::map<i64, CommittingClient> &committing_clients) {
    NetCfgP next_config = starting_state.config;
    auto out = std::make_shared<vector<ClientStateS>>();
    for (const auto &old_client : starting_state.clients) {
        auto it = committing_clients.find(old_client.id);
        if (it == committing_clients.end())
            throw EngineError("no committing client instance");
        out->push_back(it->second.create_checkpoint_state());
    }
    for (const auto &reconfig : starting_state.pending) {
        if (reconfig.t == ReconfigS::NewClient) {
            out->push_back(
                ClientStateS{reconfig.id, reconfig.width, 0, 0, string()});
        } else if (reconfig.t == ReconfigS::RemoveClient) {
            bool found = false;
            for (size_t i = 0; i < out->size(); i++)
                if ((*out)[i].id == reconfig.id) {
                    out->erase(out->begin() + (std::ptrdiff_t)i);
                    found = true;
                    break;
                }
            if (!found)
                throw EngineError("asked to remove a client which doesn't exist");
        } else {
            next_config = reconfig.config;
        }
    }
    return {std::move(next_config), std::move(out)};
}

Actions CommitState::drain() {
    i64 ci = active_state->config->ci;
    // Fast path (commitstate.py:370-384).
    i64 lac = last_applied_commit;
    if (lac < low_watermark + 2 * ci &&
        !(lac == low_watermark + ci && !checkpoint_pending)) {
        auto [commits, offset] = slot(lac + 1, ci);
        if (!(*commits)[offset]) return Actions();
    }

    Actions actions;
    while (last_applied_commit < low_watermark + 2 * ci) {
        if (last_applied_commit == low_watermark + ci && !checkpoint_pending) {
            auto [network_config, client_configs] =
                next_network_config(*active_state, committing_clients);
            actions.push_back(act_checkpoint(
                last_applied_commit, std::move(network_config),
                std::move(client_configs)));
            checkpoint_pending = true;
        }
        i64 next_commit = last_applied_commit + 1;
        auto [commits, offset] = slot(next_commit, ci);
        QEntryP commit = (*commits)[offset];
        if (!commit) break;
        if (commit->seq != next_commit)
            throw EngineError("attempted out-of-order commit");
        actions.push_back(act_commit(commit));
        for (const auto &req : commit->reqs)
            committing_clients.at(req.client).mark_committed(commit->seq,
                                                             req.reqno);
        last_applied_commit = next_commit;
    }
    return actions;
}

// ---------------------------------------------------------------------------
// Per-sequence three-phase commit (statemachine/sequence.py, dict path; the
// Python engine's native-plane path is observably identical to it).
// ---------------------------------------------------------------------------

enum class SeqState : u8 {
    UNINITIALIZED = 0, ALLOCATED = 1, PENDING_REQUESTS = 2, READY = 3,
    PREPREPARED = 4, PREPARED = 5, COMMITTED = 6,
};

struct Sequence {
    const Ctx *ctx;
    i32 owner;
    i64 seq_no, epoch;
    i32 my_id;
    PersistedLog *persisted;
    SeqState state = SeqState::UNINITIALIZED;
    QEntryP q_entry;
    vector<CRP> client_requests;
    vector<AckS> batch;
    std::unordered_set<AckS, AckHash> outstanding_reqs;
    bool has_outstanding_set = false;
    i32 digest = -1;  // -1 = None
    Mask prep_mask, commit_mask;
    SmallDigMap<i64> prepares, commits;
    i32 my_prepare_digest = -1;

    Sequence(const Ctx *c, i32 own, i64 ep, i64 sn, PersistedLog *p, i32 my)
        : ctx(c), owner(own), seq_no(sn), epoch(ep), my_id(my), persisted(p) {}

    i32 key_of(i32 d) const { return d < 0 ? 0 : d; }

    Actions advance_state() {
        Actions actions;
        while (true) {
            SeqState old_state = state;
            if (state == SeqState::PENDING_REQUESTS) {
                if (!(has_outstanding_set && !outstanding_reqs.empty()))
                    state = SeqState::READY;
            } else if (state == SeqState::READY) {
                if (digest != -1 || batch.empty()) concat(actions, prepare_());
            } else if (state == SeqState::PREPREPARED) {
                concat(actions, check_prepare_quorum());
            } else if (state == SeqState::PREPARED) {
                check_commit_quorum();
            }
            if (state == old_state) return actions;
        }
    }

    Actions allocate_as_owner(vector<CRP> crs) {
        client_requests = std::move(crs);
        vector<AckS> acks;
        for (const auto &cr : client_requests) acks.push_back(cr->ack);
        return allocate(std::move(acks), nullptr);
    }

    Actions allocate(vector<AckS> request_acks,
                     std::unordered_set<AckS, AckHash> *outstanding) {
        if (state != SeqState::UNINITIALIZED)
            throw EngineError("sequence must be uninitialized to allocate");
        state = SeqState::ALLOCATED;
        batch = std::move(request_acks);
        if (outstanding) {
            outstanding_reqs = std::move(*outstanding);
            has_outstanding_set = true;
        } else {
            has_outstanding_set = false;
        }
        if (batch.empty()) {
            state = SeqState::READY;
            return apply_batch_hash_result(-1);
        }
        vector<string> parts;
        for (const auto &a : batch) parts.push_back(ctx->intern.get(a.dig));
        HashOriginS origin;
        origin.t = OT::Batch;
        origin.source = owner;
        origin.epoch = epoch;
        origin.seq = seq_no;
        origin.request_acks = batch;
        Actions actions;
        actions.push_back(act_hash(std::move(parts), std::move(origin)));
        state = SeqState::PENDING_REQUESTS;
        concat(actions, advance_state());
        return actions;
    }

    Actions satisfy_outstanding(const AckS &ack) {
        auto it = outstanding_reqs.find(ack);
        if (!has_outstanding_set || it == outstanding_reqs.end())
            throw EngineError("told request was ready but we weren't waiting");
        outstanding_reqs.erase(it);
        return advance_state();
    }

    Actions apply_batch_hash_result(i32 dig) {
        digest = dig;
        return apply_prepare_msg(owner, dig);
    }

    Actions prepare_() {
        auto q = std::make_shared<QEntryS>();
        q->seq = seq_no;
        q->dig = key_of(digest);
        q->reqs = batch;
        q_entry = q;
        state = SeqState::PREPREPARED;

        Actions actions = persisted->append(pe_q(q_entry));

        if (owner == my_id) {
            for (const auto &cr : client_requests) {
                const Mask &agreements = cr->agreements;
                vector<i32> missing;
                for (i32 node : ctx->cfg.nodes)
                    if (!agreements.test(node)) missing.push_back(node);
                if (!missing.empty())
                    actions.push_back(act_forward(std::move(missing), cr->ack));
            }
            actions.push_back(
                act_send(ctx->bcast, mk_preprepare(seq_no, epoch, batch)));
        } else {
            actions.push_back(act_send(
                ctx->bcast, mk_prepare(seq_no, epoch, key_of(digest))));
        }
        return actions;
    }

    // apply_prepare_msg (sequence.py:255-291); dig -1 = None.
    Actions apply_prepare_msg(i32 source, i32 dig) {
        if (prep_mask.test(source) || commit_mask.test(source))
            return Actions();  // duplicate
        prep_mask.set(source);
        if (source == my_id) my_prepare_digest = dig;
        i32 key = key_of(dig);
        i64 *cnt = prepares.get(key);
        i64 count = cnt ? *cnt + 1 : 1;
        prepares.put(key, count);
        SeqState s = state;
        if (s == SeqState::PREPREPARED) {
            if (count >= ctx->iq) return advance_state();
            return Actions();
        }
        if (s == SeqState::READY || s == SeqState::PENDING_REQUESTS)
            return advance_state();
        return Actions();
    }

    Actions check_prepare_quorum() {
        i32 my_key = key_of(digest);
        const i64 *cntp = prepares.get(my_key);
        i64 agreements = cntp ? *cntp : 0;
        if (!prep_mask.test(my_id) && !commit_mask.test(my_id))
            return Actions();
        i32 my_digest = key_of(my_prepare_digest);
        if (my_digest != my_key) return Actions();
        if (agreements < ctx->iq) return Actions();

        state = SeqState::PREPARED;
        Actions actions = persisted->append(pe_p(seq_no, my_key));
        actions.push_back(
            act_send(ctx->bcast, mk_commit(seq_no, epoch, my_key)));
        return actions;
    }

    void apply_commit_msg(i32 source, i32 dig) {
        if (commit_mask.test(source)) return;  // duplicate
        commit_mask.set(source);
        i32 key = key_of(dig);
        i64 *cnt = commits.get(key);
        i64 count = cnt ? *cnt + 1 : 1;
        commits.put(key, count);
        if (state == SeqState::PREPARED && count >= ctx->iq)
            check_commit_quorum();
    }

    void check_commit_quorum() {
        i32 my_key = key_of(digest);
        const i64 *cntp = commits.get(my_key);
        i64 agreements = cntp ? *cntp : 0;
        if (!commit_mask.test(my_id)) return;
        if (agreements < ctx->iq) return;
        state = SeqState::COMMITTED;
    }
};
using SeqP = shared_ptr<Sequence>;

// ---------------------------------------------------------------------------
// Outstanding-request bookkeeping (statemachine/outstanding.py).
// ---------------------------------------------------------------------------

struct ClientOutstandingReqs {
    i64 next_req_no;
    i64 num_buckets;
    ClientStateS client;

    void skip_previously_committed() {
        while (is_committed(next_req_no, client)) next_req_no += num_buckets;
    }
};

struct AllOutstandingReqs {
    shared_ptr<AppendList<AckS>> available_iterator;
    std::unordered_set<AckS, AckHash> correct_requests;
    std::unordered_map<AckS, SeqP, AckHash> outstanding_requests;
    std::map<i64, std::map<i64, ClientOutstandingReqs>> buckets;

    AllOutstandingReqs(shared_ptr<AppendList<AckS>> available_list,
                       const NetStateS &network_state) {
        available_list->reset_iterator();
        available_iterator = std::move(available_list);
        i64 num_buckets = network_state.config->nb;
        for (i64 bucket = 0; bucket < num_buckets; bucket++) {
            auto &clients = buckets[bucket];
            for (const auto &client : network_state.clients) {
                i64 lw = client.lw;
                i64 first_uncommitted =
                    lw + ((((bucket - client.id - lw) % num_buckets) +
                           num_buckets) %
                          num_buckets);
                ClientOutstandingReqs cors{first_uncommitted, num_buckets,
                                           client};
                cors.skip_previously_committed();
                clients.emplace(client.id, cors);
            }
        }
        advance_requests();  // no sequences allocated yet -> no actions
    }

    Actions advance_requests() {
        Actions actions;
        while (available_iterator->has_next()) {
            AckS ack = available_iterator->next();
            auto it = outstanding_requests.find(ack);
            if (it != outstanding_requests.end()) {
                SeqP seq = it->second;
                outstanding_requests.erase(it);
                concat(actions, seq->satisfy_outstanding(ack));
                continue;
            }
            correct_requests.insert(ack);
        }
        return actions;
    }

    Actions apply_acks(i64 bucket, const SeqP &seq, vector<AckS> batch) {
        auto bit = buckets.find(bucket);
        if (bit == buckets.end()) throw EngineError("no such bucket");
        auto &clients = bit->second;

        std::unordered_set<AckS, AckHash> outstanding;
        for (const auto &req : batch) {
            auto cit = clients.find(req.client);
            if (cit == clients.end())
                throw EngineError("fastengine: batch references unknown client");
            ClientOutstandingReqs &co = cit->second;
            if (co.next_req_no != req.reqno)
                throw EngineError("fastengine: out-of-order batch req_no");
            auto crit = correct_requests.find(req);
            if (crit != correct_requests.end()) {
                correct_requests.erase(crit);
            } else {
                outstanding_requests.emplace(req, seq);
                outstanding.insert(req);
            }
            co.next_req_no += co.num_buckets;
            co.skip_previously_committed();
        }
        return seq->allocate(std::move(batch), &outstanding);
    }
};

// ---------------------------------------------------------------------------
// Active epoch (statemachine/epoch_active.py).
// ---------------------------------------------------------------------------

std::map<i64, i32> assign_buckets(const EpochCfgS &epoch_config,
                                  const NetConfigS &cfg) {
    std::set<i32> leaders(epoch_config.leaders.begin(),
                          epoch_config.leaders.end());
    std::map<i64, i32> buckets;
    i64 overflow_index = 0;
    const auto &nodes = cfg.nodes;
    for (i64 i = 0; i < cfg.nb; i++) {
        i32 natural = nodes[(size_t)((i + epoch_config.number) % (i64)nodes.size())];
        if (leaders.count(natural)) {
            buckets[i] = natural;
        } else {
            buckets[i] = epoch_config.leaders[(size_t)(
                overflow_index % (i64)epoch_config.leaders.size())];
            overflow_index += 1;
        }
    }
    return buckets;
}

struct PreprepareBuffer {
    i64 next_seq_no;
    MsgBuffer buffer;
};

struct ActiveEpoch {
    const Ctx *ctx;
    EpochCfgS epoch_config;
    InitParms my_config;
    shared_ptr<AllOutstandingReqs> outstanding_reqs;
    shared_ptr<Proposer> proposer;
    PersistedLog *persisted;
    CommitState *commit_state;
    std::map<i64, i32> buckets;
    deque<vector<SeqP>> sequences;
    vector<PreprepareBuffer> preprepare_buffers;
    std::map<i32, MsgBuffer> other_buffers;
    vector<i64> lowest_unallocated;
    i64 lowest_uncommitted;
    i64 last_committed_at_tick = 0;
    i64 ticks_since_progress = 0;
    i64 buffered = 0;  // shared live count across this epoch's buffers
    i64 nb, ci;
    vector<i64> owned_buckets;

    ActiveEpoch(const Ctx *c, const EpochCfgS &ecfg, PersistedLog *p,
                NodeBuffers *node_buffers, CommitState *cs,
                ClientTracker *client_tracker, InitParms mc)
        : ctx(c), epoch_config(ecfg), my_config(mc), persisted(p),
          commit_state(cs) {
        i64 starting_seq_no = cs->highest_commit;
        const NetConfigS &net_cfg = *cs->active_state->config;
        outstanding_reqs = std::make_shared<AllOutstandingReqs>(
            client_tracker->available_list, *cs->active_state);
        buckets = assign_buckets(ecfg, net_cfg);
        nb = (i64)buckets.size();
        ci = net_cfg.ci;
        for (i64 b = 0; b < nb; b++)
            if (buckets[b] == mc.id) owned_buckets.push_back(b);
        lowest_unallocated.assign((size_t)nb, 0);
        for (i64 i = 0; i < nb; i++) {
            i64 first_seq_no = starting_seq_no + i + 1;
            lowest_unallocated[(size_t)(first_seq_no % nb)] = first_seq_no;
        }
        lowest_uncommitted = cs->highest_commit + 1;
        proposer = std::make_shared<Proposer>(
            c, starting_seq_no, mc, client_tracker->ready_list, buckets);
        for (i64 i = 0; i < nb; i++) {
            PreprepareBuffer pb;
            pb.next_seq_no = lowest_unallocated[(size_t)i];
            pb.buffer.nb = node_buffers->node_buffer(buckets[i]);
            pb.buffer.wire = &c->wire;
            pb.buffer.group = &buffered;
            preprepare_buffers.push_back(std::move(pb));
        }
        for (i32 node : c->cfg.nodes) {
            MsgBuffer mb;
            mb.nb = node_buffers->node_buffer(node);
            mb.wire = &c->wire;
            mb.group = &buffered;
            other_buffers.emplace(node, std::move(mb));
        }
    }

    i64 seq_to_bucket(i64 seq_no) const { return seq_no % nb; }
    i64 low_watermark() const { return sequences.front()[0]->seq_no; }
    i64 high_watermark() const {
        if (sequences.empty()) return commit_state->low_watermark;
        return sequences.back().back()->seq_no;
    }
    bool in_watermarks(i64 seq_no) const {
        return !sequences.empty() && low_watermark() <= seq_no &&
               seq_no <= high_watermark();
    }

    SeqP sequence(i64 seq_no) {
        i64 index = (seq_no - low_watermark()) / ci;
        i64 offset = (seq_no - low_watermark()) % ci;
        SeqP seq = sequences[(size_t)index][(size_t)offset];
        if (seq->seq_no != seq_no)
            throw EngineError("sequence retrieved had unexpected seq_no");
        return seq;
    }

    Applyable filter(i32 source, const MsgS &msg) {
        if (msg.t == MT::Preprepare) {
            i64 seq_no = msg.seq;
            i64 bucket = seq_to_bucket(seq_no);
            if (buckets[bucket] != source) return Applyable::INVALID;
            if (seq_no > epoch_config.planned_expiration)
                return Applyable::INVALID;
            if (seq_no > high_watermark()) return Applyable::FUTURE;
            if (seq_no < low_watermark()) return Applyable::PAST;
            i64 next_preprepare = preprepare_buffers[(size_t)bucket].next_seq_no;
            if (seq_no < next_preprepare) return Applyable::PAST;
            if (seq_no > next_preprepare) return Applyable::FUTURE;
            return Applyable::CURRENT;
        }
        if (msg.t == MT::Prepare) {
            i64 seq_no = msg.seq;
            i64 bucket = seq_to_bucket(seq_no);
            if (buckets[bucket] == source) return Applyable::INVALID;
            if (seq_no > epoch_config.planned_expiration)
                return Applyable::INVALID;
            if (seq_no < low_watermark()) return Applyable::PAST;
            if (seq_no > high_watermark()) return Applyable::FUTURE;
            return Applyable::CURRENT;
        }
        if (msg.t == MT::Commit) {
            i64 seq_no = msg.seq;
            if (seq_no > epoch_config.planned_expiration)
                return Applyable::INVALID;
            if (seq_no < low_watermark()) return Applyable::PAST;
            if (seq_no > high_watermark()) return Applyable::FUTURE;
            return Applyable::CURRENT;
        }
        throw EngineError("unexpected msg type in active epoch filter");
    }

    Actions apply(i32 source, const MsgP &msg) {
        Actions actions;
        if (msg->t == MT::Preprepare) {
            i64 bucket = seq_to_bucket(msg->seq);
            PreprepareBuffer &buffer = preprepare_buffers[(size_t)bucket];
            MsgP next_msg = msg;
            while (next_msg) {
                concat(actions, apply_preprepare_msg(source, next_msg->seq,
                                                     next_msg->acks));
                buffer.next_seq_no += nb;
                next_msg = buffer.buffer.next([this, source](const MsgS &m) {
                    return filter(source, m);
                });
            }
        } else if (msg->t == MT::Prepare) {
            concat(actions,
                   sequence(msg->seq)->apply_prepare_msg(source, msg->dig));
        } else if (msg->t == MT::Commit) {
            concat(actions, apply_commit_msg(source, msg->seq, msg->dig));
        } else {
            throw EngineError("unexpected msg type in active epoch apply");
        }
        return actions;
    }

    Actions step(i32 source, const MsgP &msg) {
        if (msg->t == MT::Prepare) return step_prepare(source, msg);
        if (msg->t == MT::Commit) return step_commit(source, msg);
        Applyable verdict = filter(source, *msg);
        if (verdict == Applyable::CURRENT) return apply(source, msg);
        if (verdict == Applyable::FUTURE) {
            if (msg->t == MT::Preprepare) {
                i64 bucket = seq_to_bucket(msg->seq);
                preprepare_buffers[(size_t)bucket].buffer.store(msg);
            } else {
                other_buffers.at(source).store(msg);
            }
        }
        return Actions();
    }

    Actions step_prepare(i32 source, const MsgP &msg) {
        i64 seq_no = msg->seq;
        if (buckets[seq_no % nb] == source) return Actions();  // INVALID
        if (seq_no > epoch_config.planned_expiration) return Actions();
        i64 low = sequences.front()[0]->seq_no;
        if (seq_no < low) return Actions();  // PAST
        if (seq_no > sequences.back().back()->seq_no) {
            other_buffers.at(source).store(msg);  // FUTURE
            return Actions();
        }
        i64 offset = seq_no - low;
        SeqP seq = sequences[(size_t)(offset / ci)][(size_t)(offset % ci)];
        return seq->apply_prepare_msg(source, msg->dig);
    }

    Actions step_commit(i32 source, const MsgP &msg) {
        i64 seq_no = msg->seq;
        if (seq_no > epoch_config.planned_expiration) return Actions();
        i64 low = sequences.front()[0]->seq_no;
        if (seq_no < low) return Actions();  // PAST
        i64 high = sequences.back().back()->seq_no;
        if (seq_no > high) {
            other_buffers.at(source).store(msg);  // FUTURE
            return Actions();
        }
        i64 offset = seq_no - low;
        SeqP seq = sequences[(size_t)(offset / ci)][(size_t)(offset % ci)];
        seq->apply_commit_msg(source, msg->dig);
        if (seq->state != SeqState::COMMITTED || seq_no != lowest_uncommitted)
            return Actions();
        commit_cascade();
        return Actions();
    }

    // Envelope vote application — replicates the Python native-plane path
    // (voteplane.py + ackplane.cpp seq_apply_core + machine.py MsgBatch arm):
    // Phase A applies every vote's mask/count update in envelope order,
    // recording fallbacks (wrong epoch / future) and transition hints;
    // Phase B runs the records in order, re-validating each quorum.
    template <typename StepFn>
    Actions apply_envelope_votes(const vector<MsgP> &votes, i32 source,
                                 StepFn &&generic_step) {
        struct Rec {
            bool fallback;
            size_t idx;
            int kind;
            i64 seq;
        };
        vector<Rec> records;
        for (size_t k = 0; k < votes.size(); k++) {
            const MsgS &m = *votes[k];
            if (m.t != MT::Prepare && m.t != MT::Commit) continue;  // rest
            int kind = m.t == MT::Prepare ? 0 : 1;
            if (m.epoch != epoch_config.number) {
                records.push_back({true, k, 0, 0});
                continue;
            }
            i64 seq_no = m.seq;
            i64 low = sequences.front()[0]->seq_no;
            if (seq_no < low) continue;  // PAST
            if (kind == 0 && nb > 0 && buckets[seq_no % nb] == source)
                continue;  // INVALID: owners never send Prepare
            if (seq_no > epoch_config.planned_expiration) continue;  // INVALID
            if (seq_no > sequences.back().back()->seq_no) {
                records.push_back({true, k, 0, 0});  // FUTURE
                continue;
            }
            i64 offset = seq_no - low;
            Sequence &s =
                *sequences[(size_t)(offset / ci)][(size_t)(offset % ci)];
            i32 key = s.key_of(m.dig);
            i32 expected = s.key_of(s.digest);
            bool matches = key == expected;
            bool hint = false;
            if (kind == 0) {
                if (s.prep_mask.test(source) || s.commit_mask.test(source))
                    continue;  // dup
                s.prep_mask.set(source);
                if (source == s.my_id) s.my_prepare_digest = m.dig;
                i64 *cnt = s.prepares.get(key);
                i64 n = cnt ? *cnt + 1 : 1;
                s.prepares.put(key, n);
                if (s.state == SeqState::PREPREPARED) {
                    if (matches && n >= ctx->iq) hint = true;
                } else if (s.state == SeqState::READY ||
                           s.state == SeqState::PENDING_REQUESTS) {
                    hint = true;
                }
            } else {
                if (s.commit_mask.test(source)) continue;  // dup
                s.commit_mask.set(source);
                i64 *cnt = s.commits.get(key);
                i64 n = cnt ? *cnt + 1 : 1;
                s.commits.put(key, n);
                if (s.state == SeqState::PREPARED && matches && n >= ctx->iq)
                    hint = true;
            }
            if (hint) records.push_back({false, k, kind, seq_no});
        }
        Actions actions;
        for (const Rec &rec : records) {
            if (rec.fallback) {
                concat(actions, generic_step(source, votes[rec.idx]));
                continue;
            }
            SeqP seq = sequence(rec.seq);
            if (rec.kind == 0) {
                SeqState s = seq->state;
                if (s == SeqState::PREPREPARED || s == SeqState::READY ||
                    s == SeqState::PENDING_REQUESTS)
                    concat(actions, seq->advance_state());
            } else {
                seq->check_commit_quorum();
            }
            if (seq->state == SeqState::COMMITTED &&
                seq->seq_no == lowest_uncommitted)
                commit_cascade();
        }
        return actions;
    }

    void commit_cascade() {
        i64 low = sequences.front()[0]->seq_no;
        i64 high = sequences.back().back()->seq_no;
        i64 lowest = lowest_uncommitted;
        while (lowest <= high) {
            i64 offset = lowest - low;
            SeqP seq = sequences[(size_t)(offset / ci)][(size_t)(offset % ci)];
            if (seq->state != SeqState::COMMITTED) break;
            commit_state->commit(seq->q_entry);
            lowest += 1;
        }
        lowest_uncommitted = lowest;
    }

    Actions apply_preprepare_msg(i32 source, i64 seq_no, vector<AckS> batch) {
        SeqP seq = sequence(seq_no);
        if (seq->owner == my_config.id)
            return seq->apply_prepare_msg(source, seq->digest);
        i64 bucket = seq_to_bucket(seq_no);
        if (seq_no != lowest_unallocated[(size_t)bucket])
            throw EngineError("step should defer all but the next expected preprepare");
        lowest_unallocated[(size_t)bucket] += nb;
        return outstanding_reqs->apply_acks(bucket, seq, std::move(batch));
    }

    Actions apply_commit_msg(i32 source, i64 seq_no, i32 dig) {
        SeqP seq = sequence(seq_no);
        seq->apply_commit_msg(source, dig);
        if (seq->state != SeqState::COMMITTED || seq_no != lowest_uncommitted)
            return Actions();
        commit_cascade();
        return Actions();
    }

    Actions apply_batch_hash_result(i64 seq_no, i32 digest) {
        if (!in_watermarks(seq_no)) return Actions();
        return sequence(seq_no)->apply_batch_hash_result(digest);
    }

    // move_low_watermark -> (actions, epoch_done)
    std::pair<Actions, bool> move_low_watermark(i64 seq_no) {
        if (seq_no == epoch_config.planned_expiration)
            return {Actions(), true};
        if (seq_no == commit_state->stop_at_seq_no) return {Actions(), true};
        Actions actions = advance();
        while (seq_no > low_watermark()) sequences.pop_front();
        return {std::move(actions), false};
    }

    Actions drain_buffers() {
        Actions actions;
        if (!buffered) return actions;
        for (i64 bucket = 0; bucket < nb; bucket++) {
            PreprepareBuffer &buffer = preprepare_buffers[(size_t)bucket];
            if (buffer.buffer.empty()) continue;
            i32 source = buckets[bucket];
            MsgP next_msg = buffer.buffer.next(
                [this, source](const MsgS &m) { return filter(source, m); });
            if (!next_msg) continue;
            concat(actions, apply(source, next_msg));
        }
        for (i32 node : ctx->cfg.nodes) {
            MsgBuffer &other = other_buffers.at(node);
            if (other.empty()) continue;
            other.iterate(
                [this, node](const MsgS &m) { return filter(node, m); },
                [this, node, &actions](MsgP m) {
                    concat(actions, apply(node, m));
                });
        }
        return actions;
    }

    bool needs_advance() {
        i64 hw = high_watermark();
        if (hw < epoch_config.planned_expiration &&
            hw < commit_state->stop_at_seq_no)
            return true;
        if (buffered) return true;
        if (proposer->ready_iterator->has_next()) return true;
        for (i64 bucket : owned_buckets) {
            i64 seq_no = lowest_unallocated[(size_t)bucket];
            if (seq_no <= hw &&
                proposer->proposal_bucket(bucket)->has_pending(seq_no))
                return true;
        }
        return false;
    }

    Actions advance() {
        Actions actions;
        if (high_watermark() > epoch_config.planned_expiration)
            throw EngineError("window extends beyond planned expiration");
        if (high_watermark() > commit_state->stop_at_seq_no)
            throw EngineError("window extends beyond the stop sequence");

        while (high_watermark() < epoch_config.planned_expiration &&
               high_watermark() < commit_state->stop_at_seq_no) {
            i64 base = high_watermark() + 1;
            concat(actions, persisted->append(pe_n(base, epoch_config)));
            vector<SeqP> chunk;
            for (i64 i = 0; i < ci; i++) {
                chunk.push_back(std::make_shared<Sequence>(
                    ctx, buckets[seq_to_bucket(base + i)],
                    epoch_config.number, base + i, persisted, my_config.id));
            }
            sequences.push_back(std::move(chunk));
        }

        concat(actions, drain_buffers());
        proposer->advance(lowest_uncommitted);

        for (i64 bucket : owned_buckets) {
            ProposalBucket *prb = proposer->proposal_bucket(bucket);
            while (true) {
                i64 seq_no = lowest_unallocated[(size_t)bucket];
                if (seq_no > high_watermark()) break;
                if (!prb->has_pending(seq_no)) break;
                SeqP seq = sequence(seq_no);
                concat(actions, seq->allocate_as_owner(prb->next()));
                lowest_unallocated[(size_t)bucket] += nb;
            }
        }
        return actions;
    }

    Actions tick() {
        if (last_committed_at_tick < commit_state->highest_commit) {
            last_committed_at_tick = commit_state->highest_commit;
            ticks_since_progress = 0;
            return Actions();
        }
        ticks_since_progress += 1;
        Actions actions;

        if (ticks_since_progress > my_config.suspect_ticks) {
            actions.push_back(act_send(ctx->bcast,
                                       mk_suspect(epoch_config.number)));
            concat(actions, persisted->append(pe_suspect(epoch_config.number)));
        }
        if (my_config.heartbeat_ticks == 0 ||
            ticks_since_progress % my_config.heartbeat_ticks != 0)
            return actions;

        for (i64 bucket : owned_buckets) {
            i64 unallocated_seq_no = lowest_unallocated[(size_t)bucket];
            if (unallocated_seq_no > high_watermark()) continue;
            SeqP seq = sequence(unallocated_seq_no);
            ProposalBucket *prb = proposer->proposal_bucket(bucket);
            vector<CRP> client_reqs;
            if (prb->has_outstanding(unallocated_seq_no))
                client_reqs = prb->next();
            concat(actions, seq->allocate_as_owner(std::move(client_reqs)));
            lowest_unallocated[(size_t)bucket] += nb;
        }
        return actions;
    }
};

// ---------------------------------------------------------------------------
// Epoch-change parsing and ack accumulation (statemachine/epoch_change.py).
// ---------------------------------------------------------------------------

struct ParsedEC {
    EpochChangeP underlying;
    i64 low_watermark = 0;
    std::map<i64, ECSetEntryS> p_set;              // seq -> entry
    std::map<i64, std::map<i64, i32>> q_set;       // seq -> epoch -> digest
    std::set<i32> acks;
};
using ParsedECP = shared_ptr<ParsedEC>;

// try_parse (raising variant returns nullptr on malformed content).
ParsedECP try_parse_epoch_change(const EpochChangeP &underlying) {
    if (underlying->checkpoints.empty()) return nullptr;
    auto out = std::make_shared<ParsedEC>();
    out->underlying = underlying;
    out->low_watermark = underlying->checkpoints[0].first;
    std::set<i64> seen_cp;
    for (const auto &cp : underlying->checkpoints) {
        out->low_watermark = std::min(out->low_watermark, cp.first);
        if (seen_cp.count(cp.first)) return nullptr;
        seen_cp.insert(cp.first);
    }
    for (const auto &e : underlying->p_set) {
        if (out->p_set.count(e.seq)) return nullptr;
        out->p_set.emplace(e.seq, e);
    }
    for (const auto &e : underlying->q_set) {
        auto &views = out->q_set[e.seq];
        if (views.count(e.epoch)) return nullptr;
        views.emplace(e.epoch, e.dig);
    }
    return out;
}

struct EpochChangeVotes {
    // (digest, parsed) insertion-ordered.
    vector<std::pair<i32, ParsedECP>> parsed_by_digest;
    i32 strong_cert = -1;

    ParsedECP get(i32 digest) const {
        for (const auto &pr : parsed_by_digest)
            if (pr.first == digest) return pr.second;
        return nullptr;
    }

    void add_ack(i32 source, const EpochChangeP &msg, i32 digest, i64 iq) {
        ParsedECP parsed = get(digest);
        if (!parsed) {
            parsed = try_parse_epoch_change(msg);
            if (!parsed) return;  // malformed; drop
            parsed_by_digest.emplace_back(digest, parsed);
        }
        parsed->acks.insert(source);
        if (strong_cert == -1 && (i64)parsed->acks.size() >= iq)
            strong_cert = digest;
    }
};

// construct_new_epoch_config (statemachine/stateless.py:164-315).
NewEpochCfgP construct_new_epoch_config(
    const Ctx *ctx, const NetConfigS &config, const vector<i32> &new_leaders,
    const std::map<i32, ParsedECP> &epoch_changes) {
    // (seq, value) -> supporters, insertion-ordered.
    vector<std::pair<std::pair<i64, i32>, vector<i32>>> checkpoint_supporters;
    i64 new_epoch_number = 0;
    for (i32 node : config.nodes) {
        auto it = epoch_changes.find(node);
        if (it == epoch_changes.end()) continue;
        const ParsedEC &ec = *it->second;
        new_epoch_number = ec.underlying->new_epoch;
        std::set<std::pair<i64, i32>> seen;
        for (const auto &cp : ec.underlying->checkpoints) {
            std::pair<i64, i32> key(cp.first, cp.second);
            if (seen.count(key)) continue;
            seen.insert(key);
            bool found = false;
            for (auto &pr : checkpoint_supporters)
                if (pr.first == key) {
                    pr.second.push_back(node);
                    found = true;
                    break;
                }
            if (!found)
                checkpoint_supporters.emplace_back(key, vector<i32>{node});
        }
    }

    bool have_max = false;
    std::pair<i64, i32> max_checkpoint{0, 0};
    for (const auto &pr : checkpoint_supporters) {
        if ((i64)pr.second.size() < ctx->wq) continue;
        i64 lower_watermarks = 0;
        for (const auto &ec : epoch_changes)
            if (ec.second->low_watermark <= pr.first.first) lower_watermarks++;
        if (lower_watermarks < ctx->iq) continue;
        if (!have_max) {
            max_checkpoint = pr.first;
            have_max = true;
            continue;
        }
        if (max_checkpoint.first > pr.first.first) continue;
        if (max_checkpoint.first == pr.first.first)
            throw EngineError("two correct quorums disagree on checkpoint value");
        max_checkpoint = pr.first;
    }
    if (!have_max) return nullptr;

    i64 cp_seq = max_checkpoint.first;
    i32 cp_value = max_checkpoint.second;
    i64 window = 2 * config.ci;
    vector<i32> final_preprepares((size_t)window, 0);
    bool any_selected = false;

    vector<vector<const ECSetEntryS *>> candidates((size_t)window);
    vector<i64> entry_counts((size_t)window, 0);
    for (i32 node : config.nodes) {
        auto it = epoch_changes.find(node);
        if (it == epoch_changes.end()) continue;
        const ParsedEC &node_ec = *it->second;
        i64 lw = node_ec.low_watermark;
        for (const auto &pr : node_ec.p_set) {
            i64 p_off = pr.first - cp_seq - 1;
            if (0 <= p_off && p_off < window) {
                candidates[(size_t)p_off].push_back(&pr.second);
                if (lw < pr.first) entry_counts[(size_t)p_off]++;
            }
        }
    }
    vector<i64> sorted_lws;
    for (const auto &ec : epoch_changes)
        sorted_lws.push_back(ec.second->low_watermark);
    std::sort(sorted_lws.begin(), sorted_lws.end());

    for (i64 offset = 0; offset < window; offset++) {
        i64 seq_no = cp_seq + 1 + offset;
        const ECSetEntryS *selected = nullptr;
        for (const ECSetEntryS *entry : candidates[(size_t)offset]) {
            i64 a1 = 0;
            for (const auto &opr : epoch_changes) {
                const ParsedEC &other = *opr.second;
                if (other.low_watermark >= seq_no) continue;
                auto oit = other.p_set.find(seq_no);
                if (oit == other.p_set.end() ||
                    oit->second.epoch < entry->epoch) {
                    a1++;
                    continue;
                }
                if (oit->second.epoch > entry->epoch) continue;
                if (oit->second.dig == entry->dig) a1++;
            }
            if (a1 < ctx->iq) continue;
            i64 a2 = 0;
            for (const auto &opr : epoch_changes) {
                const ParsedEC &other = *opr.second;
                auto qit = other.q_set.find(seq_no);
                if (qit == other.q_set.end() || qit->second.empty()) continue;
                for (const auto &ed : qit->second) {
                    if (ed.first >= entry->epoch && ed.second == entry->dig) {
                        a2++;
                        break;
                    }
                }
            }
            if (a2 < ctx->wq) continue;
            selected = entry;
            break;
        }
        if (selected) {
            final_preprepares[(size_t)offset] = selected->dig;
            any_selected = true;
            continue;
        }
        i64 b_count =
            (i64)(std::lower_bound(sorted_lws.begin(), sorted_lws.end(),
                                   seq_no) -
                  sorted_lws.begin()) -
            entry_counts[(size_t)offset];
        if (b_count < ctx->iq) return nullptr;
    }

    auto out = std::make_shared<NewEpochCfgS>();
    out->config.number = new_epoch_number;
    out->config.leaders = new_leaders;
    out->config.planned_expiration = cp_seq + config.mel;
    out->cp_seq = cp_seq;
    out->cp_value = cp_value;
    if (any_selected) out->final_preprepares = std::move(final_preprepares);
    return out;
}

// ---------------------------------------------------------------------------
// Epoch target: the 11-state lifecycle machine (statemachine/epoch_target.py).
// ---------------------------------------------------------------------------

struct ETS {
    enum V {
        PREPENDING = 0, PENDING = 1, VERIFYING = 2, FETCHING = 3,
        ECHOING = 4, READYING = 5, RESUMING = 6, READY = 7,
        IN_PROGRESS = 8, ENDING = 9, DONE = 10,
    };
};

struct EpochTarget {
    const Ctx *ctx;
    int state = ETS::PREPENDING;
    CommitState *commit_state;
    i64 state_ticks = 0;
    i64 number;
    i64 starting_seq_no = 0;
    std::map<i32, EpochChangeVotes> changes;
    std::map<i32, ParsedECP> strong_changes;
    vector<std::pair<NewEpochCfgP, std::set<i32>>> echos, readies;
    shared_ptr<ActiveEpoch> active_epoch;
    std::set<i32> suspicions;
    MsgP my_new_epoch;              // NewEpoch message (nullptr = None)
    ParsedECP my_epoch_change;
    vector<i32> my_leader_choice;
    bool have_leader_choice = false;
    MsgP leader_new_epoch;          // NewEpoch message
    NewEpochCfgP network_new_epoch;
    // Crash-recovery resume (no Bracha broadcast ran): the epoch config
    // from the last NEntry, used to rebuild the active epoch at READY
    // (epoch_target.py resume_epoch_config).
    EpochCfgS resume_epoch_config{};
    bool have_resume_config = false;
    bool is_primary;
    std::map<i32, MsgBuffer> prestart_buffers;
    PersistedLog *persisted;
    NodeBuffers *node_buffers;
    ClientTracker *client_tracker;
    Disseminator *client_hash_disseminator;
    BatchTracker *batch_tracker;
    NetCfgP network_config;  // the active consensused config at creation
    InitParms my_config;
    // digest state per EC content: (digest | -1 pending | -2 fresh,
    // waiting (source, origin) pairs).  The content-keyed map is the
    // source of truth; the pointer cache avoids hashing the multi-KB
    // content key per ack (EC objects are shared across every receiver of
    // a broadcast; unordered_map values are node-stable under rehash).
    std::unordered_map<string, std::pair<i32, vector<std::pair<i32, i32>>>>
        ec_digests;
    std::unordered_map<const void *,
                       std::pair<i32, vector<std::pair<i32, i32>>> *>
        ec_entry_by_ptr;

    std::pair<i32, vector<std::pair<i32, i32>>> &ec_entry(
        const EpochChangeP &ec) {
        auto pit = ec_entry_by_ptr.find((const void *)ec.get());
        if (pit != ec_entry_by_ptr.end()) return *pit->second;
        ec_fill_hash_cache(ctx->intern, *ec);
        auto [it, inserted] = ec_digests.try_emplace(
            ec->hash_key_cache,
            std::make_pair((i32)-2, vector<std::pair<i32, i32>>()));
        (void)inserted;
        ec_entry_by_ptr.emplace((const void *)ec.get(), &it->second);
        return it->second;
    }

    EpochTarget(const Ctx *c, i64 num, PersistedLog *p, NodeBuffers *nbufs,
                CommitState *cs, ClientTracker *ct, Disseminator *dis,
                BatchTracker *bt, NetCfgP ncfg, InitParms mc)
        : ctx(c), commit_state(cs), number(num), persisted(p),
          node_buffers(nbufs), client_tracker(ct),
          client_hash_disseminator(dis), batch_tracker(bt),
          network_config(std::move(ncfg)), my_config(mc) {
        is_primary = num % (i64)c->cfg.nodes.size() == mc.id;
        for (i32 node : c->cfg.nodes) {
            MsgBuffer mb;
            mb.nb = nbufs->node_buffer(node);
            mb.wire = &c->wire;
            prestart_buffers.emplace(node, std::move(mb));
        }
    }

    Actions step(i32 source, const MsgP &msg) {
        if (state < ETS::IN_PROGRESS) {
            prestart_buffers.at(source).store(msg);
            return Actions();
        }
        if (state == ETS::DONE) return Actions();
        return active_epoch->step(source, msg);
    }

    MsgP construct_new_epoch(const vector<i32> &new_leaders) {
        if ((i64)strong_changes.size() < ctx->iq)
            throw EngineError("need more acked epoch changes");
        NewEpochCfgP new_config = construct_new_epoch_config(
            ctx, *network_config, new_leaders, strong_changes);
        if (!new_config) return nullptr;
        auto m = std::make_shared<MsgS>();
        m->t = MT::NewEpoch;
        m->necfg = new_config;
        for (i32 node : ctx->cfg.nodes) {
            if (!strong_changes.count(node)) continue;
            m->remote_changes.emplace_back(node,
                                           changes.at(node).strong_cert);
        }
        return m;
    }

    void verify_new_epoch_state() {
        std::map<i32, ParsedECP> epoch_changes;
        for (const auto &remote : leader_new_epoch->remote_changes) {
            if (epoch_changes.count(remote.first)) return;  // malformed
            auto vit = changes.find(remote.first);
            if (vit == changes.end()) return;
            ParsedECP parsed = vit->second.get(remote.second);
            if (!parsed || (i64)parsed->acks.size() < ctx->wq) return;
            epoch_changes.emplace(remote.first, parsed);
        }
        NewEpochCfgP reconstructed = construct_new_epoch_config(
            ctx, *network_config, leader_new_epoch->necfg->config.leaders,
            epoch_changes);
        if (!reconstructed || !(*reconstructed == *leader_new_epoch->necfg))
            return;  // byzantine primary
        state = ETS::FETCHING;
    }

    Actions fetch_new_epoch_state() {
        const NewEpochCfgS &nec = *leader_new_epoch->necfg;
        if (commit_state->transferring)
            return Actions();  // wait for state transfer first
        if (nec.cp_seq > commit_state->highest_commit)
            return commit_state->transfer_to(nec.cp_seq, nec.cp_value);

        Actions actions;
        bool fetch_pending = false;
        for (size_t i = 0; i < nec.final_preprepares.size(); i++) {
            i32 digest = nec.final_preprepares[i];
            if (digest == 0) continue;  // null request
            i64 seq_no = (i64)i + nec.cp_seq + 1;
            if (seq_no <= commit_state->highest_commit) continue;

            vector<i32> sources;
            for (const auto &remote : leader_new_epoch->remote_changes) {
                ParsedECP parsed = changes.at(remote.first).get(remote.second);
                auto qit = parsed->q_set.find(seq_no);
                if (qit == parsed->q_set.end()) continue;
                for (const auto &ed : qit->second)
                    if (ed.second == digest) {
                        sources.push_back(remote.first);
                        break;
                    }
            }
            if ((i64)sources.size() < ctx->wq)
                throw EngineError("too few sources for new-epoch batch");

            BatchRec *batch = batch_tracker->get_batch_mut(digest);
            if (!batch) {
                concat(actions,
                       batch_tracker->fetch_batch(seq_no, digest, sources));
                fetch_pending = true;
                continue;
            }
            batch->observed_for.insert(seq_no);
            for (const auto &request_ack : batch->request_acks) {
                CRP cr;
                for (i32 node : sources)
                    cr = client_hash_disseminator->ack(actions, node,
                                                       request_ack, true);
                if (cr->stored) continue;
                fetch_pending = true;
                concat(actions, cr->fetch());
                client_hash_disseminator->note_fetching(request_ack);
            }
        }
        if (fetch_pending) return actions;
        if (nec.cp_seq > commit_state->low_watermark) return actions;

        state = ETS::ECHOING;
        if (nec.cp_seq == commit_state->stop_at_seq_no &&
            !nec.final_preprepares.empty())
            // Provably unreachable among correct nodes (see the proof in
            // epoch_target.py fetch_new_epoch_state / docs/Divergences.md
            // #9): window extension never passes stop_at, so A2 support
            // for a batch past a halted boundary needs f+1 byzantine
            // attestations, and verify_new_epoch_state's reconstruction
            // rejects a fabricated carryover before FETCHING.
            throw EngineError(
                "verified NewEpoch carries batches past a reconfiguration "
                "boundary (impossible for <= f byzantine nodes)");

        concat(actions,
               persisted->append(pe_n(nec.cp_seq + 1, nec.config)));
        for (size_t i = 0; i < nec.final_preprepares.size(); i++) {
            i32 digest = nec.final_preprepares[i];
            i64 seq_no = (i64)i + nec.cp_seq + 1;
            if (digest == 0) {
                auto q = std::make_shared<QEntryS>();
                q->seq = seq_no;
                q->dig = 0;
                concat(actions, persisted->append(pe_q(q)));
                continue;
            }
            const BatchRec *batch = batch_tracker->get_batch(digest);
            if (!batch) {
                if (seq_no <= commit_state->highest_commit)
                    // Already committed (fetch loop skipped it) and
                    // possibly checkpoint-truncated from the tracker;
                    // its QEntry is in the log from the original commit
                    // (mirrors epoch_target.py fetch_new_epoch_state).
                    continue;
                throw EngineError("batch verified above is now missing");
            }
            auto q = std::make_shared<QEntryS>();
            q->seq = seq_no;
            q->dig = digest;
            q->reqs = batch->request_acks;
            concat(actions, persisted->append(pe_q(q)));
            if (seq_no % network_config->ci == 0 &&
                seq_no < commit_state->stop_at_seq_no)
                concat(actions,
                       persisted->append(pe_n(seq_no + 1, nec.config)));
        }
        starting_seq_no = nec.cp_seq + (i64)nec.final_preprepares.size() + 1;

        auto echo = std::make_shared<MsgS>();
        echo->t = MT::NewEpochEcho;
        echo->necfg = leader_new_epoch->necfg;
        actions.push_back(act_send(ctx->bcast, echo));
        return actions;
    }

    Actions repeat_epoch_change_broadcast() {
        auto m = std::make_shared<MsgS>();
        m->t = MT::EpochChange;
        m->ec = my_epoch_change->underlying;
        Actions a;
        a.push_back(act_send(ctx->bcast, m));
        return a;
    }

    Actions tick_prepending() {
        if (!my_new_epoch) {
            i64 half = my_config.new_epoch_timeout_ticks / 2;
            if (half && state_ticks % half == 0 && my_epoch_change)
                return repeat_epoch_change_broadcast();
            return Actions();
        }
        if (is_primary) {
            Actions a;
            a.push_back(act_send(ctx->bcast, my_new_epoch));
            return a;
        }
        return Actions();
    }

    Actions tick_pending() {
        if (!my_new_epoch || !my_epoch_change) return Actions();
        i64 pending_ticks = state_ticks % my_config.new_epoch_timeout_ticks;
        if (is_primary) {
            if (pending_ticks % 2 == 0) {
                Actions a;
                a.push_back(act_send(ctx->bcast, my_new_epoch));
                return a;
            }
        } else {
            if (pending_ticks == 0) {
                Actions a;
                a.push_back(act_send(
                    ctx->bcast,
                    mk_suspect(my_new_epoch->necfg->config.number)));
                concat(a, persisted->append(
                              pe_suspect(my_new_epoch->necfg->config.number)));
                return a;
            }
            if (pending_ticks % 2 == 0) return repeat_epoch_change_broadcast();
        }
        return Actions();
    }

    Actions tick() {
        state_ticks += 1;
        if (state == ETS::PREPENDING) return tick_prepending();
        if (state <= ETS::RESUMING) return tick_pending();
        if (state <= ETS::IN_PROGRESS) return active_epoch->tick();
        return Actions();
    }

    Actions apply_epoch_change_msg(i32 source, const MsgP &msg) {
        Actions actions;
        if (source != my_config.id) {
            auto ack = std::make_shared<MsgS>();
            ack->t = MT::EpochChangeAck;
            ack->originator = source;
            ack->ec = msg->ec;
            actions.push_back(act_send(ctx->bcast, ack));
        }
        concat(actions, apply_epoch_change_ack_msg(source, source, msg->ec));
        return actions;
    }

    Actions apply_epoch_change_ack_msg(i32 source, i32 origin,
                                       const EpochChangeP &ec) {
        auto &entry = ec_entry(ec);
        if (entry.first >= 0)
            return apply_ec_digest(source, origin, ec, entry.first);
        if (entry.first == -1) {  // hash already in flight
            entry.second.emplace_back(source, origin);
            return Actions();
        }
        entry.first = -1;
        HashOriginS ho;
        ho.t = OT::EpochChange;
        ho.source = source;
        ho.origin = origin;
        ho.ec = ec;
        Actions actions;
        // Small ECs stay multi-part so the host-floor classification (and
        // with it the device-plane routing) is unchanged from the
        // pre-cache behavior; only large certs use the single-part cache.
        if (ec->hash_joined_cache.size() < 512)
            actions.push_back(act_hash(ec_hash_data(ctx->intern, *ec),
                                       std::move(ho)));
        else
            actions.push_back(act_hash(vector<string>{ec->hash_joined_cache},
                                       std::move(ho)));
        return actions;
    }

    Actions apply_epoch_change_digest(const HashOriginS &origin, i32 digest) {
        const EpochChangeP &msg = origin.ec;
        auto &entry = ec_entry(msg);
        vector<std::pair<i32, i32>> waiters;
        if (entry.first == -1) waiters = std::move(entry.second);
        entry.first = digest;
        entry.second.clear();
        Actions actions =
            apply_ec_digest(origin.source, origin.origin, msg, digest);
        for (const auto &w : waiters)
            concat(actions, apply_ec_digest(w.first, w.second, msg, digest));
        return actions;
    }

    Actions apply_ec_digest(i32 source_node, i32 origin_node,
                            const EpochChangeP &msg, i32 digest) {
        EpochChangeVotes &votes = changes[origin_node];
        votes.add_ack(source_node, msg, digest, ctx->iq);
        if (votes.strong_cert != -1 && !strong_changes.count(origin_node)) {
            strong_changes.emplace(origin_node, votes.get(votes.strong_cert));
            return advance_state();
        }
        return Actions();
    }

    Actions check_epoch_quorum() {
        if ((i64)strong_changes.size() < ctx->iq || !my_epoch_change)
            return Actions();
        my_new_epoch = construct_new_epoch(my_leader_choice);
        if (!my_new_epoch) return Actions();
        state_ticks = 0;
        state = ETS::PENDING;
        if (is_primary) {
            Actions a;
            a.push_back(act_send(ctx->bcast, my_new_epoch));
            return a;
        }
        return Actions();
    }

    Actions apply_new_epoch_msg(const MsgP &msg) {
        leader_new_epoch = msg;
        return advance_state();
    }

    std::set<i32> *cfg_set(vector<std::pair<NewEpochCfgP, std::set<i32>>> &m,
                           const NewEpochCfgP &config) {
        for (auto &pr : m)
            if (*pr.first == *config) return &pr.second;
        m.emplace_back(config, std::set<i32>());
        return &m.back().second;
    }

    Actions apply_new_epoch_echo_msg(i32 source, const NewEpochCfgP &config) {
        cfg_set(echos, config)->insert(source);
        return advance_state();
    }

    Actions check_new_epoch_echo_quorum() {
        Actions actions;
        for (auto &pr : echos) {
            if ((i64)pr.second.size() < ctx->iq) continue;
            state = ETS::READYING;
            const NewEpochCfgS &config = *pr.first;
            for (size_t i = 0; i < config.final_preprepares.size(); i++) {
                i64 seq_no = (i64)i + config.cp_seq + 1;
                concat(actions,
                       persisted->append(
                           pe_p(seq_no, config.final_preprepares[i])));
            }
            auto ready = std::make_shared<MsgS>();
            ready->t = MT::NewEpochReady;
            ready->necfg = pr.first;
            actions.push_back(act_send(ctx->bcast, ready));
            return actions;
        }
        return actions;
    }

    Actions apply_new_epoch_ready_msg(i32 source, const NewEpochCfgP &config) {
        if (state > ETS::READYING) return Actions();
        std::set<i32> *rs = cfg_set(readies, config);
        rs->insert(source);
        if ((i64)rs->size() < ctx->wq) return Actions();
        if (state < ETS::ECHOING) return advance_state();
        if (state < ETS::READYING) {
            state = ETS::READYING;
            auto ready = std::make_shared<MsgS>();
            ready->t = MT::NewEpochReady;
            ready->necfg = config;
            Actions a;
            a.push_back(act_send(ctx->bcast, ready));
            return a;
        }
        return advance_state();
    }

    void check_new_epoch_ready_quorum() {
        for (auto &pr : readies) {
            if ((i64)pr.second.size() < ctx->iq) continue;
            state = ETS::RESUMING;
            network_new_epoch = pr.first;

            bool current_epoch = false;
            for (const auto &e : persisted->entries) {
                if (e.second->t == PET::Q) {
                    if (current_epoch) commit_state->commit(e.second->q);
                } else if (e.second->t == PET::EC) {
                    if (e.second->num < pr.first->config.number) continue;
                    if (pr.first->config.number < e.second->num)
                        throw EngineError(
                            "epoch change entries cannot exceed the target epoch");
                    current_epoch = true;
                }
            }
        }
    }

    void check_epoch_resumed() {
        if (commit_state->stop_at_seq_no < starting_seq_no) return;
        if (commit_state->low_watermark + 1 != starting_seq_no) return;
        state = ETS::READY;
    }

    template <typename StepFn>
    Actions advance_state_with(StepFn &&generic_step_unused) {
        return advance_state();
    }

    Actions advance_state() {
        if (state == ETS::IN_PROGRESS) {
            ActiveEpoch *ae = active_epoch.get();
            if (!ae->outstanding_reqs->available_iterator->has_next() &&
                !ae->needs_advance())
                return Actions();
        }
        Actions actions;
        while (true) {
            int old_state = state;
            if (state == ETS::PREPENDING) {
                concat(actions, check_epoch_quorum());
            } else if (state == ETS::PENDING) {
                if (!leader_new_epoch) return actions;
                state = ETS::VERIFYING;
            } else if (state == ETS::VERIFYING) {
                verify_new_epoch_state();
            } else if (state == ETS::FETCHING) {
                concat(actions, fetch_new_epoch_state());
            } else if (state == ETS::ECHOING) {
                concat(actions, check_new_epoch_echo_quorum());
            } else if (state == ETS::READYING) {
                check_new_epoch_ready_quorum();
            } else if (state == ETS::RESUMING) {
                check_epoch_resumed();
            } else if (state == ETS::READY) {
                if (!network_new_epoch && !have_resume_config)
                    throw EngineError(
                        "READY with neither a network config nor a resume config");
                const EpochCfgS &epoch_config = network_new_epoch
                                                    ? network_new_epoch->config
                                                    : resume_epoch_config;
                if (commit_state->low_watermark >=
                    epoch_config.planned_expiration) {
                    // The epoch expired while we were down or state
                    // transferring past it: no window left to resume
                    // (activating would assert in advance()).  End it so
                    // the tracker rolls to an epoch change targeting
                    // max_correct_epoch (epoch_target.py READY arm).
                    state = ETS::DONE;
                    continue;
                }
                active_epoch = std::make_shared<ActiveEpoch>(
                    ctx, epoch_config, persisted, node_buffers, commit_state,
                    client_tracker, my_config);
                concat(actions, active_epoch->advance());
                state = ETS::IN_PROGRESS;
                for (i32 node : ctx->cfg.nodes) {
                    prestart_buffers.at(node).iterate(
                        [](const MsgS &) { return Applyable::CURRENT; },
                        [this, node, &actions](MsgP m) {
                            concat(actions, active_epoch->step(node, m));
                        });
                }
                concat(actions, active_epoch->drain_buffers());
            } else if (state == ETS::IN_PROGRESS) {
                ActiveEpoch *ae = active_epoch.get();
                if (ae->outstanding_reqs->available_iterator->has_next())
                    concat(actions, ae->outstanding_reqs->advance_requests());
                if (ae->needs_advance()) concat(actions, ae->advance());
            }
            if (state == old_state) return actions;
        }
    }

    Actions move_low_watermark(i64 seq_no) {
        if (state != ETS::IN_PROGRESS) return Actions();
        auto [actions, done] = active_epoch->move_low_watermark(seq_no);
        if (done) state = ETS::DONE;
        return actions;
    }

    void apply_suspect_msg(i32 source) {
        suspicions.insert(source);
        if ((i64)suspicions.size() >= ctx->iq) state = ETS::DONE;
    }
};

// ---------------------------------------------------------------------------
// Epoch tracker (statemachine/epoch_tracker.py).
// ---------------------------------------------------------------------------

constexpr i64 TICKS_OUT_OF_CORRECT_EPOCH_LIMIT = 10;

i64 epoch_for_msg(const MsgS &msg) {
    switch (msg.t) {
        case MT::Preprepare:
        case MT::Prepare:
        case MT::Commit:
        case MT::Suspect:
            return msg.epoch;
        case MT::EpochChange:
            return msg.ec->new_epoch;
        case MT::EpochChangeAck:
            return msg.ec->new_epoch;
        case MT::NewEpoch:
        case MT::NewEpochEcho:
        case MT::NewEpochReady:
            return msg.necfg->config.number;
        default:
            throw EngineError("unexpected epoch message type");
    }
}

struct EpochTracker {
    const Ctx *ctx;
    shared_ptr<EpochTarget> current_epoch;
    PersistedLog *persisted;
    NodeBuffers *node_buffers;
    CommitState *commit_state;
    InitParms my_config;
    BatchTracker *batch_tracker;
    ClientTracker *client_tracker;
    Disseminator *client_hash_disseminator;
    std::map<i32, MsgBuffer> future_msgs;
    vector<std::pair<i32, i64>> max_epochs;  // insertion-ordered (source, max)
    i64 max_correct_epoch = 0;
    i64 ticks_out_of_correct_epoch = 0;
    bool needs_state_transfer = false;  // mirror of epoch_tracker.py's flag

    NetCfgP network_config;  // refreshed from the commit state's active state

    shared_ptr<EpochTarget> new_target(i64 number) {
        return std::make_shared<EpochTarget>(
            ctx, number, persisted, node_buffers, commit_state, client_tracker,
            client_hash_disseminator, batch_tracker, network_config,
            my_config);
    }

    Actions reinitialize() {
        network_config = commit_state->active_state->config;
        for (i32 node : ctx->cfg.nodes) {
            if (!future_msgs.count(node)) {
                MsgBuffer mb;
                mb.nb = node_buffers->node_buffer(node);
                mb.wire = &ctx->wire;
                future_msgs.emplace(node, std::move(mb));
            }
        }

        Actions actions;
        const PersistEntS *last_n = nullptr, *last_f = nullptr;
        bool have_ec = false;
        i64 last_ec_num = 0;
        i64 highest_preprepared = 0;
        for (const auto &pr : persisted->entries) {
            const PersistEntS &e = *pr.second;
            if (e.t == PET::N) last_n = &e;
            else if (e.t == PET::F) last_f = &e;
            else if (e.t == PET::EC) { have_ec = true; last_ec_num = e.num; }
            else if (e.t == PET::Q) {
                if (e.q->seq > highest_preprepared)
                    highest_preprepared = e.q->seq;
            } else if (e.t == PET::C) {
                // After state transfer we may have a CEntry with no QEntry.
                if (e.seq > highest_preprepared) highest_preprepared = e.seq;
            }
        }
        if (!last_n && !last_f)
            throw EngineError("no active epoch and no last epoch in log");
        if (last_n && last_f &&
            last_n->epoch_config.number <= last_f->epoch_config.number)
            throw EngineError("new epoch number must exceed last terminated epoch");

        if (last_n && (!have_ec || last_ec_num <= last_n->epoch_config.number)) {
            // Reinitializing mid-epoch: resume it (and suspect it, since we
            // may have missed traffic while down) —
            // epoch_tracker.py:163-181.
            current_epoch = new_target(last_n->epoch_config.number);
            i64 starting_seq_no = highest_preprepared + 1;
            i64 ci = network_config->ci;
            while (starting_seq_no % ci != 1) {
                // Advance to the first sequence after some checkpoint, so
                // we never re-consent on sequences we already consented on.
                starting_seq_no += 1;
                needs_state_transfer = true;
            }
            current_epoch->starting_seq_no = starting_seq_no;
            current_epoch->state = ETS::RESUMING;
            current_epoch->resume_epoch_config = last_n->epoch_config;
            current_epoch->have_resume_config = true;
            concat(actions,
                   persisted->append(pe_suspect(last_n->epoch_config.number)));
            actions.push_back(act_send(
                ctx->bcast, mk_suspect(last_n->epoch_config.number)));
            for (i32 node : ctx->cfg.nodes) {
                future_msgs.at(node).iterate(
                    [this](const MsgS &m) { return filter(m); },
                    [this, node, &actions](MsgP m) {
                        concat(actions, apply_msg(node, m));
                    });
            }
            return actions;
        }
        if (last_f && (!have_ec || last_ec_num <= last_f->epoch_config.number)) {
            last_ec_num = last_f->epoch_config.number + 1;
            have_ec = true;
            concat(actions, persisted->append(pe_ec(last_ec_num)));
        }
        if (!have_ec) throw EngineError("no epoch-change entry after recovery");
        if (current_epoch && current_epoch->number == last_ec_num) {
            concat(actions, current_epoch->advance_state());
            return actions;
        }
        EpochChangeP epoch_change = persisted->construct_epoch_change(last_ec_num);
        ParsedECP parsed = try_parse_epoch_change(epoch_change);
        if (!parsed) throw EngineError("own epoch change failed to parse");
        current_epoch = new_target(last_ec_num);
        current_epoch->my_epoch_change = parsed;
        current_epoch->my_leader_choice = network_config->nodes;  // all lead
        current_epoch->have_leader_choice = true;

        for (i32 node : ctx->cfg.nodes) {
            future_msgs.at(node).iterate(
                [this](const MsgS &m) { return filter(m); },
                [this, node, &actions](MsgP m) {
                    concat(actions, apply_msg(node, m));
                });
        }
        return actions;
    }

    Actions advance_state() {
        if (current_epoch->state < ETS::DONE)
            return current_epoch->advance_state();
        if (commit_state->checkpoint_pending) return Actions();

        i64 new_epoch_number = current_epoch->number + 1;
        if (max_correct_epoch > new_epoch_number)
            new_epoch_number = max_correct_epoch;
        EpochChangeP epoch_change =
            persisted->construct_epoch_change(new_epoch_number);
        ParsedECP my_epoch_change = try_parse_epoch_change(epoch_change);
        if (!my_epoch_change)
            throw EngineError("own epoch change failed to parse");

        current_epoch = new_target(new_epoch_number);
        current_epoch->my_epoch_change = my_epoch_change;
        current_epoch->my_leader_choice = {my_config.id};
        current_epoch->have_leader_choice = true;

        Actions actions = persisted->append(pe_ec(new_epoch_number));
        auto ecm = std::make_shared<MsgS>();
        ecm->t = MT::EpochChange;
        ecm->ec = epoch_change;
        actions.push_back(act_send(ctx->bcast, ecm));

        for (i32 node : ctx->cfg.nodes) {
            future_msgs.at(node).iterate(
                [this](const MsgS &m) { return filter(m); },
                [this, node, &actions](MsgP m) {
                    concat(actions, apply_msg(node, m));
                });
        }
        return actions;
    }

    Applyable filter(const MsgS &msg) {
        i64 epoch_number = epoch_for_msg(msg);
        if (epoch_number < current_epoch->number) return Applyable::PAST;
        if (epoch_number > current_epoch->number) return Applyable::FUTURE;
        return Applyable::CURRENT;
    }

    Actions step(i32 source, const MsgP &msg) {
        i64 epoch_number = epoch_for_msg(*msg);
        if (epoch_number < current_epoch->number) return Actions();
        if (epoch_number > current_epoch->number) {
            bool found = false;
            for (auto &pr : max_epochs)
                if (pr.first == source) {
                    if (pr.second < epoch_number) pr.second = epoch_number;
                    found = true;
                    break;
                }
            if (!found) max_epochs.emplace_back(source, epoch_number);
            future_msgs.at(source).store(msg);
            return Actions();
        }
        return apply_msg(source, msg);
    }

    Actions apply_msg(i32 source, const MsgP &msg) {
        EpochTarget *target = current_epoch.get();
        switch (msg->t) {
            case MT::Preprepare:
            case MT::Prepare:
            case MT::Commit:
                return target->step(source, msg);
            case MT::Suspect:
                target->apply_suspect_msg(source);
                return Actions();
            case MT::EpochChange: {
                u64 _t0 = __rdtsc();
                Actions _a = target->apply_epoch_change_msg(source, msg);
                g_parts[4].fetch_add(__rdtsc() - _t0, std::memory_order_relaxed);
                return _a;
            }
            case MT::EpochChangeAck: {
                u64 _t0 = __rdtsc();
                Actions _a = target->apply_epoch_change_ack_msg(
                    source, msg->originator, msg->ec);
                g_parts[5].fetch_add(__rdtsc() - _t0, std::memory_order_relaxed);
                return _a;
            }
            case MT::NewEpoch:
                if (msg->necfg->config.number % (i64)ctx->cfg.nodes.size() !=
                    source)
                    return Actions();  // not from the epoch primary
                return target->apply_new_epoch_msg(msg);
            case MT::NewEpochEcho:
                return target->apply_new_epoch_echo_msg(source, msg->necfg);
            case MT::NewEpochReady:
                return target->apply_new_epoch_ready_msg(source, msg->necfg);
            default:
                throw EngineError("unexpected epoch message type");
        }
    }

    Actions apply_batch_hash_result(i64 epoch, i64 seq_no, i32 digest) {
        if (epoch != current_epoch->number ||
            current_epoch->state != ETS::IN_PROGRESS)
            return Actions();
        return current_epoch->active_epoch->apply_batch_hash_result(seq_no,
                                                                    digest);
    }

    Actions apply_epoch_change_digest(const HashOriginS &origin, i32 digest) {
        i64 target_number = origin.ec->new_epoch;
        if (target_number < current_epoch->number) return Actions();
        if (target_number > current_epoch->number)
            throw EngineError("epoch change digest for future epoch");
        return current_epoch->apply_epoch_change_digest(origin, digest);
    }

    Actions tick() {
        for (const auto &pr : max_epochs) {
            i64 max_epoch = pr.second;
            if (max_epoch <= max_correct_epoch) continue;
            i64 matches = 0;
            for (const auto &pr2 : max_epochs)
                if (pr2.second >= max_epoch) matches++;
            if (matches < ctx->wq) continue;
            max_correct_epoch = max_epoch;
        }
        if (max_correct_epoch > current_epoch->number) {
            ticks_out_of_correct_epoch += 1;
            if (ticks_out_of_correct_epoch > TICKS_OUT_OF_CORRECT_EPOCH_LIMIT)
                current_epoch->state = ETS::DONE;
        }
        return current_epoch->tick();
    }

    Actions move_low_watermark(i64 seq_no) {
        return current_epoch->move_low_watermark(seq_no);
    }
};

// ---------------------------------------------------------------------------
// Root state machine (statemachine/machine.py).
// ---------------------------------------------------------------------------

struct MachineState_ {
    enum V { UNINITIALIZED = 0, LOADING_PERSISTED = 1, INITIALIZED = 2 };
};

struct Machine {
    const Ctx *ctx;
    int state = MachineState_::UNINITIALIZED;
    InitParms my_config{};
    bool have_config = false;
    std::unique_ptr<PersistedLog> persisted;
    std::unique_ptr<NodeBuffers> node_buffers;
    std::unique_ptr<CheckpointTracker> checkpoint_tracker;
    std::unique_ptr<ClientTracker> client_tracker;
    std::unique_ptr<CommitState> commit_state;
    std::unique_ptr<Disseminator> client_hash_disseminator;
    std::unique_ptr<BatchTracker> batch_tracker;
    std::unique_ptr<EpochTracker> epoch_tracker;

    void initialize(const InitParms &parameters) {
        if (state != MachineState_::UNINITIALIZED)
            throw EngineError("state machine has already been initialized");
        my_config = parameters;
        have_config = true;
        state = MachineState_::LOADING_PERSISTED;
        persisted = std::make_unique<PersistedLog>();
        node_buffers = std::make_unique<NodeBuffers>();
        node_buffers->buffer_size = parameters.buffer_size;
        checkpoint_tracker = std::make_unique<CheckpointTracker>();
        checkpoint_tracker->persisted = persisted.get();
        checkpoint_tracker->node_buffers = node_buffers.get();
        checkpoint_tracker->my_config = parameters;
        checkpoint_tracker->ctx = ctx;
        client_tracker = std::make_unique<ClientTracker>();
        client_tracker->my_config = parameters;
        commit_state = std::make_unique<CommitState>();
        commit_state->ctx = ctx;
        commit_state->persisted = persisted.get();
        client_hash_disseminator = std::make_unique<Disseminator>();
        client_hash_disseminator->ctx = ctx;
        client_hash_disseminator->my_config = parameters;
        client_hash_disseminator->node_buffers = node_buffers.get();
        client_hash_disseminator->client_tracker = client_tracker.get();
        batch_tracker = std::make_unique<BatchTracker>();
        batch_tracker->persisted = persisted.get();
        epoch_tracker = std::make_unique<EpochTracker>();
        epoch_tracker->ctx = ctx;
        epoch_tracker->persisted = persisted.get();
        epoch_tracker->node_buffers = node_buffers.get();
        epoch_tracker->commit_state = commit_state.get();
        epoch_tracker->my_config = parameters;
        epoch_tracker->batch_tracker = batch_tracker.get();
        epoch_tracker->client_tracker = client_tracker.get();
        epoch_tracker->client_hash_disseminator = client_hash_disseminator.get();
    }

    void apply_persisted(i64 index, PersistEntP entry) {
        if (state != MachineState_::LOADING_PERSISTED)
            throw EngineError("not in the loading-persisted phase");
        persisted->append_initial_load(index, std::move(entry));
    }

    Actions complete_initialization() {
        if (state != MachineState_::LOADING_PERSISTED)
            throw EngineError("not in the loading-persisted phase");
        state = MachineState_::INITIALIZED;
        return reinitialize();
    }

    Actions reinitialize() {
        Actions actions = complete_pending_reconfiguration();
        concat(actions, recover_log());
        concat(actions, commit_state->reinitialize());
        client_tracker->reinitialize(*commit_state->active_state);
        concat(actions,
               client_hash_disseminator->reinitialize(
                   commit_state->low_watermark, *commit_state->active_state));
        checkpoint_tracker->reinitialize();
        batch_tracker->reinitialize();
        concat(actions, epoch_tracker->reinitialize());
        return actions;
    }

    Actions complete_pending_reconfiguration() {
        // Close the epoch at a reconfiguration boundary (machine.py:151-194):
        // when the checkpoint APPLYING a pending reconfiguration is
        // persisted (its predecessor CEntry still carries the pending list)
        // but no FEntry follows it yet, append the FEntry ending the
        // current epoch config.
        const PersistEntS *prev_c = nullptr, *last_c = nullptr;
        const EpochCfgS *last_epoch_config = nullptr;
        bool f_after_last_c = false;
        for (const auto &pr : persisted->entries) {
            const PersistEntS &e = *pr.second;
            if (e.t == PET::C) {
                prev_c = last_c;
                last_c = &e;
                f_after_last_c = false;
            } else if (e.t == PET::F) {
                f_after_last_c = true;
                last_epoch_config = &e.epoch_config;
            } else if (e.t == PET::N) {
                last_epoch_config = &e.epoch_config;
            }
        }
        if (!last_c || !prev_c || f_after_last_c ||
            prev_c->netstate->pending.empty())
            return Actions();
        if (!last_epoch_config)
            throw EngineError(
                "reconfiguration completed with no epoch config in the log");
        return persisted->append(pe_f(*last_epoch_config));
    }

    Actions recover_log() {
        Actions actions;
        const PersistEntS *last_c = nullptr;
        // Collect truncation points first (Python iterates a snapshot).
        vector<i64> truncate_seqs;
        for (const auto &pr : persisted->entries) {
            const PersistEntS &e = *pr.second;
            if (e.t == PET::C) last_c = &e;
            else if (e.t == PET::F) {
                if (!last_c)
                    throw EngineError("FEntry without corresponding CEntry");
                truncate_seqs.push_back(last_c->seq);
            }
        }
        if (!last_c) throw EngineError("found no checkpoints in the log");
        for (i64 seq : truncate_seqs)
            concat(actions, persisted->truncate(seq));
        return actions;
    }

    Actions step(i32 source, const MsgP &msg);

    Actions process_hash_result(i32 digest, const HashOriginS &origin) {
        if (origin.t == OT::Batch) {
            batch_tracker->add_batch(origin.seq, digest, origin.request_acks);
            return epoch_tracker->apply_batch_hash_result(origin.epoch,
                                                          origin.seq, digest);
        }
        if (origin.t == OT::EpochChange)
            return epoch_tracker->apply_epoch_change_digest(origin, digest);
        if (origin.t == OT::VerifyBatch) {
            Actions actions;
            batch_tracker->apply_verify_batch_hash_result(digest, origin);
            if (!batch_tracker->has_fetch_in_flight() &&
                epoch_tracker->current_epoch->state == ETS::FETCHING)
                concat(actions,
                       epoch_tracker->current_epoch->fetch_new_epoch_state());
            return actions;
        }
        throw EngineError("no hash origin type set");
    }

    Actions process_checkpoint_result(const EventS &result) {
        Actions actions;
        NetStateP ns = result.netstate();
        if (result.a < commit_state->low_watermark) return actions;
        i64 expected = commit_state->low_watermark +
                       commit_state->active_state->config->ci;
        if (expected != result.a)
            throw EngineError("checkpoint results must be one interval after the last");
        bool completing_reconfiguration =
            !commit_state->active_state->pending.empty();
        i64 prev_stop = commit_state->stop_at_seq_no;
        concat(actions, commit_state->apply_checkpoint_result(
                            result.a, result.digest, ns));
        if (completing_reconfiguration && !commit_state->transferring) {
            // This checkpoint applied a reconfiguration: the epoch ends
            // here; reinitialize under the new network state (the FEntry
            // flow of reference docs/LogMovement.md).
            concat(actions, reinitialize());
            return actions;
        }
        if (prev_stop < commit_state->stop_at_seq_no) {
            client_tracker->allocate(*ns);
            concat(actions, client_hash_disseminator->allocate(result.a, *ns));
        }
        return actions;
    }

    Actions apply_event(const EventS &event) {
        if (event.t == ET::InitialParameters)
            throw EngineError("init params handled by caller");
        if (event.t == ET::LoadPersistedEntry) {
            apply_persisted(event.a, event.entry());
            return Actions();
        }
        Actions actions;
        if (event.t == ET::LoadCompleted) {
            actions = complete_initialization();
        } else if (event.t == ET::ActionsReceived) {
            if (state == MachineState_::INITIALIZED)
                return client_hash_disseminator->flush_acks();
            return actions;
        } else {
            if (state != MachineState_::INITIALIZED)
                throw EngineError("cannot apply events to an uninitialized machine");
            if (event.t == ET::Step) {
                concat(actions, step(event.digest, event.msg()));
            } else if (event.t == ET::RequestPersisted) {
                concat(actions,
                       client_hash_disseminator->apply_new_request(event.ack));
            } else if (event.t == ET::HashResult) {
                concat(actions,
                       process_hash_result(event.digest, *event.origin()));
            } else if (event.t == ET::CheckpointResult) {
                concat(actions, process_checkpoint_result(event));
            } else if (event.t == ET::TickElapsed) {
                concat(actions, client_hash_disseminator->tick());
                concat(actions, epoch_tracker->tick());
                concat(actions, commit_state->tick());
            } else if (event.t == ET::StateTransferFailed) {
                concat(actions, commit_state->apply_transfer_failed(
                                    event.a, event.digest));
            } else if (event.t == ET::StateTransferComplete) {
                if (!commit_state->transferring)
                    throw EngineError(
                        "state transfer completed but none was requested");
                concat(actions, persisted->append(
                                    pe_c(event.a, event.digest,
                                         event.netstate())));
                concat(actions, reinitialize());
            } else {
                throw EngineError("unknown event type");
            }
        }

        if (checkpoint_tracker->state == CheckpointState_::GARBAGE_COLLECTABLE) {
            i64 new_low = checkpoint_tracker->garbage_collect();
            concat(actions, persisted->truncate(new_low));
            i64 ci = checkpoint_tracker->net_cfg->ci;
            if (new_low > ci) batch_tracker->truncate(new_low - ci);
            concat(actions, epoch_tracker->move_low_watermark(new_low));
        }

        // Mid-epoch catch-up (docs/Divergences.md #13; machine.py twin).
        // The target stays armed while a transfer is in flight: checkpoint
        // messages are sent once, so dropping it could strand the replica.
        if (checkpoint_tracker->catch_up_seq >= 0) {
            i64 seq_no = checkpoint_tracker->catch_up_seq;
            i32 value = checkpoint_tracker->catch_up_value;
            if (seq_no <= commit_state->highest_commit) {
                checkpoint_tracker->catch_up_seq = -1;  // stale
                checkpoint_tracker->catch_up_value = -1;
            } else if (!commit_state->transferring) {
                checkpoint_tracker->catch_up_seq = -1;
                checkpoint_tracker->catch_up_value = -1;
                concat(actions, commit_state->transfer_to(seq_no, value));
            }
        }

        u64 t0 = __rdtsc();
        while (true) {
            concat(actions, commit_state->drain());
            Actions loop_actions = epoch_tracker->advance_state();
            if (loop_actions.empty()) break;
            concat(actions, std::move(loop_actions));
        }
        g_parts[2].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
        return actions;
    }
};

Actions Machine::step(i32 source, const MsgP &msg) {
    MT t = msg->t;
    if (t == MT::Prepare || t == MT::Commit) {
        EpochTarget *target = epoch_tracker->current_epoch.get();
        if (msg->epoch == target->number && target->state == ETS::IN_PROGRESS)
            return target->active_epoch->step(source, msg);
        return epoch_tracker->step(source, msg);
    }
    if (t == MT::AckBatch || t == MT::AckMsg || t == MT::FetchRequest)
        return client_hash_disseminator->step(source, msg);
    if (t == MT::MsgBatch) {
        EpochTarget *target = epoch_tracker->current_epoch.get();
        if (target->state == ETS::IN_PROGRESS) {
            // Native-plane envelope path (voteplane.py split_votes): votes
            // first (in order), then the rest (in order) — classified
            // inline; apply_envelope_votes skips the non-votes.
            bool any_vote = false;
            for (const auto &im : msg->inner)
                if (im->t == MT::Prepare || im->t == MT::Commit) {
                    any_vote = true;
                    break;
                }
            if (any_vote) {
                u64 t0 = __rdtsc();
                Actions actions = target->active_epoch->apply_envelope_votes(
                    msg->inner, source, [this](i32 src, const MsgP &m) {
                        return step(src, m);
                    });
                g_parts[1].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
                for (const auto &im : msg->inner)
                    if (im->t != MT::Prepare && im->t != MT::Commit)
                        concat(actions, step(source, im));
                return actions;
            }
        }
        Actions actions;
        for (const auto &im : msg->inner) concat(actions, step(source, im));
        return actions;
    }
    if (t == MT::Checkpoint) {
        checkpoint_tracker->step(source, msg);
        return Actions();
    }
    if (t == MT::FetchBatch)
        return batch_tracker->reply_fetch_batch(source, msg->seq, msg->dig);
    if (t == MT::ForwardBatch)
        return batch_tracker->apply_forward_batch_msg(source, msg->seq,
                                                      msg->dig, msg->acks,
                                                      ctx->intern);
    if (t == MT::Suspect || t == MT::EpochChange || t == MT::EpochChangeAck ||
        t == MT::NewEpoch || t == MT::NewEpochEcho || t == MT::NewEpochReady ||
        t == MT::Preprepare)
        return epoch_tracker->step(source, msg);
    throw EngineError("unexpected message type in machine step");
}

// ClientTracker::allocate (deferred: needs is_committed over CRNP/AckS).
void ClientTracker::allocate(const NetStateS &state) {
    std::map<i64, const ClientStateS *> state_map;
    for (const auto &cs : state.clients) state_map.emplace(cs.id, &cs);
    available_list->garbage_collect([&](const AckS &ack) {
        auto it = state_map.find(ack.client);
        if (it == state_map.end())
            throw EngineError("available client req must have its client in config");
        return is_committed(ack.reqno, *it->second);
    });
    ready_list->garbage_collect([&](const CRNP &crn) {
        auto it = state_map.find(crn->client_id);
        if (it == state_map.end())
            throw EngineError("client removal not yet supported");
        return is_committed(crn->req_no, *it->second);
    });
}

// ---------------------------------------------------------------------------
// Processor layer (processor/{serial,work,clients}.py) and simulated node
// fakes (testengine/recorder.py).
// ---------------------------------------------------------------------------

struct WorkItems {
    Actions wal_actions, net_actions, hash_actions, client_actions,
        app_actions;
    Events req_store_events, result_events;

    void add_state_machine_results(Actions &&actions) {
        for (auto &action : actions) {
            switch (action.t) {
                case AT::Send: {
                    MT t = action.msg_raw()->t;
                    if (t == MT::AckMsg || t == MT::AckBatch ||
                        t == MT::Checkpoint || t == MT::FetchBatch ||
                        t == MT::ForwardBatch)
                        net_actions.push_back(std::move(action));
                    else
                        wal_actions.push_back(std::move(action));
                    break;
                }
                case AT::Hash:
                    hash_actions.push_back(std::move(action));
                    break;
                case AT::Persist:
                case AT::Truncate:
                    wal_actions.push_back(std::move(action));
                    break;
                case AT::Commit:
                case AT::Checkpoint:
                case AT::StateTransfer:
                    app_actions.push_back(std::move(action));
                    break;
                case AT::AllocatedRequest:
                case AT::CorrectRequest:
                case AT::StateApplied:
                    client_actions.push_back(std::move(action));
                    break;
                case AT::ForwardRequest:
                    break;  // dropped (reference work.go:176)
            }
        }
    }
};

// In-memory request store (testengine SimReqStore).
struct SimReqStore {
    std::unordered_set<AckS, AckHash> requests;
    std::unordered_map<u64, i32> allocations;  // (client<<32|reqno) packed

    static u64 key(i64 client, i64 reqno) {
        return ((u64)client << 40) | (u64)reqno;
    }
    void put_request(const AckS &ack) { requests.insert(ack); }
    bool has_request(const AckS &ack) const { return requests.count(ack) != 0; }
    void put_allocation(i64 client, i64 reqno, i32 dig) {
        allocations[key(client, reqno)] = dig;
    }
    i32 get_allocation(i64 client, i64 reqno) const {  // -1 = None
        auto it = allocations.find(key(client, reqno));
        return it == allocations.end() ? -1 : it->second;
    }
};

// In-memory WAL (testengine SimWAL) — index bookkeeping only; entries are
// retained for parity of the strict accounting, trimmed on truncate.
struct SimWAL {
    i64 low_index = 1;
    deque<PersistEntP> entries;

    void write(i64 index, PersistEntP entry) {
        i64 expected = low_index + (i64)entries.size();
        if (index != expected) throw EngineError("WAL out of order");
        entries.push_back(std::move(entry));
    }
    void truncate(i64 index) {
        if (index < low_index) throw EngineError("truncate below low index");
        i64 to_remove = index - low_index;
        if (to_remove >= (i64)entries.size())
            throw EngineError("truncate beyond highest index");
        entries.erase(entries.begin(), entries.begin() + to_remove);
        low_index = index;
    }
};

// The simulated replicated app (testengine NodeState).
//
// Cluster-shared hash-chain memoization: all N replicas apply the SAME
// ordered QEntry stream to the same app semantics, so the expensive parts
// of the evolution — the SHA-256 chain state and the per-client
// committed-reqs map — are functions of the chain position, not of the
// replica.  The engine keeps one content-addressed chain DAG (AppChain);
// each replica holds only a cursor (chain_id).  The first replica to reach
// a position pays for it; the other N-1 follow pointers.  Divergent
// streams (impossible in the green envelope, but the memo does not assume
// it) simply grow separate branches keyed by (seq, batch digest).
// Per-replica semantic assertions (commit ordering, reqstore presence)
// still run per replica — only the symmetric computation is shared.
struct AppChainNode {
    Sha256 hash_state;
    std::unordered_map<u64, i32> next;       // (seq<<32|digest) -> node
    std::unordered_map<i32, i32> snap_next;  // checkpoint value id -> node
    string digest;  // memoized hash_state.digest()
    bool digest_done = false;
    // Committed-floor delta memo: absolute (client, floor) assignments a
    // replica with CANONICAL floors applies at this position, one entry
    // per client the batch raised.  Consumers apply it with MAX, so a
    // delta created by a floor-lagging (state-transferred) replica — a
    // superset with never-higher values — stays correct for everyone.
    vector<std::pair<i64, i64>> delta;
};

struct AppChain {
    vector<AppChainNode> nodes;
    AppChain() { nodes.emplace_back(); }
};

struct AppState {
    const Ctx *ctx;
    SimReqStore *req_store;
    AppChain *chain = nullptr;
    i32 chain_id = 0;
    i64 last_seq_no = 0;
    i64 checkpoint_seq_no = 0;
    string checkpoint_hash;
    NetStateP checkpoint_state;
    std::map<i64, i64> committed_reqs;
    // State-transfer bookkeeping + app-level failure injection
    // (testengine/recorder.py NodeState).
    i64 fail_transfers = 0;
    // False once this node state-transfers: its committed_reqs floors lag
    // the chain's canonical floors (skipped batches are never applied), so
    // it leaves the shared-delta fast path for the per-request one.
    bool floors_canonical = true;
    vector<i64> state_transfers;
    vector<i64> transfer_failures;
    vector<i64> transfer_attempt_times;
    // Reconfiguration points (engine-global) + this replica's accumulated
    // pending reconfigurations since its last snap.
    const vector<std::tuple<i64, i64, ReconfigS>> *reconfig_points = nullptr;
    vector<ReconfigS> pending;

    const string &active_hash_digest() {
        AppChainNode &cur = chain->nodes[(size_t)chain_id];
        if (!cur.digest_done) {
            cur.digest = cur.hash_state.digest();
            cur.digest_done = true;
        }
        return cur.digest;
    }

    // snap() -> value interner id.  Consumes pending reconfigurations into
    // the checkpointed network state (testengine NodeState.snap).
    i32 snap(Interner &intern, NetCfgP config,
             const vector<ClientStateS> &client_states) {
        checkpoint_seq_no = last_seq_no;
        auto ns = std::make_shared<NetStateS>();
        ns->config = std::move(config);
        ns->clients = client_states;
        ns->pending = std::move(pending);
        pending.clear();
        checkpoint_state = ns;
        checkpoint_hash = active_hash_digest();
        // The value embeds the (per-replica-encoded) network state, so the
        // snap transition is keyed by the value id: replicas snapping the
        // same state at the same position converge on one chain node.
        string value = checkpoint_hash;
        ctx->wire.net_state(value, *ns);
        i32 vid = intern.put(value);
        AppChainNode &cur = chain->nodes[(size_t)chain_id];
        auto it = cur.snap_next.find(vid);
        if (it != cur.snap_next.end()) {
            chain_id = it->second;
            return vid;
        }
        AppChainNode nxt;
        nxt.hash_state.update(checkpoint_hash);
        i32 nid = (i32)chain->nodes.size();
        chain->nodes.push_back(std::move(nxt));
        chain->nodes[(size_t)chain_id].snap_next.emplace(vid, nid);
        chain_id = nid;
        return vid;
    }

    void apply(const QEntryS &batch, const Interner &intern) {
        last_seq_no += 1;
        if (batch.seq != last_seq_no) throw EngineError("out-of-order commit");
        for (const auto &request : batch.reqs)
            if (!req_store->has_request(request))
                throw EngineError("reqstore must have a request we are committing");
        u64 key = ((u64)(u32)batch.seq << 32) | (u32)batch.dig;
        i32 nid;
        {
            AppChainNode &cur = chain->nodes[(size_t)chain_id];
            auto it = cur.next.find(key);
            nid = it != cur.next.end() ? it->second : -1;
        }
        if (nid < 0) {
            // First replica at this position: compute the hash transition
            // and the committed-floor delta (from OUR floors; a lagging
            // creator emits a superset with never-higher values — see the
            // delta comment on AppChainNode).
            AppChainNode nxt;
            nxt.hash_state = chain->nodes[(size_t)chain_id].hash_state;
            for (const auto &request : batch.reqs) {
                nxt.hash_state.update(intern.get(request.dig));
                auto cit = committed_reqs.find(request.client);
                i64 prev = cit == committed_reqs.end() ? 0 : cit->second;
                if (request.reqno + 1 > prev) {
                    bool found = false;
                    for (auto &pr : nxt.delta)
                        if (pr.first == request.client) {
                            if (request.reqno + 1 > pr.second)
                                pr.second = request.reqno + 1;
                            found = true;
                            break;
                        }
                    if (!found)
                        nxt.delta.emplace_back(request.client,
                                               request.reqno + 1);
                }
            }
            nid = (i32)chain->nodes.size();
            chain->nodes.push_back(std::move(nxt));
            chain->nodes[(size_t)chain_id].next.emplace(key, nid);
        }
        if (floors_canonical && (!reconfig_points || reconfig_points->empty())) {
            // Fast path (the common one: never-transferred replica, no
            // reconfiguration points): the memoized delta applied with
            // MAX is exactly the per-request floor update.
            for (const auto &pr : chain->nodes[(size_t)nid].delta) {
                i64 &slot = committed_reqs[pr.first];
                if (pr.second > slot) slot = pr.second;
            }
        } else {
            // Per-request path: a transferred replica's floors lag the
            // chain (Python computes per replica too, NodeState.apply),
            // and reconfiguration points must see every request.
            for (const auto &request : batch.reqs) {
                i64 &slot = committed_reqs[request.client];
                if (request.reqno + 1 > slot) slot = request.reqno + 1;
                if (reconfig_points)
                    for (const auto &point : *reconfig_points)
                        if (std::get<0>(point) == request.client &&
                            std::get<1>(point) == request.reqno)
                            pending.push_back(std::get<2>(point));
            }
        }
        chain_id = nid;
    }
};

// Client-side request-store logic (processor/clients.py).
struct ProcClientRequest {
    bool present = false;
    i32 local_allocation_digest = -1;  // -1 = None
    vector<i32> remote_correct_digests;
};

struct ProcClient {
    i64 client_id;
    SimReqStore *request_store;
    i64 next_req_no = 0;
    // Dense window over [base, base + win.size()): client request slots are
    // created/consumed in ascending runs, so the Python dict (insertion-
    // ordered, pruned from the bottom at state_applied) maps onto a deque.
    // Slots may be holes (present == false) until allocated/proposed.
    i64 base = 0;
    bool base_set = false;
    deque<ProcClientRequest> win;
    i64 live = 0;  // count of present slots

    ProcClientRequest *slot(i64 req_no) {
        if (!base_set) return nullptr;
        i64 off = req_no - base;
        if (off < 0 || off >= (i64)win.size()) return nullptr;
        ProcClientRequest &cr = win[(size_t)off];
        return cr.present ? &cr : nullptr;
    }

    ProcClientRequest *ensure_slot(i64 req_no) {
        if (!base_set) {
            base = req_no;
            base_set = true;
        }
        while (req_no < base) {
            // The Python dict re-creates entries below a pruned low
            // watermark (clients.py Client.allocate); extend downward.
            win.emplace_front();
            base -= 1;
        }
        while ((i64)win.size() <= req_no - base) win.emplace_back();
        ProcClientRequest &cr = win[(size_t)(req_no - base)];
        if (!cr.present) {
            cr.present = true;
            live += 1;
        }
        return &cr;
    }

    void state_applied(const ClientStateS &state) {
        while (base_set && !win.empty() && base < state.lw) {
            if (win.front().present) live -= 1;
            win.pop_front();
            base += 1;
        }
        // A fully drained window must rebase, or the next ensure_slot would
        // materialize every hole between the stale base and the new slot.
        if (base_set && win.empty() && base < state.lw) base = state.lw;
        if (next_req_no < state.lw) next_req_no = state.lw;
    }

    // allocate() -> local digest or -1.
    i32 allocate(i64 req_no) {
        ProcClientRequest *existing = slot(req_no);
        if (existing) return existing->local_allocation_digest;
        ProcClientRequest *cr = ensure_slot(req_no);
        cr->local_allocation_digest =
            request_store->get_allocation(client_id, req_no);
        return cr->local_allocation_digest;
    }

    bool empty() const { return live == 0; }

    i64 first_req_no() const {
        for (size_t i = 0; i < win.size(); i++)
            if (win[i].present) return base + (i64)i;
        throw EngineError("empty proc client window");
    }

    void add_correct_digest(i64 req_no, i32 digest) {
        if (empty())
            throw EngineError("client-not-exist in add_correct_digest");
        ProcClientRequest *cr = slot(req_no);
        if (!cr) {
            if (req_no < first_req_no()) return;  // already GC'd
            throw EngineError("unallocated client request marked correct");
        }
        auto &rcd = cr->remote_correct_digests;
        for (i32 d : rcd)
            if (d == digest) return;
        rcd.push_back(digest);
    }

    i64 next_req_no_value() const {
        if (empty()) throw EngineError("ClientNotExist");
        return next_req_no;
    }

    // propose() (clients.py:98-144); digest precomputed by the engine.
    // Returns (has_event, ack) — the RequestPersisted event if emitted.
    bool propose(i64 req_no, i32 digest, AckS *out) {
        if (empty()) throw EngineError("ClientNotExist");
        if (req_no < next_req_no) return false;

        if (req_no == next_req_no) {
            while (true) {
                next_req_no += 1;
                ProcClientRequest *nxt = slot(next_req_no);
                if (!nxt || nxt->local_allocation_digest == -1) break;
            }
        }
        ProcClientRequest *existing = slot(req_no);
        bool previously_allocated = existing != nullptr;
        ProcClientRequest &cr = *(existing ? existing : ensure_slot(req_no));
        if (cr.local_allocation_digest != -1) {
            if (cr.local_allocation_digest == digest) return false;
            throw EngineError("conflicting digest for req_no");
        }
        if (!cr.remote_correct_digests.empty()) {
            bool ok = false;
            for (i32 d : cr.remote_correct_digests)
                if (d == digest) ok = true;
            if (!ok)
                throw EngineError("other known-correct digests exist for req_no");
        }
        AckS ack{client_id, req_no, digest};
        request_store->put_request(ack);
        request_store->put_allocation(client_id, req_no, digest);
        cr.local_allocation_digest = digest;
        if (previously_allocated) {
            *out = ack;
            return true;
        }
        return false;
    }
};

struct ProcClients {
    SimReqStore *request_store;
    std::map<i64, ProcClient> clients;

    ProcClient *client(i64 client_id) {
        auto it = clients.find(client_id);
        if (it == clients.end()) {
            ProcClient c;
            c.client_id = client_id;
            c.request_store = request_store;
            it = clients.emplace(client_id, std::move(c)).first;
        }
        return &it->second;
    }

    Events process_client_actions(const Actions &actions) {
        Events events;
        i64 last_id = -1;
        ProcClient *cached = nullptr;
        for (const auto &action : actions) {
            if (action.t == AT::AllocatedRequest) {
                if (action.a != last_id) {
                    last_id = action.a;
                    cached = client(last_id);
                }
                i32 digest = cached->allocate(action.b);
                if (digest == -1) continue;
                EventS ev;
                ev.t = ET::RequestPersisted;
                ev.ack = AckS{action.a, action.b, digest};
                events.push_back(std::move(ev));
            } else if (action.t == AT::CorrectRequest) {
                client(action.ack.client)
                    ->add_correct_digest(action.ack.reqno, action.ack.dig);
            } else if (action.t == AT::StateApplied) {
                for (const auto &cs : action.netstate()->clients)
                    client(cs.id)->state_applied(cs);
            } else {
                throw EngineError("unexpected client action type");
            }
        }
        return events;
    }
};

// _coalesce_sends (processor/serial.py:96-145).
vector<ActionS> coalesce_sends(Actions &&actions) {
    struct Group {
        size_t index;
        vector<MsgP> msgs;
        vector<AckS> acks;
    };
    vector<std::pair<Targets, Group>> groups;  // insertion-ordered by key
    vector<std::optional<ActionS>> out;
    for (auto &action : actions) {
        if (action.t != AT::Send)
            throw EngineError("unexpected Net action type");
        Group *slot = nullptr;
        for (auto &pr : groups)
            if (pr.first == action.targets ||
                *pr.first == *action.targets) {
                slot = &pr.second;
                break;
            }
        if (!slot) {
            groups.emplace_back(action.targets,
                                Group{out.size(), {}, {}});
            slot = &groups.back().second;
            out.emplace_back(std::nullopt);
        }
        const MsgS *msg = action.msg_raw();
        if (msg->t == MT::AckMsg) slot->acks.push_back(msg->acks[0]);
        else if (msg->t == MT::AckBatch)
            for (const auto &a : msg->acks) slot->acks.push_back(a);
        else slot->msgs.push_back(action.msg());
    }
    for (auto &pr : groups) {
        Group &g = pr.second;
        if (!g.acks.empty()) {
            std::stable_sort(g.acks.begin(), g.acks.end(),
                             [](const AckS &a, const AckS &b) {
                                 if (a.client != b.client)
                                     return a.client < b.client;
                                 return a.reqno < b.reqno;
                             });
            if (g.acks.size() == 1) g.msgs.push_back(mk_ack_msg(g.acks[0]));
            else g.msgs.push_back(mk_ack_batch(std::move(g.acks)));
        }
        MsgP final_msg;
        if (g.msgs.size() == 1) {
            final_msg = g.msgs[0];
        } else {
            auto mb = std::make_shared<MsgS>();
            mb->t = MT::MsgBatch;
            mb->inner = std::move(g.msgs);
            final_msg = mb;
        }
        out[g.index] = act_send(pr.first, final_msg);
    }
    vector<ActionS> result;
    for (auto &o : out)
        if (o) result.push_back(std::move(*o));
    return result;
}

// ---------------------------------------------------------------------------
// The engine: nodes + scheduler (testengine/recorder.py Recording).
// ---------------------------------------------------------------------------

constexpr int PROPOSAL_CHUNK = 32;

struct RuntimeParms {
    i64 tick_interval = 500, link_latency = 100, wal_latency = 100,
        net_latency = 15, hash_latency = 25, client_latency = 15,
        app_latency = 30, req_store_latency = 150, events_latency = 10;
    // Per-destination link latency (docs/PERFORMANCE.md §7.1, per-link
    // lookahead): empty means the scalar link_latency applies to every
    // destination.  The self entry is ignored (self-sends short-circuit).
    vector<i64> lat_to;
    i64 link_lat(i64 dest) const {
        return lat_to.empty() ? link_latency : lat_to[(size_t)dest];
    }
};

struct ClientSpec {
    i64 id;
    i64 total;
    bool signed_mode = false;
    bool corrupt = false;
    std::set<i32> ignore_nodes;
    vector<i32> payloads;        // interner id per req_no
    vector<i32> payload_digests; // sha256 id per req_no
    vector<u8> verdicts;         // auth verdict per req_no (signed mode)
};

struct EngineNode {
    i32 id;
    InitParms init_parms;
    RuntimeParms runtime;
    i64 start_delay = 0;
    SimWAL wal;
    SimReqStore req_store;
    AppState state;
    std::unique_ptr<WorkItems> work_items;
    std::unique_ptr<ProcClients> clients;
    std::unique_ptr<Machine> machine;
    bool pending[7] = {false, false, false, false, false, false, false};
    // category order: wal, net, client, hash, app, req_store, result
    bool drain_ready = false;
};

struct Engine {
    Ctx ctx;
    EventQueue queue;
    // PDES state (empty for sequential runs; see struct Partition).
    vector<std::unique_ptr<Partition>> parts;
    vector<i32> part_of;  // node id -> partition id
    bool pdes_threaded = false;
    i64 pdes_W = 0;  // conservative window width for the current part_of
    // Traffic model for rebalancing: per-node EWMA of window work cycles,
    // plus the raw per-window vectors of the last few windows — the
    // repartition objective (sum of per-window partition maxima) lives in
    // the window-to-window burst structure that the EWMA smooths away.
    vector<double> node_load;
    std::deque<vector<u64>> node_hist;
    // Pooled barrier-merge buffers (reused every window).
    vector<vector<i64>> pdes_fin;
    vector<size_t> pdes_logi, pdes_flipi, pdes_purgei;
    std::shared_mutex intern_mu;  // installed on ctx.intern when threaded
    std::mutex chain_mu, snap_mu;  // shared chain / snap registry guards
    vector<std::unique_ptr<EngineNode>> nodes;
    vector<ClientSpec> client_specs;  // in config order
    i64 steps = 0;
    i64 committed_ops = 0;
    // Incremental drain bookkeeping: the drain predicate is a pure function
    // of state that changes only in checkpoint snaps (client low watermarks)
    // and commits (committed counts), so it is maintained there instead of
    // rescanning nodes x clients every step.  drained() stays exactly the
    // predicate of recorder.py:761-803, evaluated after every step.
    std::unordered_map<i64, i64> drain_targets;  // client -> target (0 corrupt)
    i64 nodes_not_ready = 0;   // nodes whose checkpoint lws miss targets
    i64 clients_unsatisfied = 0;  // targets>0 not yet committed anywhere
    std::unordered_map<i64, bool> client_satisfied;
    u64 kind_cycles[11] = {0};
    u64 kind_counts[11] = {0};
    u64 ev_cycles[12] = {0};
    u64 ev_counts[12] = {0};
    // Per-message-kind attribution of Step-event application (the c4
    // profile's dominant bucket): indexed by MT.
    u64 msg_cycles[16] = {0};
    u64 msg_counts[16] = {0};
    u64 fix_cycles = 0;  // post-event GC+fixpoint share (inside apply_event)
    u64 crypto_ns = 0;  // host CPU spent hashing (SHA-256) in-engine
    // Wave mirror log: (joined message id, digest id) for wave-eligible
    // content; first sight of a content logs it for the device plane.
    vector<std::pair<i32, i32>> wave_log;
    // Cluster-symmetric hash memos: all N replicas hash identical protocol
    // content (batch digests, epoch-change data), so each unique content is
    // hashed once and the other N-1 requests are lookups.  Two maps keep
    // the domains separate (a host-fast hit must never shadow the same
    // bytes arriving as wave-eligible content, which must reach wave_log);
    // wave_memo doubles as the device-mirror dedup set.  Metering stays
    // honest: crypto_ns accrues only when SHA-256 actually runs.
    std::unordered_map<string, i32> host_memo;
    std::unordered_map<string, i32> wave_memo;
    // Cluster-shared app hash-chain DAG (see AppChain above).
    AppChain app_chain;
    // Reconfiguration points: (client_id, req_no, reconfiguration) applied
    // by every replica's app when that request commits.
    vector<std::tuple<i64, i64, ReconfigS>> reconfig_points;
    // Cluster-shared ack-wave ledger (see AckLedger above); enabled when
    // link latency is uniform (so send order == arrival order).
    AckLedger ack_ledger;
    // Device-authoritative crypto (docs/FastEngine.md "Device crypto"):
    // in device_hash_mode, wave-eligible digests are CONSUMED from device
    // collects — the engine pauses (wall-clock only; the simulated
    // schedule is untouched, so step counts stay bit-identical to mirror
    // mode) whenever the next event needs a digest not yet supplied.  In
    // streaming_auth_mode, signed-request verdicts arrive in lookahead
    // waves during the run instead of one pre-run bitmap.
    bool device_hash_mode = false;
    bool streaming_auth_mode = false;
    // Structured drop mangler (testengine/manglers.py DropMessages): drop
    // MsgReceived deliveries matching (from, to); empty set = match any.
    // The only mangler inside the fast envelope.
    bool drop_mangler = false;
    Mask drop_from, drop_to;
    bool drop_from_any = false, drop_to_any = false;

    bool drop_matches(i32 source, i32 target) const {
        if (source == target) return false;  // self-links stay reliable
        if (!drop_from_any && !drop_from.test(source)) return false;
        if (!drop_to_any && !drop_to.test(target)) return false;
        return true;
    }
    std::unordered_map<string, i32> device_digests;  // content -> digest id
    vector<string> need_hash_content;
    vector<std::pair<i64, i64>> need_verdicts;  // (client, verdicts needed up to)

    ClientSpec *spec_of(i64 client_id) {
        for (auto &cs : client_specs)
            if (cs.id == client_id) return &cs;
        return nullptr;
    }

    // Engine-wide hashing service (the hash plane): identical digests to
    // hashlib; wave-eligible content (multi-part or >= 512 B single part —
    // the complement of crypto.py::_host_fast) is mirrored for the device.
    // PDES runs (part != null) use partition-local memos/meters and skip
    // the device mirror (device modes are outside the PDES envelope).
    i32 hash_parts(const vector<string> &parts, Partition *part = nullptr) {
        auto &h_memo = part ? part->host_memo : host_memo;
        auto &w_memo = part ? part->wave_memo : wave_memo;
        u64 &c_ns = part ? part->crypto_ns : crypto_ns;
        if (hash_is_host_floor(parts)) {
            // Below the wave floor (host-only content).  Memo lookup keys
            // on the part itself — no copy on the hit path.
            auto hit = h_memo.find(parts[0]);
            if (hit != h_memo.end()) return hit->second;
            auto t0 = std::chrono::steady_clock::now();
            i32 r = ctx.intern.put(sha256(parts[0]));
            c_ns += (u64)std::chrono::duration_cast<std::chrono::nanoseconds>(
                             std::chrono::steady_clock::now() - t0)
                             .count();
            if (h_memo.size() > (1u << 17)) h_memo.clear();  // bounded
            h_memo.emplace(parts[0], r);
            return r;
        }
        string joined;
        for (const auto &p : parts) joined.append(p);
        if (device_hash_mode) {
            // The device is authoritative for wave-eligible content: no
            // host hash, no mirror log.  check_ready() guarantees the
            // digest was supplied before this event ran.
            auto dit = device_digests.find(joined);
            if (dit == device_digests.end())
                throw EngineError("device digest missing at hash time");
            return dit->second;
        }
        auto hit = w_memo.find(joined);
        if (hit != w_memo.end()) return hit->second;
        auto t0 = std::chrono::steady_clock::now();
        string digest = sha256(joined);
        i32 did = ctx.intern.put(digest);
        c_ns += (u64)std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
        // First sight of this wave content: mirror it for the device.  A
        // bounded-clear re-sight re-logs, which the Python side verifies
        // again harmlessly.  (PDES: no device plane, no mirror.)
        if (!part) wave_log.emplace_back(ctx.intern.put(joined), did);
        if (w_memo.size() > (1u << 17)) w_memo.clear();  // bounded
        w_memo.emplace(std::move(joined), did);
        return did;
    }

    void init_node_world(i32 node_id, const vector<ClientStateS> &init_clients) {
        EngineNode &node = *nodes[(size_t)node_id];
        node.state.ctx = &ctx;
        node.state.req_store = &node.req_store;
        node.state.chain = &app_chain;
        node.state.reconfig_points = &reconfig_points;
        i32 checkpoint_value =
            node.state.snap(ctx.intern, ctx.cfg_p, init_clients);
        register_snap(checkpoint_value, node.state);
        auto ns = node.state.checkpoint_state;
        node.wal.entries.clear();
        node.wal.low_index = 1;
        node.wal.entries.push_back(pe_c(0, checkpoint_value, ns));
        EpochCfgS cfg0;
        cfg0.number = 0;
        cfg0.leaders = ctx.cfg.nodes;
        cfg0.planned_expiration = 0;
        node.wal.entries.push_back(pe_f(cfg0));
    }

    void initialize_node(EngineNode &node) {
        node.work_items = std::make_unique<WorkItems>();
        node.clients = std::make_unique<ProcClients>();
        node.clients->request_store = &node.req_store;
        node.machine = std::make_unique<Machine>();
        node.machine->ctx = &ctx;
        for (auto &p : node.pending) p = false;
        // recover_wal_for_existing_node: init + load entries + complete.
        Events &ev = node.work_items->result_events;
        {
            EventS e;
            e.t = ET::InitialParameters;
            ev.push_back(std::move(e));
        }
        for (size_t i = 0; i < node.wal.entries.size(); i++) {
            EventS e;
            e.t = ET::LoadPersistedEntry;
            e.a = node.wal.low_index + (i64)i;
            e.payload = node.wal.entries[i];
            ev.push_back(std::move(e));
        }
        {
            EventS e;
            e.t = ET::LoadCompleted;
            ev.push_back(std::move(e));
        }
    }

    void schedule_proposal(i32 node_id, i64 client_id, i64 req_no,
                           i64 delay, Partition *part = nullptr) {
        EventQueue &q = part ? part->q : queue;
        SimEv ev;
        ev.time = q.fake_time + delay;
        ev.kind = SK::ClientProposal;
        ev.target = node_id;
        ev.client = client_id;
        ev.reqno = req_no;
        q.insert(std::move(ev));
    }

    Actions process_wal_actions(EngineNode &node, Actions &&actions) {
        Actions net_actions;
        for (auto &action : actions) {
            if (action.t == AT::Send) net_actions.push_back(std::move(action));
            else if (action.t == AT::Persist)
                node.wal.write(action.a, action.entry());
            else if (action.t == AT::Truncate)
                node.wal.truncate(action.a);
            else
                throw EngineError("unexpected WAL action type");
        }
        return net_actions;
    }

    // Bound ledger memory: drop waves every live receiver's cursor has
    // passed and canonical records below every receiver's low watermark.
    void prune_ledger() {
        u32 minv = UINT32_MAX;
        std::map<i64, i64> min_lw;
        for (const auto &np : nodes) {
            if (!np->machine || !np->machine->client_hash_disseminator)
                continue;
            Disseminator &d = *np->machine->client_hash_disseminator;
            if (!d.initialized) continue;
            if (d.led_view.version < minv) minv = d.led_view.version;
            for (const auto &pr : d.clients) {
                i64 lw = pr.second->client_state.lw;
                auto it = min_lw.find(pr.first);
                if (it == min_lw.end() || lw < it->second)
                    min_lw[pr.first] = lw;
            }
        }
        if (minv == UINT32_MAX) return;
        // Clear stale divergence marks on fully-retired records (below
        // every receiver's low watermark no window will ever cover them
        // again); fixes the owning receivers' counters so the fast gates
        // and prune() are not blocked by a missed release.
        for (auto &cp : ctx.ack_ledger->clients) {
            auto it = min_lw.find(cp.first);
            if (it == min_lw.end()) continue;
            CanonClient &cc = cp.second;
            for (i64 rn = cc.base; rn >= 0 && rn < it->second &&
                                   rn - cc.base < (i64)cc.recs.size();
                 rn++) {
                CanonRec &R = cc.recs[(size_t)(rn - cc.base)];
                if (!R.diverged.any()) continue;
                for (size_t r = 0; r < nodes.size(); r++) {
                    if (!R.diverged.test((i64)r)) continue;
                    R.diverged.clearbit((i64)r);
                    EngineNode &dn = *nodes[r];
                    if (!dn.machine || !dn.machine->client_hash_disseminator)
                        continue;
                    ClientD *dc =
                        dn.machine->client_hash_disseminator->client(cp.first);
                    if (dc && !dc->led_classic) {
                        dc->led_diverged -= 1;
                        dn.machine->client_hash_disseminator
                            ->led_diverged_total -= 1;
                    }
                }
            }
        }
        ctx.ack_ledger->prune(minv, min_lw);
    }

    Events process_net_actions(EngineNode &node, Actions &&actions,
                               Partition *part = nullptr) {
        EventQueue &q = part ? part->q : queue;
        Events events;
        u64 t0 = __rdtsc();
        auto coalesced = coalesce_sends(std::move(actions));
        g_parts[3].fetch_add(__rdtsc() - t0, std::memory_order_relaxed);
        for (auto &action : coalesced) {
            MsgP m = action.msg();
            // Register broadcast ack waves in the cluster ledger at send
            // time (send order == arrival order under uniform latency), so
            // receivers consume them as cursor bumps + crossing replays.
            // (PDES runs require the ledger disabled.)
            if (ctx.ack_ledger != nullptr &&
                (action.targets == ctx.bcast || *action.targets == *ctx.bcast)) {
                if (part) {
                    // PDES window: provisional registration in this
                    // partition's shard, tagged with the sending step's
                    // plog index so the barrier folds it into the global
                    // ledger at the exact replay position.  Pruning is
                    // deferred to the (serial) barrier.
                    if (part->plog.empty())
                        throw EngineError("pdes ledger: send outside step");
                    u32 at = (u32)(part->plog.size() - 1);
                    if (m->t == MT::AckBatch || m->t == MT::AckMsg) {
                        part->shard->register_msg_lite(m, node.id, at);
                    } else if (m->t == MT::MsgBatch) {
                        for (const auto &im : m->inner)
                            if (im->t == MT::AckBatch || im->t == MT::AckMsg)
                                part->shard->register_msg_lite(im, node.id,
                                                               at);
                    }
                } else {
                    if (m->t == MT::AckBatch || m->t == MT::AckMsg) {
                        ctx.ack_ledger->register_msg(m, node.id);
                    } else if (m->t == MT::MsgBatch) {
                        for (const auto &im : m->inner)
                            if (im->t == MT::AckBatch || im->t == MT::AckMsg)
                                ctx.ack_ledger->register_msg(im, node.id);
                    }
                    if (ctx.ack_ledger->waves.size() >= 256) prune_ledger();
                }
            }
            for (i32 replica : *action.targets) {
                if (replica == node.id) {
                    EventS e;
                    e.t = ET::Step;
                    e.digest = replica;
                    e.payload = m;
                    events.push_back(std::move(e));
                } else {
                    if (drop_mangler && drop_matches(node.id, replica))
                        continue;  // mangled away (DropMessages)
                    SimEv ev;
                    ev.time = q.fake_time + node.runtime.link_lat(replica);
                    ev.kind = SK::MsgReceived;
                    ev.target = replica;
                    ev.src = node.id;
                    ev.msg = m;
                    if (part && part_of[(size_t)replica] != part->id) {
                        // Cross-partition send: stamp the provisional
                        // birth key from the same monotone source as heap
                        // inserts (the interleaved insertion order is the
                        // global one) and hold it for barrier delivery.
                        ev.bt = q.fake_time;
                        ev.ctr = part->prov_counter++;
                        part->outbox.push_back(std::move(ev));
                    } else {
                        q.insert(std::move(ev));
                    }
                }
            }
        }
        return events;
    }

    Events process_hash_actions(Actions &&actions, Partition *part = nullptr) {
        Events events;
        for (auto &action : actions) {
            if (action.t != AT::Hash)
                throw EngineError("unexpected Hash action type");
            HashReqP hr = action.hash();
            i32 digest = hash_parts(hr->parts, part);
            EventS e;
            e.t = ET::HashResult;
            e.digest = digest;
            e.payload = shared_ptr<const HashOriginS>(hr, &hr->origin);
            events.push_back(std::move(e));
        }
        return events;
    }

    Events process_app_actions(EngineNode &node, Actions &&actions,
                               Partition *part = nullptr) {
        Events events;
        for (auto &action : actions) {
            if (action.t == AT::Commit) {
                QEntryP q = action.qentry();
                if (pdes_threaded) {
                    std::lock_guard<std::mutex> lk(chain_mu);
                    node.state.apply(*q, ctx.intern);
                } else {
                    node.state.apply(*q, ctx.intern);
                }
                (part ? part->committed_ops : committed_ops) +=
                    (i64)q->reqs.size();
                note_commits(node, *q, part);
            } else if (action.t == AT::Checkpoint) {
                i32 value;
                if (pdes_threaded) {
                    std::lock_guard<std::mutex> lk(chain_mu);
                    value = node.state.snap(ctx.intern, action.cfg,
                                            *action.cstates());
                } else {
                    value = node.state.snap(ctx.intern, action.cfg,
                                            *action.cstates());
                }
                register_snap(value, node.state);
                refresh_node_ready(node, part);
                EventS e;
                e.t = ET::CheckpointResult;
                e.a = action.a;
                e.digest = value;
                e.payload = node.state.checkpoint_state;
                events.push_back(std::move(e));
            } else if (action.t == AT::StateTransfer) {
                // NodeState.transfer_to (testengine/recorder.py:189-206)
                // with the engine's app-level failure injection.  (Reachable
                // in PDES runs too: a lagging replica may transfer even on
                // the green path, hence the snap-registry lock below.)
                node.state.transfer_attempt_times.push_back(
                    (part ? part->q : queue).fake_time);
                i64 seq = action.a;
                i32 value = (i32)action.b;
                if (node.state.fail_transfers > 0) {
                    node.state.fail_transfers -= 1;
                    node.state.transfer_failures.push_back(seq);
                    EventS e;
                    e.t = ET::StateTransferFailed;
                    e.a = seq;
                    e.digest = value;
                    events.push_back(std::move(e));
                    continue;
                }
                std::unique_lock<std::mutex> snap_lk(snap_mu,
                                                     std::defer_lock);
                if (pdes_threaded) snap_lk.lock();
                auto sit = snap_registry.find(value);
                if (sit == snap_registry.end())
                    throw EngineError(
                        "transfer target value never snapped in this engine");
                node.state.state_transfers.push_back(seq);
                node.state.last_seq_no = seq;
                node.state.checkpoint_seq_no = seq;
                node.state.checkpoint_state = sit->second.second;
                node.state.checkpoint_hash =
                    ctx.intern.get(value).substr(0, 32);
                node.state.chain_id = sit->second.first;
                // Skipped batches are never applied: this node's floors
                // now lag the chain's canonical ones for good.
                node.state.floors_canonical = false;
                refresh_node_ready(node, part);
                EventS e;
                e.t = ET::StateTransferComplete;
                e.a = seq;
                e.digest = value;
                e.payload = node.state.checkpoint_state;
                events.push_back(std::move(e));
            } else {
                throw EngineError("unexpected App action type");
            }
        }
        return events;
    }

    // Checkpoint value id -> (chain node, network state): every value a
    // state transfer can target was produced by some replica's snap in this
    // engine, so the app-side decode is a content-addressed lookup.
    std::unordered_map<i32, std::pair<i32, NetStateP>> snap_registry;

    void register_snap(i32 value, const AppState &state) {
        if (pdes_threaded) {
            std::lock_guard<std::mutex> lk(snap_mu);
            snap_registry.emplace(
                value, std::make_pair(state.chain_id, state.checkpoint_state));
            return;
        }
        snap_registry.emplace(value,
                              std::make_pair(state.chain_id,
                                             state.checkpoint_state));
    }

    Actions process_state_machine_events(EngineNode &node, Events &&events,
                                         Partition *part = nullptr) {
        Actions actions;
        for (const auto &event : events) {
            if (event.t == ET::InitialParameters) {
                node.machine->initialize(node.init_parms);
                continue;
            }
            if (part) {
                concat(actions, node.machine->apply_event(event));
                continue;
            }
            u64 t0 = __rdtsc();
            concat(actions, node.machine->apply_event(event));
            u64 dt = __rdtsc() - t0;
            ev_cycles[(int)event.t] += dt;
            ev_counts[(int)event.t] += 1;
            if (event.t == ET::Step && event.payload) {
                const MsgS *m = (const MsgS *)event.payload.get();
                msg_cycles[(int)m->t] += dt;
                msg_counts[(int)m->t] += 1;
            }
        }
        EventS marker;
        marker.t = ET::ActionsReceived;
        concat(actions, node.machine->apply_event(marker));
        return actions;
    }

    void step(Partition *part = nullptr);
    i64 run(i64 max_steps, i64 timeout, bool *done, bool *timed_out,
            bool *need_device);
    PdesResult run_pdes(i64 partitions, bool threaded, i64 timeout,
                        i64 stop_time, i64 stop_steps);
    // Envelope probe: empty string = this engine can run under PDES with
    // the given partition count; otherwise a structured reason of the form
    // "pdes_envelope[<code>]: <detail>" (the Python layer parses the code
    // into PdesEnvelopeUnsupported.reason).
    string pdes_check(i64 partitions) const;
    void pdes_setup(i64 partitions, bool threaded);
    // Conservative lookahead for a partition assignment: the smallest
    // latency on any link that can carry a cross-partition message (with
    // the ledger live, the smallest inter-node latency outright — wave
    // registration order must fold once per window).
    i64 pdes_lookahead_for(const vector<i32> &assign) const;
    // Traffic-aware rebalancing at a barrier (all keys final, outboxes
    // empty): recompute part_of from the node-load EWMA, migrate queued
    // events, refresh pdes_W.  Returns true if the assignment changed.
    bool pdes_repartition(double imbalance);
    void pdes_window(Partition &part, i64 window_start, i64 window_end,
                     i64 step_cap);
    // Barrier replay: finalize birth-key ranks, deliver cross-partition
    // sends, fold stats and drain flips in exact global order.  Returns
    // the global step index (1-based) at which the drain predicate first
    // held, or -1.
    i64 pdes_barrier(i64 window_start, i64 *flip_time);

    // Inspect the queue head: does the next event need device results the
    // wrapper has not supplied yet?  Fills need_hash_content /
    // need_verdicts when so.  Consumes nothing; the simulated schedule is
    // independent of the pause.
    i64 ready_head_ctr = -1;  // head already vetted (pause/resume path)

    // Deep device-work scan (device-authoritative stall collapse,
    // docs/PERFORMANCE.md §9): every unsupplied wave-eligible content in
    // ANY queued ProcessHash event — not just the head's — so one pause
    // serves a whole generation of future waves in one dispatch+collect.
    // Batch contents carry no dependency on earlier device digests, so
    // everything visible is dispatchable immediately.
    void collect_pending_hash_deep(deque<string> &out) {
        // string_views over stable storage (need_hash_content is untouched
        // here; out is a deque, so grown elements keep their addresses).
        std::set<std::string_view> seen;
        for (const auto &c : need_hash_content) seen.insert(c);
        for (const auto &ev : queue.heap) {
            if (ev.kind != SK::ProcessHash || !ev.actions) continue;
            for (const auto &action : *ev.actions) {
                if (action.t != AT::Hash) continue;
                HashReqP hr = action.hash();
                if (hr->scan_state == 2) continue;
                if (hr->scan_state == 0) {
                    if (hash_is_host_floor(hr->parts)) {
                        hr->scan_state = 2;
                        continue;
                    }
                    for (const auto &p : hr->parts)
                        hr->scan_join.append(p);
                    hr->scan_state = 1;
                }
                if (device_digests.find(hr->scan_join) !=
                    device_digests.end()) {
                    hr->scan_state = 2;
                    hr->scan_join.clear();
                    hr->scan_join.shrink_to_fit();
                    continue;
                }
                if (seen.count(hr->scan_join)) continue;
                out.push_back(hr->scan_join);
                seen.insert(out.back());
            }
        }
    }

    bool check_ready() {
        if (!device_hash_mode && !streaming_auth_mode) return true;
        if (queue.heap.empty()) return true;
        const SimEv &head = queue.heap.front();
        if (head.ctr == ready_head_ctr) return true;
        need_hash_content.clear();
        need_verdicts.clear();
        if (device_hash_mode && head.kind == SK::ProcessHash) {
            for (const auto &action : *head.actions) {
                if (action.t != AT::Hash) continue;
                HashReqP hr = action.hash();
                const vector<string> &parts = hr->parts;
                if (hash_is_host_floor(parts)) continue;
                string joined;
                for (const auto &p : parts) joined.append(p);
                if (device_digests.find(joined) != device_digests.end())
                    continue;
                bool dup = false;  // same content twice in one event batch
                for (const auto &c : need_hash_content)
                    if (c == joined) { dup = true; break; }
                if (!dup) need_hash_content.push_back(std::move(joined));
            }
        }
        if (streaming_auth_mode && head.kind == SK::ClientProposal) {
            ClientSpec *cs = spec_of(head.client);
            if (cs && cs->signed_mode) {
                i64 need_to = std::min(head.reqno + (i64)PROPOSAL_CHUNK,
                                       cs->total);
                if ((i64)cs->verdicts.size() < need_to)
                    need_verdicts.emplace_back(head.client, need_to);
            }
        }
        bool ready = need_hash_content.empty() && need_verdicts.empty();
        if (ready) ready_head_ctr = head.ctr;
        return ready;
    }
    bool drained() const {
        return nodes_not_ready == 0 && clients_unsatisfied == 0;
    }
    bool node_lws_ready(const EngineNode &node) const {
        if (!node.state.checkpoint_state) return false;
        for (const auto &cs : node.state.checkpoint_state->clients) {
            auto it = drain_targets.find(cs.id);
            if (it != drain_targets.end() && it->second != cs.lw)
                return false;
        }
        return true;
    }
    void refresh_node_ready(EngineNode &node, Partition *part = nullptr) {
        bool ready = node_lws_ready(node);
        if (ready != node.drain_ready) {
            node.drain_ready = ready;
            if (part) {
                // PDES: the global counter is folded at the barrier, in
                // exact merged order (a node lives in one partition, so
                // its flag itself is safe to flip here).  Kind 1 = became
                // ready, kind 2 = regressed (e.g. a state transfer
                // installing a snapshot short of the targets).
                part->flips.push_back({(u32)part->plog.size() - 1,
                                       (u8)(ready ? 1 : 2), (i64)node.id});
            } else {
                nodes_not_ready += ready ? -1 : 1;
            }
        }
    }
    void note_commits(const EngineNode &node, const QEntryS &batch,
                      Partition *part = nullptr) {
        for (const auto &req : batch.reqs) {
            auto sit = client_satisfied.find(req.client);
            if (sit == client_satisfied.end() || sit->second) continue;
            auto tit = drain_targets.find(req.client);
            auto cit = node.state.committed_reqs.find(req.client);
            if (cit != node.state.committed_reqs.end() &&
                cit->second >= tit->second) {
                if (part) {
                    // Candidate only: client_satisfied stays untouched
                    // until the barrier (two partitions may both cross a
                    // client's threshold in one window; the replay keeps
                    // the globally-first and drops the rest).
                    part->flips.push_back(
                        {(u32)part->plog.size() - 1, 0, req.client});
                } else {
                    sit->second = true;
                    clients_unsatisfied -= 1;
                }
            }
        }
    }
};

void Engine::step(Partition *part) {
    u64 t_start = __rdtsc();
    EventQueue &queue = part ? part->q : this->queue;
    i64 plog_prov_start = part ? part->prov_counter : 0;
    SimEv event = queue.consume();
    if (part) {
        // Log the processed event's identity for the barrier replay.  The
        // key is provisional iff the event was born inside the current
        // window; births are the prov-id range consumed while processing.
        Partition::PLogE e;
        e.time = event.time;
        e.bt = event.bt;
        e.rank = event.ctr;
        e.prov_start = plog_prov_start;
        e.births = 0;  // patched below
        e.prov = event.bt >= part->window_start ? 1 : 0;
        part->plog.push_back(e);
    }
    EngineNode &node = *nodes[(size_t)event.target];
    const RuntimeParms &parms = node.runtime;
    // Ledger-on PDES: point the node's overlay slot at its partition's
    // shard for the duration of this step (null for sequential/tail
    // steps — led paths then read the global ledger alone).
    if (node.machine && node.machine->client_hash_disseminator)
        node.machine->client_hash_disseminator->led_shard =
            part && part->shard ? part->shard.get() : nullptr;

    switch (event.kind) {
        case SK::Initialize: {
            queue.remove_events_for(node.id);
            if (part)
                part->purges.push_back(
                    {(u32)(part->plog.size() - 1), node.id});
            if (event.init) {
                // Crash-and-restart: reboot under the event's parameters.
                // The restarted node missed ack-ledger wave prefixes while
                // down, so it consumes classically from here on.
                bool classic =
                    node.init_parms.led_classic || ctx.ack_ledger != nullptr;
                node.init_parms = *event.init;
                node.init_parms.led_classic = classic;
            }
            initialize_node(node);
            if (node.machine && node.machine->client_hash_disseminator)
                node.machine->client_hash_disseminator->led_shard =
                    part && part->shard ? part->shard.get() : nullptr;
            {
                SimEv tick;
                tick.time = queue.fake_time + parms.tick_interval;
                tick.kind = SK::Tick;
                tick.target = node.id;
                queue.insert(std::move(tick));
            }
            std::map<i64, const ClientStateS *> state_clients;
            for (const auto &cs : node.state.checkpoint_state->clients)
                state_clients.emplace(cs.id, &cs);
            for (const auto &client : client_specs) {
                if (client.ignore_nodes.count(node.id)) continue;
                auto it = state_clients.find(client.id);
                i64 start_req = it != state_clients.end() ? it->second->lw : 0;
                if (start_req < client.total)
                    schedule_proposal(node.id, client.id, start_req,
                                      parms.client_latency, part);
            }
            break;
        }
        case SK::MsgReceived: {
            if (node.machine) {
                EventS e;
                e.t = ET::Step;
                e.digest = event.src;
                e.payload = std::move(event.msg);
                node.work_items->result_events.push_back(std::move(e));
            }
            break;
        }
        case SK::ClientProposal: {
            ProcClient *client = node.clients->client(event.client);
            ClientSpec *sim_client = spec_of(event.client);
            if (!sim_client || sim_client->ignore_nodes.count(node.id))
                throw EngineError("node should be skipped by client");
            i64 req_no = event.reqno;
            bool broke = false;
            for (int k = 0; k < PROPOSAL_CHUNK; k++) {
                if (client->empty()) {
                    // ClientNotExistError: retry later.
                    schedule_proposal(node.id, event.client, req_no,
                                      parms.client_latency * 100, part);
                    broke = true;
                    break;
                }
                i64 next_req_no = client->next_req_no_value();
                if (next_req_no != req_no) {
                    if (next_req_no < sim_client->total)
                        schedule_proposal(node.id, event.client, next_req_no,
                                          parms.client_latency, part);
                    broke = true;
                    break;
                }
                if (sim_client->signed_mode &&
                    !(req_no < (i64)sim_client->verdicts.size() &&
                      sim_client->verdicts[(size_t)req_no])) {
                    // Forged/corrupt proposal: reject (skips the
                    // work-scheduling scan, like the Python `return`).
                    return;
                }
                AckS persisted_ack{0, 0, 0};
                if (client->propose(req_no,
                                    sim_client->payload_digests[(size_t)req_no],
                                    &persisted_ack)) {
                    EventS e;
                    e.t = ET::RequestPersisted;
                    e.ack = persisted_ack;
                    node.work_items->req_store_events.push_back(std::move(e));
                }
                req_no += 1;
                if (req_no >= sim_client->total) {
                    broke = true;
                    break;  // no more requests from this client
                }
            }
            if (!broke)
                schedule_proposal(node.id, event.client, req_no,
                                  parms.client_latency, part);
            break;
        }
        case SK::Tick: {
            EventS e;
            e.t = ET::TickElapsed;
            node.work_items->result_events.push_back(std::move(e));
            SimEv tick;
            tick.time = queue.fake_time + parms.tick_interval;
            tick.kind = SK::Tick;
            tick.target = node.id;
            queue.insert(std::move(tick));
            break;
        }
        case SK::ProcessReqStore: {
            // req_store.sync() is a no-op; events pass through.
            for (auto &e : *event.events)
                node.work_items->result_events.push_back(std::move(e));
            node.pending[5] = false;
            break;
        }
        case SK::ProcessResult: {
            Actions actions =
                process_state_machine_events(node, std::move(*event.events), part);
            node.work_items->add_state_machine_results(std::move(actions));
            node.pending[6] = false;
            break;
        }
        case SK::ProcessWal: {
            Actions net =
                process_wal_actions(node, std::move(*event.actions));
            for (auto &a : net)
                node.work_items->net_actions.push_back(std::move(a));
            node.pending[0] = false;
            break;
        }
        case SK::ProcessNet: {
            Events events =
                process_net_actions(node, std::move(*event.actions), part);
            for (auto &e : events)
                node.work_items->result_events.push_back(std::move(e));
            node.pending[1] = false;
            break;
        }
        case SK::ProcessHash: {
            Events events = process_hash_actions(std::move(*event.actions), part);
            for (auto &e : events)
                node.work_items->result_events.push_back(std::move(e));
            node.pending[3] = false;
            break;
        }
        case SK::ProcessClient: {
            Events events =
                node.clients->process_client_actions(*event.actions);
            for (auto &e : events)
                node.work_items->req_store_events.push_back(std::move(e));
            node.pending[2] = false;
            break;
        }
        case SK::ProcessApp: {
            Events events =
                process_app_actions(node, std::move(*event.actions), part);
            for (auto &e : events)
                node.work_items->result_events.push_back(std::move(e));
            node.pending[4] = false;
            break;
        }
    }

    if (part) {
        u64 dt = __rdtsc() - t_start;
        part->work_cycles += dt;
        part->node_cycles[(size_t)event.target] += dt;
    } else {
        kind_cycles[(int)event.kind] += __rdtsc() - t_start;
        kind_counts[(int)event.kind] += 1;
    }

    if (!node.work_items) {
        if (part)
            part->plog.back().births =
                (u32)(part->prov_counter - plog_prov_start);
        return;
    }

    // Schedule processing for non-empty categories with no batch in flight
    // (same order as recorder.py:742-749).
    WorkItems &work = *node.work_items;
    struct Cat {
        int idx;
        bool is_events;
        SK kind;
        i64 latency;
    };
    const Cat cats[7] = {
        {0, false, SK::ProcessWal, parms.wal_latency},
        {1, false, SK::ProcessNet, parms.net_latency},
        {2, false, SK::ProcessClient, parms.client_latency},
        {3, false, SK::ProcessHash, parms.hash_latency},
        {4, false, SK::ProcessApp, parms.app_latency},
        {5, true, SK::ProcessReqStore, parms.req_store_latency},
        {6, true, SK::ProcessResult, parms.events_latency},
    };
    Actions *action_batches[5] = {&work.wal_actions, &work.net_actions,
                                  &work.client_actions, &work.hash_actions,
                                  &work.app_actions};
    Events *event_batches[2] = {&work.req_store_events, &work.result_events};
    for (const Cat &cat : cats) {
        if (node.pending[cat.idx]) continue;
        if (!cat.is_events) {
            Actions *batch = action_batches[cat.idx];
            if (batch->empty()) continue;
            node.pending[cat.idx] = true;
            SimEv ev;
            ev.time = queue.fake_time + cat.latency;
            ev.kind = cat.kind;
            ev.target = node.id;
            ev.actions = std::make_shared<Actions>(std::move(*batch));
            batch->clear();
            queue.insert(std::move(ev));
        } else {
            Events *batch = event_batches[cat.idx - 5];
            if (batch->empty()) continue;
            node.pending[cat.idx] = true;
            SimEv ev;
            ev.time = queue.fake_time + cat.latency;
            ev.kind = cat.kind;
            ev.target = node.id;
            ev.events = std::make_shared<Events>(std::move(*batch));
            batch->clear();
            queue.insert(std::move(ev));
        }
    }
    if (part)
        part->plog.back().births =
            (u32)(part->prov_counter - plog_prov_start);
}

i64 Engine::run(i64 max_steps, i64 timeout, bool *done, bool *timed_out,
                bool *need_device) {
    *done = false;
    *timed_out = false;
    *need_device = false;
    i64 executed = 0;
    while (executed < max_steps) {
        if (!check_ready()) {
            *need_device = true;
            return executed;
        }
        steps += 1;
        executed += 1;
        step();
        if (drained()) {
            *done = true;
            return executed;
        }
        if (steps > timeout) {
            *timed_out = true;
            return executed;
        }
    }
    return executed;
}

// ---------------------------------------------------------------------------
// PDES run modes (docs/PERFORMANCE.md §7.1).  The simulation is bit-
// identical to the sequential engine: each window is processed partition-
// locally under provisional birth keys, and the barrier replay reconstructs
// the exact global order (see struct Partition above).  Two modes:
//
// * measurement (stop_steps < 0): run until the drain predicate first
//   holds, detected at the following barrier.  The returned step count and
//   fake-time are EXACT (computed from the replay); the engine state
//   overshoots by at most one window, so node summaries are not the
//   drain-step state.  This is the bench mode.
// * exact (stop_time/stop_steps from a prior run): process full windows
//   strictly before stop_time, then merge every partition queue into the
//   sequential queue and finish single-threaded to exactly stop_steps.
//   Node summaries then match the sequential engine bit-for-bit.
// ---------------------------------------------------------------------------

string Engine::pdes_check(i64 partitions) const {
    // Structured envelope probe (empty = eligible).  Codes are stable API:
    // the Python layer parses "pdes_envelope[<code>]" into
    // PdesEnvelopeUnsupported.reason, and bench.py keys c3_pdes_envelope
    // off them.  The structured DropMessages mangler IS in the envelope:
    // it applies at the SEND site (process_net_actions), which is
    // partition-local and deterministic — no RNG, no queue surgery.
    // Start delays and ignored nodes are in the envelope too: boot-time
    // queue purges replay exactly (see Partition::Purge), ignore sets are
    // partition-local, and a late-boot node consumes acks classically
    // when the ledger is live (led_classic at construction).
    if (!parts.empty())
        return "pdes_envelope[state]: pdes already initialized";
    if (steps != 0 || queue.fake_time != 0)
        return "pdes_envelope[state]: pdes requires a fresh engine";
    if (queue.mangler)
        return "pdes_envelope[mangler]: no consume-time manglers";
    if (device_hash_mode || streaming_auth_mode)
        return "pdes_envelope[device]: no device-paced modes";
    if (!reconfig_points.empty())
        return "pdes_envelope[reconfig]: no reconfiguration";
    for (const auto &np : nodes) {
        if (np->state.fail_transfers > 0)
            return "pdes_envelope[transfer_fail]: "
                   "no transfer-failure injection";
        if (np->runtime.link_latency < 1)
            return "pdes_envelope[latency]: link latency must be positive";
        for (size_t d = 0; d < np->runtime.lat_to.size(); d++)
            if ((i64)d != (i64)np->id && np->runtime.lat_to[d] < 1)
                return "pdes_envelope[latency]: "
                       "link latency must be positive";
    }
    if (partitions < 1 || partitions > (i64)nodes.size())
        return "pdes_envelope[partitions]: "
               "partitions must be in [1, node count]";
    return "";
}

i64 Engine::pdes_lookahead_for(const vector<i32> &assign) const {
    const i64 N = (i64)nodes.size();
    i64 w = INT64_MAX;
    for (i64 j = 0; j < N; j++) {
        const RuntimeParms &rt = nodes[(size_t)j]->runtime;
        for (i64 k = 0; k < N; k++) {
            if (j == k) continue;
            if (ctx.ack_ledger == nullptr &&
                assign[(size_t)j] == assign[(size_t)k])
                continue;
            w = std::min(w, rt.link_lat(k));
        }
    }
    if (w == INT64_MAX) {
        // Single partition, ledger off: no link constrains the window;
        // fall back to the smallest inter-node latency so window/barrier
        // cadence (and stats) stay comparable across partition counts.
        for (i64 j = 0; j < N; j++)
            for (i64 k = 0; k < N; k++)
                if (j != k)
                    w = std::min(w, nodes[(size_t)j]->runtime.link_lat(k));
        if (w == INT64_MAX) w = nodes[0]->runtime.link_latency;
    }
    return w;
}

void Engine::pdes_setup(i64 partitions, bool threaded) {
    string reason = pdes_check(partitions);
    if (!reason.empty()) throw EngineError(reason);
    pdes_threaded = threaded;
    if (threaded) ctx.intern.mu = &intern_mu;
    i64 N = (i64)nodes.size();
    part_of.assign((size_t)N, 0);
    node_load.assign((size_t)N, 0.0);
    for (i64 p = 0; p < partitions; p++) {
        auto part = std::make_unique<Partition>();
        part->id = (i32)p;
        part->q.stamp_mode = EventQueue::PDES;
        part->q.prov = &part->prov_counter;
        part->node_cycles.assign((size_t)N, 0);
        if (ctx.ack_ledger != nullptr) {
            part->shard = std::make_unique<AckShard>();
            part->shard->global = ctx.ack_ledger;
        }
        parts.push_back(std::move(part));
    }
    if (ctx.ack_ledger != nullptr) {
        // Pre-populate every client and its full reachable record range:
        // during windows partition threads may only READ the global
        // ledger's structure (operator[] inserts and rec_or_create
        // extensions would race); req_no never exceeds a sender's high
        // watermark <= total + width.
        for (const auto &ic : ctx.init_clients) {
            CanonClient &cc = ctx.ack_ledger->client(ic.id);
            i64 total = 0;
            const ClientSpec *cs = spec_of(ic.id);
            if (cs) total = cs->total;
            cc.rec_or_create(0);
            cc.rec_or_create(total + 2 * ic.width + 16);
        }
    }
    for (i64 i = 0; i < N; i++)
        part_of[(size_t)i] = (i32)(i * partitions / N);
    pdes_W = pdes_lookahead_for(part_of);
    // Distribute genesis events, restamped to birth time -1 (before any
    // in-run birth, so window-0 births cannot collide with their keys).
    for (auto &ev : queue.heap) {
        ev.bt = -1;
        Partition &pp = *parts[(size_t)part_of[(size_t)ev.target]];
        pp.q.heap.push_back(std::move(ev));
    }
    queue.heap.clear();
    for (auto &pp : parts)
        std::make_heap(pp->q.heap.begin(), pp->q.heap.end(), SimEvCmp());
}

bool Engine::pdes_repartition(double imbalance) {
    const size_t P = parts.size();
    const i64 N = (i64)nodes.size();
    double total = 0;
    for (i64 i = 0; i < N; i++) total += node_load[(size_t)i];
    if (total <= 0) return false;
    // Weights: the node-load EWMA, floored so currently-idle nodes still
    // count (they own future traffic once their clients rotate in).
    vector<double> w((size_t)N);
    double floor_w = total / (double)(N * 64);
    for (i64 i = 0; i < N; i++)
        w[(size_t)i] = std::max(node_load[(size_t)i], floor_w);
    bool nonuniform = false;
    for (const auto &np : nodes)
        if (!np->runtime.lat_to.empty()) nonuniform = true;
    vector<vector<i32>> cands;
    {
        // Contiguous weighted split: preserves index locality (regional
        // latency matrices are index-contiguous), which is what keeps the
        // cross-partition lookahead wide on WAN topologies.
        vector<i32> c((size_t)N, 0);
        double tw = 0;
        for (double x : w) tw += x;
        double per = tw / (double)P;
        double acc = 0;
        i32 cur = 0;
        i64 in_cur = 0;
        for (i64 i = 0; i < N; i++) {
            i64 remaining = N - i;
            if (cur < (i32)P - 1 && in_cur > 0 &&
                (acc >= per * (double)(cur + 1) ||
                 remaining == (i64)P - 1 - (i64)cur)) {
                cur += 1;
                in_cur = 0;
            }
            c[(size_t)i] = cur;
            in_cur += 1;
            acc += w[(size_t)i];
        }
        cands.push_back(std::move(c));
    }
    if (!nonuniform) {
        // LPT greedy onto the least-loaded partition (uniform latency:
        // any assignment keeps the same lookahead).
        vector<i64> order((size_t)N);
        for (i64 i = 0; i < N; i++) order[(size_t)i] = i;
        std::sort(order.begin(), order.end(), [&](i64 a, i64 b) {
            if (w[(size_t)a] != w[(size_t)b])
                return w[(size_t)a] > w[(size_t)b];
            return a < b;
        });
        vector<i32> c((size_t)N, 0);
        vector<double> bin(P, 0.0);
        for (i64 i : order) {
            size_t best = 0;
            for (size_t b = 1; b < P; b++)
                if (bin[b] < bin[best]) best = b;
            c[(size_t)i] = (i32)best;
            bin[best] += w[(size_t)i];
        }
        cands.push_back(std::move(c));
        // Round-robin interleave: bucket ownership rotates through
        // consecutive node ids, so a commit sweep's hot neighbors land in
        // different partitions — this balances each window's burst, which
        // total-weight balancing cannot see.
        vector<i32> rr((size_t)N);
        for (i64 i = 0; i < N; i++) rr[(size_t)i] = (i32)(i % (i64)P);
        cands.push_back(std::move(rr));
    }
    // Score = the objective itself on recent history: sum over kept
    // windows of that window's critical path (max partition member-cycle
    // sum) under the assignment.  The incumbent competes on the same
    // history, and migration isn't free, so switching needs a real win.
    auto score = [&](const vector<i32> &asn) {
        double s = 0;
        vector<double> bin(P, 0.0);
        for (const auto &hv : node_hist) {
            std::fill(bin.begin(), bin.end(), 0.0);
            for (i64 i = 0; i < N; i++)
                bin[(size_t)asn[(size_t)i]] += (double)hv[(size_t)i];
            s += *std::max_element(bin.begin(), bin.end());
        }
        return s;
    };
    const double cur_score = score(part_of);
    const vector<i32> *chosen = nullptr;
    double chosen_score = cur_score;
    for (const auto &c : cands) {
        if (c == part_of) continue;
        // Never trade lookahead for balance unless the imbalance is
        // severe: a narrower window multiplies barrier count for every
        // partition.
        if (pdes_lookahead_for(c) < pdes_W && imbalance <= 2.0) continue;
        double s = score(c);
        if (s < chosen_score) {
            chosen_score = s;
            chosen = &c;
        }
    }
    if (chosen == nullptr || chosen_score > 0.97 * cur_score) return false;
    const vector<i32> cand = *chosen;
    // Migrate queued events.  Safe at a barrier: every pending key is
    // final, outboxes are empty, plogs are cleared.
    vector<vector<SimEv>> moved(P);
    for (size_t p = 0; p < P; p++) {
        auto &hp = parts[p]->q.heap;
        size_t keep = 0;
        for (size_t k = 0; k < hp.size(); k++) {
            size_t np2 = (size_t)cand[(size_t)hp[k].target];
            if (np2 == p) {
                if (keep != k) hp[keep] = std::move(hp[k]);
                keep += 1;
            } else {
                moved[np2].push_back(std::move(hp[k]));
            }
        }
        hp.resize(keep);
    }
    for (size_t p = 0; p < P; p++) {
        auto &hp = parts[p]->q.heap;
        for (auto &ev : moved[p]) hp.push_back(std::move(ev));
        std::make_heap(hp.begin(), hp.end(), SimEvCmp());
    }
    part_of = cand;
    pdes_W = pdes_lookahead_for(part_of);
    return true;
}

void Engine::pdes_window(Partition &part, i64 window_start, i64 window_end,
                         i64 step_cap) {
    part.window_start = window_start;
    part.prov_base = part.prov_counter;
    EventQueue &q = part.q;
    while (!q.heap.empty() && q.heap.front().time < window_end) {
        step(&part);
        part.steps += 1;
        if (part.steps > step_cap)
            throw EngineError("pdes: window step runaway (timeout)");
    }
}

i64 Engine::pdes_barrier(i64 window_start, i64 *flip_time) {
    const size_t P = parts.size();
    // prov id -> final rank, per partition (dense, window-scoped).  The
    // buffers are engine members: capacity persists across windows, so
    // the per-barrier cost is an assign(), not an allocation.
    auto &fin = pdes_fin;
    auto &logi = pdes_logi;
    auto &flipi = pdes_flipi;
    auto &purgei = pdes_purgei;
    if (fin.size() < P) fin.resize(P);
    logi.assign(P, 0);
    flipi.assign(P, 0);
    purgei.assign(P, 0);
    for (size_t p = 0; p < P; p++)
        fin[p].assign(
            (size_t)(parts[p]->prov_counter - parts[p]->prov_base), -1);
    auto resolved = [&](size_t p, const Partition::PLogE &e) -> i64 {
        if (!e.prov) return e.rank;
        i64 r = fin[p][(size_t)(e.rank - parts[p]->prov_base)];
        if (r < 0) throw EngineError("pdes: unresolved rank in merge");
        return r;
    };
    // Incremental k-way merge: a binary min-heap of partition heads keyed
    // (time, bt, resolved rank) replaces the O(P) scan per pop.  A head's
    // rank is always resolvable when (re)pushed: a window-born event's
    // birth precedes it in the SAME partition's plog (its parent was
    // processed there first), so the birth was merged — and ranked —
    // before the event can become that partition's head.
    struct Head {
        i64 time, bt, rk;
        size_t p;
    };
    auto later = [](const Head &a, const Head &b) {
        if (a.time != b.time) return a.time > b.time;
        if (a.bt != b.bt) return a.bt > b.bt;
        return a.rk > b.rk;
    };
    vector<Head> heads;
    heads.reserve(P);
    for (size_t p = 0; p < P; p++) {
        if (parts[p]->plog.empty()) continue;
        const auto &e = parts[p]->plog[0];
        heads.push_back({e.time, e.bt, resolved(p, e), p});
    }
    std::make_heap(heads.begin(), heads.end(), later);
    i64 cur_bt = INT64_MIN, bt_rank = 0, flip_step = -1;
    while (!heads.empty()) {
        std::pop_heap(heads.begin(), heads.end(), later);
        const size_t best = heads.back().p;
        heads.pop_back();
        Partition &pp = *parts[best];
        const auto &e = pp.plog[logi[best]];
        // Initialize-driven queue purges act first: in the sequential
        // engine remove_events_for ran before the boot event's own births,
        // so exactly the already-ranked (= born-earlier) same-window cross
        // sends to the booting node are dropped.
        while (purgei[best] < pp.purges.size() &&
               pp.purges[purgei[best]].at == logi[best]) {
            const i32 purged = pp.purges[purgei[best]++].node;
            for (size_t p2 = 0; p2 < P; p2++) {
                auto &ob = parts[p2]->outbox;
                const i64 base2 = parts[p2]->prov_base;
                ob.erase(
                    std::remove_if(
                        ob.begin(), ob.end(),
                        [&](const SimEv &ev) {
                            return ev.target == purged &&
                                   fin[p2][(size_t)(ev.ctr - base2)] >= 0;
                        }),
                    ob.end());
            }
        }
        // Fold this step's provisional ack waves into the global ledger:
        // the merged order IS the sequential send order, so re-registering
        // here reproduces the canonical positions and logs bit-for-bit.
        // The sender's early-consumed provisional position is remapped to
        // the final one (then absorbed, matching the sequential cursor);
        // the shard overlay itself is discarded wholesale below.
        if (ctx.ack_ledger != nullptr) {
            AckShard &shard = *pp.shard;
            while (shard.foldi < shard.waves.size() &&
                   shard.waves[shard.foldi].plog_at == logi[best]) {
                AckShard::ShardWave &sw = shard.waves[shard.foldi++];
                const u32 prov = sw.reg.pos;
                const MsgP &m = sw.reg.msg;
                m->wave_id = -1;
                ctx.ack_ledger->register_msg(m, sw.src);
                EngineNode &sn = *nodes[(size_t)sw.src];
                if (sn.machine && sn.machine->client_hash_disseminator) {
                    LedView &lv =
                        sn.machine->client_hash_disseminator->led_view;
                    for (auto &pos : lv.own_early)
                        if (pos == prov) pos = (u32)m->wave_id;
                    lv.absorb();
                }
            }
        }
        // Its births get the next ranks of the insertion sequence at this
        // timestamp (the merged order IS the sequential processing order).
        if (e.time != cur_bt) {
            cur_bt = e.time;
            bt_rank = 0;
        }
        for (u32 k = 0; k < e.births; k++)
            fin[best][(size_t)(e.prov_start - pp.prov_base) + k] = bt_rank++;
        steps += 1;
        // Drain-predicate flips caused by this event, in global order.
        while (flipi[best] < pp.flips.size() &&
               pp.flips[flipi[best]].at == logi[best]) {
            const auto &f = pp.flips[flipi[best]++];
            if (f.kind == 0) {
                auto sit = client_satisfied.find(f.id);
                if (sit != client_satisfied.end() && !sit->second) {
                    sit->second = true;
                    clients_unsatisfied -= 1;
                }
            } else if (f.kind == 1) {
                nodes_not_ready -= 1;
            } else {
                nodes_not_ready += 1;
            }
            if (flip_step < 0 && drained()) {
                flip_step = steps;
                *flip_time = e.time;
            }
        }
        logi[best] += 1;
        if (logi[best] < pp.plog.size()) {
            const auto &ne = pp.plog[logi[best]];
            heads.push_back({ne.time, ne.bt, resolved(best, ne), best});
            std::push_heap(heads.begin(), heads.end(), later);
        }
    }
    // Re-stamp window-born events still pending, and the cross sends.
    for (size_t p = 0; p < P; p++) {
        Partition &pp = *parts[p];
        for (auto &ev : pp.q.heap) {
            if (ev.bt < window_start) continue;
            i64 r = fin[p][(size_t)(ev.ctr - pp.prov_base)];
            if (r < 0) throw EngineError("pdes: pending event unresolved");
            ev.ctr = r;
            // Relative order within every same-(time, bt) group is
            // preserved by construction, so the heap stays a heap.
        }
        for (auto &ev : pp.outbox) {
            i64 r = fin[p][(size_t)(ev.ctr - pp.prov_base)];
            if (r < 0) throw EngineError("pdes: outbox event unresolved");
            ev.ctr = r;
        }
    }
    // Deliver cross-partition sends (keys final; plain heap insert).
    for (size_t p = 0; p < P; p++) {
        for (auto &ev : parts[p]->outbox) {
            Partition &tgt = *parts[(size_t)part_of[(size_t)ev.target]];
            tgt.q.insert_stamped(std::move(ev));
        }
        parts[p]->outbox.clear();
    }
    // Fold window stats.
    for (size_t p = 0; p < P; p++) {
        Partition &pp = *parts[p];
        committed_ops += pp.committed_ops;
        pp.committed_ops = 0;
        crypto_ns += pp.crypto_ns;
        pp.crypto_ns = 0;
        pp.steps = 0;
        pp.plog.clear();
        pp.flips.clear();
        pp.purges.clear();
        if (pp.shard) {
            if (pp.shard->foldi != pp.shard->waves.size())
                throw EngineError("pdes ledger: unfolded shard waves");
            pp.shard->clear();
        }
    }
    // Deferred ledger pruning (serial here; structural mutation is unsafe
    // inside windows).
    if (ctx.ack_ledger != nullptr && ctx.ack_ledger->waves.size() >= 256)
        prune_ledger();
    // Fold the per-node work attribution into the traffic EWMA (each node
    // accrues only in its own partition), and keep the raw window vector:
    // candidate assignments are scored against the recent burst history.
    const i64 N = (i64)nodes.size();
    vector<u64> winv((size_t)N);
    for (i64 i = 0; i < N; i++) {
        Partition &pp = *parts[(size_t)part_of[(size_t)i]];
        u64 c = pp.node_cycles[(size_t)i];
        pp.node_cycles[(size_t)i] = 0;
        winv[(size_t)i] = c;
        node_load[(size_t)i] =
            0.7 * node_load[(size_t)i] + 0.3 * (double)c;
    }
    node_hist.push_back(std::move(winv));
    if (node_hist.size() > 8) node_hist.pop_front();
    return flip_step;
}

PdesResult Engine::run_pdes(i64 partitions, bool threaded, i64 timeout,
                            i64 stop_time, i64 stop_steps) {
    if (parts.empty()) pdes_setup(partitions, threaded);
    const size_t P = parts.size();
    const bool exact = stop_steps >= 0;
    const i64 step_cap = timeout + 1000;
    PdesResult res;
    res.lookahead = pdes_W;
    res.ledger_on = ctx.ack_ledger != nullptr;
    // Traffic-aware rebalancing cadence: the first windows are the
    // profiling prefix (seed assignment is naive-contiguous), then
    // rebalance on sustained imbalance with a cooldown so the event
    // migration cost amortizes.
    // The candidate scorer competes the incumbent on the same history
    // with hysteresis, so the trigger can run often and cheaply; the
    // cooldown only bounds migration churn.
    const i64 profile_windows = 3;
    const i64 repart_cooldown = 4;
    i64 last_repart = 0;

    // Persistent worker pool (threaded mode): generation-counter barrier.
    std::vector<std::thread> workers;
    std::mutex mu;
    std::condition_variable cv_go, cv_done;
    i64 gen = 0;
    size_t done_count = 0;
    bool shutdown = false;
    i64 cur_T = 0, cur_end = 0;
    const bool pool = threaded && P > 1;
    if (pool) {
        for (size_t p = 0; p < P; p++) {
            workers.emplace_back([&, p] {
                i64 seen = 0;
                while (true) {
                    i64 a, b;
                    {
                        std::unique_lock<std::mutex> lk(mu);
                        cv_go.wait(lk,
                                   [&] { return shutdown || gen > seen; });
                        if (shutdown) return;
                        seen = gen;
                        a = cur_T;
                        b = cur_end;
                    }
                    try {
                        pdes_window(*parts[p], a, b, step_cap);
                    } catch (const std::exception &ex) {
                        parts[p]->error = ex.what();
                    }
                    {
                        std::lock_guard<std::mutex> lk(mu);
                        done_count += 1;
                    }
                    cv_done.notify_all();
                }
            });
        }
    }
    auto stop_pool = [&] {
        if (!pool) return;
        {
            std::lock_guard<std::mutex> lk(mu);
            shutdown = true;
        }
        cv_go.notify_all();
        for (auto &w : workers) w.join();
        workers.clear();
    };

    i64 T = 0;
    try {
        while (true) {
            // Jump over empty stretches (no events in [T, next_t)).
            i64 next_t = INT64_MAX;
            for (auto &pp : parts)
                if (!pp->q.heap.empty())
                    next_t = std::min(next_t, pp->q.heap.front().time);
            if (next_t == INT64_MAX) break;  // queues fully drained
            if (next_t > T) T = next_t;
            i64 window_end = T + pdes_W;
            if (exact && window_end > stop_time) break;  // tail takes over

            u64 t0 = __rdtsc();
            if (pool) {
                {
                    std::lock_guard<std::mutex> lk(mu);
                    cur_T = T;
                    cur_end = window_end;
                    done_count = 0;
                    gen += 1;
                }
                cv_go.notify_all();
                {
                    std::unique_lock<std::mutex> lk(mu);
                    cv_done.wait(lk, [&] { return done_count == P; });
                }
                for (auto &pp : parts)
                    if (!pp->error.empty()) throw EngineError(pp->error);
            } else {
                for (auto &pp : parts)
                    pdes_window(*pp, T, window_end, step_cap);
            }
            u64 t1 = __rdtsc();
            u64 win_max = 0, win_sum = 0;
            for (auto &pp : parts) {
                win_sum += pp->work_cycles;
                if (pp->work_cycles > win_max) win_max = pp->work_cycles;
                pp->work_cycles = 0;
            }
            res.sum_part_cycles += win_sum;
            res.max_part_cycles += win_max;

            i64 ft = -1;
            auto b0 = std::chrono::steady_clock::now();
            i64 flip = pdes_barrier(T, &ft);
            res.barrier_cycles += __rdtsc() - t1;
            res.barrier_ns += (u64)std::chrono::duration_cast<
                                  std::chrono::nanoseconds>(
                                  std::chrono::steady_clock::now() - b0)
                                  .count();
            (void)t0;
            res.windows += 1;
            if (flip >= 0 && res.flip_step < 0) {
                res.flip_step = flip;
                res.flip_time = ft;
            }
            if (!exact && res.flip_step >= 0) break;
            if (steps > timeout) {
                res.timed_out = true;
                break;
            }
            // Rebalance at the barrier: once after the profiling prefix
            // (seeding from observed per-node work), then only on
            // sustained imbalance past the cooldown.
            if (P > 1) {
                double imb = win_sum > 0
                                 ? (double)win_max * (double)P /
                                       (double)win_sum
                                 : 1.0;
                bool due = res.windows == profile_windows ||
                           (res.windows - last_repart >= repart_cooldown &&
                            imb > 1.05);
                if (due && pdes_repartition(imb)) {
                    res.repartitions += 1;
                    res.lookahead = pdes_W;
                    last_repart = res.windows;
                }
            }
            T = window_end;
        }
        stop_pool();
    } catch (...) {
        stop_pool();
        throw;
    }

    if (exact && !res.timed_out) {
        // Sequential tail: merge every partition queue into the main one
        // (all keys final after the last barrier) and finish exactly.
        queue.fake_time = T;
        queue.stamp_mode = EventQueue::TAIL;
        for (auto &pp : parts) {
            for (auto &ev : pp->q.heap)
                queue.heap.push_back(std::move(ev));
            pp->q.heap.clear();
        }
        std::make_heap(queue.heap.begin(), queue.heap.end(), SimEvCmp());
        while (steps < stop_steps) {
            if (queue.heap.empty())
                throw EngineError("pdes exact: queue drained before stop");
            step(nullptr);
            steps += 1;
            res.tail_steps += 1;
        }
        res.done = true;
        res.steps = steps;
        res.fake_time = queue.fake_time;
    } else if (!exact) {
        res.done = res.flip_step >= 0;
        res.steps = res.done ? res.flip_step : steps;
        res.fake_time = res.done ? res.flip_time : 0;
        // Surface the exact drain point through stats(): the engine state
        // has overshot by up to one window (measurement mode), but the
        // reported step count and fake-time are the sequential ones.
        steps = res.steps;
        queue.fake_time = res.fake_time;
    }
    return res;
}

// ---------------------------------------------------------------------------
// Python bindings.
// ---------------------------------------------------------------------------

struct PyEngine {
    PyObject_HEAD
    Engine *engine;
};

// Owned-reference guard for the config-parsing paths: releases on every
// exit, including EngineError throws from get_i64.
struct PyRef {
    PyObject *p;
    explicit PyRef(PyObject *obj) : p(obj) {}
    ~PyRef() { Py_XDECREF(p); }
    PyRef(const PyRef &) = delete;
    PyRef &operator=(const PyRef &) = delete;
    explicit operator bool() const { return p != nullptr; }
};

i64 get_i64(PyObject *seq, Py_ssize_t i) {
    PyObject *o = PySequence_GetItem(seq, i);
    if (!o) throw EngineError("bad config item");
    i64 v = PyLong_AsLongLong(o);
    Py_DECREF(o);
    if (v == -1 && PyErr_Occurred()) throw EngineError("bad config int");
    return v;
}

void engine_dealloc(PyObject *self) {
    delete ((PyEngine *)self)->engine;
    Py_TYPE(self)->tp_free(self);
}

PyObject *engine_new(PyTypeObject *type, PyObject *args, PyObject *) {
    PyObject *net_tuple, *client_states, *client_specs, *node_specs;
    PyObject *mangler = Py_None;
    long long random_seed = 0;
    PyObject *reconfig_points = Py_None;
    long long flags = 0;  // bit 0: disable the ack ledger (PDES runs)
    if (!PyArg_ParseTuple(args, "OOOO|OLOL", &net_tuple, &client_states,
                          &client_specs, &node_specs, &mangler, &random_seed,
                          &reconfig_points, &flags))
        return nullptr;
    auto *engine = new Engine();
    try {
        engine->ctx.cfg.ci = get_i64(net_tuple, 1);
        engine->ctx.cfg.mel = get_i64(net_tuple, 2);
        engine->ctx.cfg.nb = get_i64(net_tuple, 3);
        engine->ctx.cfg.f = get_i64(net_tuple, 4);
        i64 n_nodes = get_i64(net_tuple, 0);
        if (n_nodes < 1 || n_nodes > 256)
            throw EngineError("fastengine supports 1..256 nodes");
        for (i64 i = 0; i < n_nodes; i++)
            engine->ctx.cfg.nodes.push_back((i32)i);
        engine->ctx.finish_init();

        // Initial client states: (id, width).
        Py_ssize_t n_cs = PySequence_Size(client_states);
        for (Py_ssize_t i = 0; i < n_cs; i++) {
            PyRef cs(PySequence_GetItem(client_states, i));
            if (!cs) throw EngineError("bad client state");
            ClientStateS c;
            c.id = get_i64(cs.p, 0);
            c.width = get_i64(cs.p, 1);
            c.wclc = 0;
            c.lw = 0;
            engine->ctx.init_clients.push_back(std::move(c));
        }

        // Client specs.
        Py_ssize_t n_clients = PySequence_Size(client_specs);
        for (Py_ssize_t i = 0; i < n_clients; i++) {
            PyRef spec(PySequence_GetItem(client_specs, i));
            if (!spec) throw EngineError("bad client spec");
            ClientSpec c;
            c.id = get_i64(spec.p, 0);
            c.total = get_i64(spec.p, 1);
            c.signed_mode = get_i64(spec.p, 2) != 0;
            c.corrupt = get_i64(spec.p, 3) != 0;
            {
                PyRef ignores(PySequence_GetItem(spec.p, 4));
                if (!ignores) throw EngineError("bad ignore list");
                Py_ssize_t n_ign = PySequence_Size(ignores.p);
                for (Py_ssize_t k = 0; k < n_ign; k++)
                    c.ignore_nodes.insert((i32)get_i64(ignores.p, k));
            }
            {
                PyRef payloads(PySequence_GetItem(spec.p, 5));
                if (!payloads) throw EngineError("bad payload list");
                Py_ssize_t n_pl = PySequence_Size(payloads.p);
                for (Py_ssize_t k = 0; k < n_pl; k++) {
                    PyRef b(PySequence_GetItem(payloads.p, k));
                    char *buf;
                    Py_ssize_t blen;
                    if (!b || PyBytes_AsStringAndSize(b.p, &buf, &blen) < 0)
                        throw EngineError("payload must be bytes");
                    string payload(buf, (size_t)blen);
                    c.payloads.push_back(engine->ctx.intern.put(payload));
                    c.payload_digests.push_back(
                        engine->ctx.intern.put(sha256(payload)));
                }
            }
            {
                PyRef verdicts(PySequence_GetItem(spec.p, 6));
                if (!verdicts) throw EngineError("bad verdicts");
                if (verdicts.p != Py_None) {
                    char *buf;
                    Py_ssize_t blen;
                    if (PyBytes_AsStringAndSize(verdicts.p, &buf, &blen) < 0)
                        throw EngineError("verdicts must be bytes or None");
                    for (Py_ssize_t k = 0; k < blen; k++)
                        c.verdicts.push_back((u8)buf[k]);
                }
            }
            engine->client_specs.push_back(std::move(c));
        }

        // Node specs.
        Py_ssize_t n_ns = PySequence_Size(node_specs);
        if (n_ns != (Py_ssize_t)n_nodes)
            throw EngineError("node spec count mismatch");
        for (Py_ssize_t i = 0; i < n_ns; i++) {
            PyRef spec(PySequence_GetItem(node_specs, i));
            if (!spec) throw EngineError("bad node spec");
            auto node = std::make_unique<EngineNode>();
            node->id = (i32)i;
            node->start_delay = get_i64(spec.p, 0);
            node->runtime.tick_interval = get_i64(spec.p, 1);
            node->runtime.link_latency = get_i64(spec.p, 2);
            node->runtime.wal_latency = get_i64(spec.p, 3);
            node->runtime.net_latency = get_i64(spec.p, 4);
            node->runtime.hash_latency = get_i64(spec.p, 5);
            node->runtime.client_latency = get_i64(spec.p, 6);
            node->runtime.app_latency = get_i64(spec.p, 7);
            node->runtime.req_store_latency = get_i64(spec.p, 8);
            node->runtime.events_latency = get_i64(spec.p, 9);
            node->init_parms.id = (i32)i;
            node->init_parms.batch_size = get_i64(spec.p, 10);
            node->init_parms.heartbeat_ticks = get_i64(spec.p, 11);
            node->init_parms.suspect_ticks = get_i64(spec.p, 12);
            node->init_parms.new_epoch_timeout_ticks = get_i64(spec.p, 13);
            node->init_parms.buffer_size = get_i64(spec.p, 14);
            // Optional element 15: per-destination link-latency row (None
            // or an N-tuple) — see RuntimeParms::lat_to.
            if (PySequence_Size(spec.p) > 15) {
                PyRef lat(PySequence_GetItem(spec.p, 15));
                if (!lat) throw EngineError("bad node spec");
                if (lat.p != Py_None) {
                    Py_ssize_t nl = PySequence_Size(lat.p);
                    if (nl != (Py_ssize_t)n_nodes)
                        throw EngineError(
                            "link_latency_to row length must equal node count");
                    for (Py_ssize_t k = 0; k < nl; k++)
                        node->runtime.lat_to.push_back(get_i64(lat.p, k));
                }
            }
            engine->nodes.push_back(std::move(node));
        }

        // Mangler descriptor: ("drop", from_nodes, to_nodes) for the
        // send-side structured DropMessages, or
        // ("generic", wrap, preds, action, value, restart_parms) for a
        // compiled DSL mangler (see fastengine.py _compile_mangler).
        if (mangler != Py_None) {
            PyRef kind_obj(PySequence_GetItem(mangler, 0));
            if (!kind_obj) throw EngineError("bad mangler descriptor");
            const char *kind_s = PyUnicode_AsUTF8(kind_obj.p);
            if (!kind_s) throw EngineError("bad mangler kind");
            string kind(kind_s);
            if (kind == "drop") {
                engine->drop_mangler = true;
                PyRef froms(PySequence_GetItem(mangler, 1));
                PyRef tos(PySequence_GetItem(mangler, 2));
                if (!froms || !tos) throw EngineError("bad mangler descriptor");
                Py_ssize_t nf = PySequence_Size(froms.p);
                Py_ssize_t nt = PySequence_Size(tos.p);
                auto checked = [n_nodes](i64 id) {
                    if (id < 0 || id >= n_nodes)
                        throw EngineError("mangler node id out of range");
                    return id;
                };
                if (nf == 0) engine->drop_from_any = true;
                for (Py_ssize_t i = 0; i < nf; i++)
                    engine->drop_from.set(checked(get_i64(froms.p, i)));
                if (nt == 0) engine->drop_to_any = true;
                for (Py_ssize_t i = 0; i < nt; i++)
                    engine->drop_to.set(checked(get_i64(tos.p, i)));
            } else if (kind == "generic") {
                auto mg = std::make_unique<ManglerG>();
                PyRef wrap_obj(PySequence_GetItem(mangler, 1));
                const char *wrap_s =
                    wrap_obj ? PyUnicode_AsUTF8(wrap_obj.p) : nullptr;
                if (!wrap_s) throw EngineError("bad mangler wrap");
                string wrap(wrap_s);
                if (wrap == "for") mg->wrap = ManglerG::WFor;
                else if (wrap == "until") mg->wrap = ManglerG::WUntil;
                else if (wrap == "after") mg->wrap = ManglerG::WAfter;
                else throw EngineError("unknown mangler wrap");

                PyRef preds(PySequence_GetItem(mangler, 2));
                if (!preds) throw EngineError("bad mangler predicates");
                Py_ssize_t np = PySequence_Size(preds.p);
                for (Py_ssize_t i = 0; i < np; i++) {
                    PyRef pd(PySequence_GetItem(preds.p, i));
                    if (!pd) throw EngineError("bad mangler predicate");
                    PyRef pk_obj(PySequence_GetItem(pd.p, 0));
                    const char *pk_s =
                        pk_obj ? PyUnicode_AsUTF8(pk_obj.p) : nullptr;
                    if (!pk_s) throw EngineError("bad predicate kind");
                    string pk(pk_s);
                    MPredD p{};
                    if (pk == "msgs") p.k = MPredD::Msgs;
                    else if (pk == "node_startup") p.k = MPredD::NodeStartup;
                    else if (pk == "client_proposal")
                        p.k = MPredD::ClientProposalEv;
                    else if (pk == "from_self") p.k = MPredD::FromSelf;
                    else if (pk == "from_nodes" || pk == "to_nodes") {
                        p.k = pk == "from_nodes" ? MPredD::FromNodes
                                                 : MPredD::ToNodes;
                        PyRef ids(PySequence_GetItem(pd.p, 1));
                        if (!ids) throw EngineError("bad node id list");
                        Py_ssize_t ni = PySequence_Size(ids.p);
                        for (Py_ssize_t j = 0; j < ni; j++)
                            p.ids.push_back(get_i64(ids.p, j));
                    } else if (pk == "at_percent" || pk == "with_sequence" ||
                               pk == "with_epoch" || pk == "from_client") {
                        if (pk == "at_percent") p.k = MPredD::AtPercent;
                        else if (pk == "with_sequence")
                            p.k = MPredD::WithSequence;
                        else if (pk == "with_epoch") p.k = MPredD::WithEpoch;
                        else p.k = MPredD::FromClient;
                        p.value = get_i64(pd.p, 1);
                    } else if (pk == "of_type") {
                        p.k = MPredD::OfType;
                        PyRef codes(PySequence_GetItem(pd.p, 1));
                        if (!codes) throw EngineError("bad type code list");
                        Py_ssize_t nc2 = PySequence_Size(codes.p);
                        for (Py_ssize_t j = 0; j < nc2; j++) {
                            i64 code = get_i64(codes.p, j);
                            if (code < 0 || code > 15)
                                throw EngineError("bad msg type code");
                            p.type_mask |= 1u << (u32)code;
                        }
                    } else {
                        throw EngineError("unknown mangler predicate kind");
                    }
                    mg->preds.push_back(std::move(p));
                }

                PyRef act_obj(PySequence_GetItem(mangler, 3));
                const char *act_s =
                    act_obj ? PyUnicode_AsUTF8(act_obj.p) : nullptr;
                if (!act_s) throw EngineError("bad mangler action");
                string act(act_s);
                if (act == "drop") mg->action = ManglerG::Drop;
                else if (act == "jitter") mg->action = ManglerG::Jitter;
                else if (act == "duplicate") mg->action = ManglerG::Duplicate;
                else if (act == "delay") mg->action = ManglerG::Delay;
                else if (act == "crash_and_restart_after")
                    mg->action = ManglerG::CrashRestart;
                else throw EngineError("unknown mangler action");
                mg->value = get_i64(mangler, 4);
                if ((mg->action == ManglerG::Jitter ||
                     mg->action == ManglerG::Duplicate) &&
                    mg->value <= 0)
                    throw EngineError("jitter/duplicate needs max_delay > 0");
                if (mg->action == ManglerG::CrashRestart) {
                    PyRef rp(PySequence_GetItem(mangler, 5));
                    if (!rp || rp.p == Py_None)
                        throw EngineError("crash restart needs init parms");
                    mg->restart_parms.id = (i32)get_i64(rp.p, 0);
                    mg->restart_parms.batch_size = get_i64(rp.p, 1);
                    mg->restart_parms.heartbeat_ticks = get_i64(rp.p, 2);
                    mg->restart_parms.suspect_ticks = get_i64(rp.p, 3);
                    mg->restart_parms.new_epoch_timeout_ticks =
                        get_i64(rp.p, 4);
                    mg->restart_parms.buffer_size = get_i64(rp.p, 5);
                    if (mg->restart_parms.id < 0 ||
                        mg->restart_parms.id >= (i32)n_nodes)
                        throw EngineError("restart target out of range");
                }
                mg->rng.seed_from_u64((u64)random_seed);
                engine->queue.mangler = std::move(mg);
            } else {
                throw EngineError("unknown mangler descriptor kind");
            }
        }

        // Ack ledger: requires send order == arrival order, i.e. uniform
        // link latency across nodes.  Late-started nodes miss canonical
        // stream prefixes, so they consume classically — and a drop
        // mangler breaks every-receiver-sees-every-wave, so it disables
        // the ledger outright (classic paths handle drops exactly).
        {
            // A consume-time mangler breaks send-order == arrival-order
            // (jitter/duplicates) and every-receiver-sees-every-wave
            // (drops), so any generic mangler disables the ledger outright.
            bool uniform = !engine->drop_mangler && !engine->queue.mangler;
            i64 base_lat = engine->nodes[0]->runtime.link_latency;
            for (const auto &node : engine->nodes) {
                if (node->runtime.link_latency != base_lat) uniform = false;
                for (size_t d = 0; d < node->runtime.lat_to.size(); d++)
                    if ((i64)d != (i64)node->id &&
                        node->runtime.lat_to[d] != base_lat)
                        uniform = false;
            }
            const char *env = std::getenv("MIRBFT_FAST_LEDGER");
            bool enabled =
                uniform && !(env && env[0] == '0') && !(flags & 1);
            if (enabled) {
                engine->ack_ledger.wq = engine->ctx.wq;
                engine->ack_ledger.sq = engine->ctx.iq;
                engine->ctx.ack_ledger = &engine->ack_ledger;
                for (auto &node : engine->nodes)
                    if (node->start_delay > 0)
                        node->init_parms.led_classic = true;
            }
        }

        // Reconfiguration points: (client_id, req_no, desc) where desc is
        // ("new_client", id, width) | ("remove_client", id) |
        // ("new_config", (nodes...), ci, mel, nb, f).  Envelope: a new
        // config must keep the node set, f, and checkpoint interval.
        if (reconfig_points != Py_None) {
            Py_ssize_t nr = PySequence_Size(reconfig_points);
            if (nr < 0) throw EngineError("bad reconfig points");
            for (Py_ssize_t i = 0; i < nr; i++) {
                PyRef pt(PySequence_GetItem(reconfig_points, i));
                if (!pt) throw EngineError("bad reconfig point");
                i64 client_id = get_i64(pt.p, 0);
                i64 req_no = get_i64(pt.p, 1);
                PyRef desc(PySequence_GetItem(pt.p, 2));
                if (!desc) throw EngineError("bad reconfig descriptor");
                PyRef kind_obj(PySequence_GetItem(desc.p, 0));
                const char *kind_s =
                    kind_obj ? PyUnicode_AsUTF8(kind_obj.p) : nullptr;
                if (!kind_s) throw EngineError("bad reconfig kind");
                string rk(kind_s);
                ReconfigS r{};
                if (rk == "new_client") {
                    r.t = ReconfigS::NewClient;
                    r.id = get_i64(desc.p, 1);
                    r.width = get_i64(desc.p, 2);
                } else if (rk == "remove_client") {
                    r.t = ReconfigS::RemoveClient;
                    r.id = get_i64(desc.p, 1);
                } else if (rk == "new_config") {
                    r.t = ReconfigS::NewConfig;
                    auto cfg = std::make_shared<NetConfigS>();
                    PyRef nodes_obj(PySequence_GetItem(desc.p, 1));
                    if (!nodes_obj) throw EngineError("bad new-config nodes");
                    Py_ssize_t nn = PySequence_Size(nodes_obj.p);
                    for (Py_ssize_t j = 0; j < nn; j++)
                        cfg->nodes.push_back((i32)get_i64(nodes_obj.p, j));
                    cfg->ci = get_i64(desc.p, 2);
                    cfg->mel = get_i64(desc.p, 3);
                    cfg->nb = get_i64(desc.p, 4);
                    cfg->f = get_i64(desc.p, 5);
                    if (cfg->nodes != engine->ctx.cfg.nodes ||
                        cfg->f != engine->ctx.cfg.f ||
                        cfg->ci != engine->ctx.cfg.ci)
                        throw EngineError(
                            "reconfig changing nodes/f/ci outside envelope");
                    r.config = std::move(cfg);
                } else {
                    throw EngineError("unknown reconfiguration kind");
                }
                engine->reconfig_points.emplace_back(client_id, req_no,
                                                     std::move(r));
            }
        }

        // Seed node worlds + initialize events (Recorder.recording()).
        for (i64 i = 0; i < n_nodes; i++) {
            engine->init_node_world((i32)i, engine->ctx.init_clients);
            SimEv ev;
            ev.time = engine->queue.fake_time +
                      engine->nodes[(size_t)i]->start_delay;
            ev.kind = SK::Initialize;
            ev.target = (i32)i;
            engine->queue.insert(std::move(ev));
        }

        // Drain-predicate bookkeeping (recorder.py:761-803 invariants).
        for (const auto &c : engine->client_specs) {
            i64 target = c.corrupt ? 0 : c.total;
            engine->drain_targets.emplace(c.id, target);
            if (target > 0) {
                engine->client_satisfied.emplace(c.id, false);
                engine->clients_unsatisfied += 1;
            }
        }
        for (auto &node : engine->nodes) {
            node->drain_ready = engine->node_lws_ready(*node);
            if (!node->drain_ready) engine->nodes_not_ready += 1;
        }
    } catch (const std::exception &e) {
        delete engine;
        if (!PyErr_Occurred()) PyErr_SetString(PyExc_RuntimeError, e.what());
        return nullptr;
    }
    PyEngine *self = (PyEngine *)type->tp_alloc(type, 0);
    if (!self) {
        delete engine;
        return nullptr;
    }
    self->engine = engine;
    return (PyObject *)self;
}

// run(max_steps, timeout) -> (executed_steps, done, timed_out)
PyObject *engine_run(PyObject *self, PyObject *args) {
    long long max_steps, timeout;
    if (!PyArg_ParseTuple(args, "LL", &max_steps, &timeout)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    bool done = false, timed_out = false, need_device = false;
    i64 executed = 0;
    string error;
    {
        PyThreadState *save = PyEval_SaveThread();
        try {
            executed = e->run(max_steps, timeout, &done, &timed_out,
                              &need_device);
        } catch (const std::exception &ex) {
            error = ex.what();
            if (error.empty()) error = "fastengine error";
        }
        PyEval_RestoreThread(save);
    }
    if (!error.empty()) {
        PyErr_SetString(PyExc_RuntimeError, error.c_str());
        return nullptr;
    }
    return Py_BuildValue("Liii", (long long)executed, done ? 1 : 0,
                         timed_out ? 1 : 0, need_device ? 1 : 0);
}

PyObject *engine_stats(PyObject *self, PyObject *) {
    Engine *e = ((PyEngine *)self)->engine;
    return Py_BuildValue("LLLd", (long long)e->steps,
                         (long long)e->queue.fake_time,
                         (long long)e->committed_ops,
                         (double)e->crypto_ns / 1e9);
}

// drain_state() -> (nodes_not_ready, clients_unsatisfied): the two halves
// of the drain predicate, for condition-bounded runs (bench config 5).
PyObject *engine_drain_state(PyObject *self, PyObject *) {
    Engine *e = ((PyEngine *)self)->engine;
    return Py_BuildValue("LL", (long long)e->nodes_not_ready,
                         (long long)e->clients_unsatisfied);
}

// node_summary(i) -> (checkpoint_seq_no, checkpoint_hash, epoch,
//                     last_seq_no, active_hash, committed_reqs dict,
//                     {client_id: low_watermark})
PyObject *engine_node_summary(PyObject *self, PyObject *args) {
    int i;
    if (!PyArg_ParseTuple(args, "i", &i)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    if (i < 0 || (size_t)i >= e->nodes.size()) {
        PyErr_SetString(PyExc_IndexError, "node index out of range");
        return nullptr;
    }
    EngineNode &node = *e->nodes[(size_t)i];
    i64 epoch = -1;
    if (node.machine && node.machine->epoch_tracker &&
        node.machine->epoch_tracker->current_epoch)
        epoch = node.machine->epoch_tracker->current_epoch->number;
    PyObject *committed = PyDict_New();
    if (!committed) return nullptr;
    for (const auto &pr : node.state.committed_reqs) {
        PyObject *v = PyLong_FromLongLong(pr.second);
        PyObject *k = PyLong_FromLongLong(pr.first);
        if (!v || !k || PyDict_SetItem(committed, k, v) < 0) {
            Py_XDECREF(v);
            Py_XDECREF(k);
            Py_DECREF(committed);
            return nullptr;
        }
        Py_DECREF(v);
        Py_DECREF(k);
    }
    PyObject *lws = PyDict_New();
    if (!lws) {
        Py_DECREF(committed);
        return nullptr;
    }
    if (node.state.checkpoint_state) {
        for (const auto &cs : node.state.checkpoint_state->clients) {
            PyObject *v = PyLong_FromLongLong(cs.lw);
            PyObject *k = PyLong_FromLongLong(cs.id);
            if (!v || !k || PyDict_SetItem(lws, k, v) < 0) {
                Py_XDECREF(v);
                Py_XDECREF(k);
                Py_DECREF(committed);
                Py_DECREF(lws);
                return nullptr;
            }
            Py_DECREF(v);
            Py_DECREF(k);
        }
    }
    const string &active = node.state.active_hash_digest();
    return Py_BuildValue(
        "Ly#LLy#NN", (long long)node.state.checkpoint_seq_no,
        node.state.checkpoint_hash.data(),
        (Py_ssize_t)node.state.checkpoint_hash.size(), (long long)epoch,
        (long long)node.state.last_seq_no, active.data(),
        (Py_ssize_t)active.size(), committed, lws);
}

// node_ack_state(i) -> int: FNV-1a fingerprint of the node's per-client
// ack-dissemination state (watermarks, vote masks, quorum sets, ledger
// cursor).  Deterministic across runs with identical event streams — the
// PDES ledger-parity test compares it against the sequential engine's.
PyObject *engine_node_ack_state(PyObject *self, PyObject *args) {
    int i;
    if (!PyArg_ParseTuple(args, "i", &i)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    if (i < 0 || (size_t)i >= e->nodes.size()) {
        PyErr_SetString(PyExc_IndexError, "node index out of range");
        return nullptr;
    }
    EngineNode &node = *e->nodes[(size_t)i];
    u64 h = 1469598103934665603ull;
    auto mix = [&h](u64 v) {
        h ^= v;
        h *= 1099511628211ull;
    };
    if (node.machine && node.machine->client_hash_disseminator &&
        node.machine->client_hash_disseminator->initialized) {
        Disseminator &d = *node.machine->client_hash_disseminator;
        mix((u64)d.led_view.version);
        vector<u32> early = d.led_view.own_early;
        std::sort(early.begin(), early.end());
        for (u32 p : early) mix((u64)p);
        mix((u64)d.led_diverged_total);
        mix((u64)d.led_classic_count);
        for (const auto &pr : d.clients) {
            const ClientD &c = *pr.second;
            mix((u64)pr.first);
            mix((u64)c.client_state.lw);
            mix((u64)c.high_watermark);
            mix(c.led_classic ? 1u : 0u);
            mix((u64)c.led_diverged);
            for (const auto &crnp : c.win) {
                const ClientReqNoD &crn = *crnp;
                mix((u64)crn.req_no);
                for (int wi = 0; wi < 4; wi++)
                    mix(crn.non_null_voters.w[wi]);
                for (i32 dg : crn.self_acked) mix((u64)(u32)dg);
                for (const auto &rp : crn.requests.items) {
                    mix((u64)(u32)rp.first);
                    for (int wi = 0; wi < 4; wi++)
                        mix(rp.second->agreements.w[wi]);
                    mix(rp.second->stored ? 1u : 0u);
                }
                for (const auto &rp : crn.weak_requests.items)
                    mix((u64)(u32)rp.first);
                for (const auto &rp : crn.strong_requests.items)
                    mix((u64)(u32)rp.first);
            }
        }
    }
    return PyLong_FromUnsignedLongLong((unsigned long long)h);
}

// set_fail_transfers(node, count): the node's next `count` state-transfer
// attempts fail at the app boundary (testengine NodeState.fail_transfers).
PyObject *engine_set_fail_transfers(PyObject *self, PyObject *args) {
    int i;
    long long count;
    if (!PyArg_ParseTuple(args, "iL", &i, &count)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    if (i < 0 || (size_t)i >= e->nodes.size()) {
        PyErr_SetString(PyExc_IndexError, "node index out of range");
        return nullptr;
    }
    e->nodes[(size_t)i]->state.fail_transfers = count;
    Py_RETURN_NONE;
}

// node_transfers(i) -> (state_transfers, transfer_failures, attempt_times)
PyObject *engine_node_transfers(PyObject *self, PyObject *args) {
    int i;
    if (!PyArg_ParseTuple(args, "i", &i)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    if (i < 0 || (size_t)i >= e->nodes.size()) {
        PyErr_SetString(PyExc_IndexError, "node index out of range");
        return nullptr;
    }
    const AppState &st = e->nodes[(size_t)i]->state;
    auto build = [](const vector<i64> &v) -> PyObject * {
        PyObject *t = PyTuple_New((Py_ssize_t)v.size());
        if (!t) return nullptr;
        for (size_t j = 0; j < v.size(); j++) {
            PyObject *n = PyLong_FromLongLong(v[j]);
            if (!n) {
                Py_DECREF(t);
                return nullptr;
            }
            PyTuple_SET_ITEM(t, (Py_ssize_t)j, n);
        }
        return t;
    };
    PyObject *a = build(st.state_transfers);
    PyObject *b = a ? build(st.transfer_failures) : nullptr;
    PyObject *c = b ? build(st.transfer_attempt_times) : nullptr;
    if (!c) {
        Py_XDECREF(a);
        Py_XDECREF(b);
        return nullptr;
    }
    return Py_BuildValue("NNN", a, b, c);
}

// pop_hash_log() -> list[(message_bytes, digest_bytes)]
PyObject *engine_pop_hash_log(PyObject *self, PyObject *) {
    Engine *e = ((PyEngine *)self)->engine;
    PyObject *out = PyList_New(0);
    if (!out) return nullptr;
    for (const auto &pr : e->wave_log) {
        const string &m = e->ctx.intern.get(pr.first);
        const string &d = e->ctx.intern.get(pr.second);
        PyObject *item =
            Py_BuildValue("y#y#", m.data(), (Py_ssize_t)m.size(), d.data(),
                          (Py_ssize_t)d.size());
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(item);
    }
    e->wave_log.clear();
    return out;
}

// Steals v; on failure (or null v) releases BOTH v and the dict so error
// paths in the profile builders cannot leak the partially built dict.
int PyDictSetItemStringSteal(PyObject *d, const char *k, PyObject *v) {
    if (!v) {
        Py_DECREF(d);
        return -1;
    }
    int r = PyDict_SetItemString(d, k, v);
    Py_DECREF(v);
    if (r < 0) Py_DECREF(d);
    return r;
}

PyObject *engine_profile(PyObject *self, PyObject *) {
    Engine *e = ((PyEngine *)self)->engine;
    static const char *names[11] = {
        "initialize", "msg_received", "client_proposal", "tick",
        "proc_wal", "proc_net", "proc_hash", "proc_client", "proc_app",
        "proc_req_store", "proc_result"};
    PyObject *out = PyDict_New();
    if (!out) return nullptr;
    for (int i = 0; i < 11; i++) {
        PyObject *v = Py_BuildValue("KK", (unsigned long long)e->kind_cycles[i],
                                    (unsigned long long)e->kind_counts[i]);
        if (PyDictSetItemStringSteal(out, names[i], v) < 0) return nullptr;
    }
    static const char *part_names[6] = {"p_ackbatch", "p_votes", "p_fixpoint",
                                        "p_coalesce", "p_ackrun", "p_other"};
    for (int i = 0; i < 6; i++) {
        PyObject *v = Py_BuildValue(
            "KK", (unsigned long long)g_parts[i].load(std::memory_order_relaxed),
            (unsigned long long)0);
        if (PyDictSetItemStringSteal(out, part_names[i], v) < 0)
            return nullptr;
    }
    static const char *ev_names[12] = {
        "ev_init", "ev_load", "ev_load_done", "ev_hash_result",
        "ev_checkpoint_result", "ev_request_persisted", "ev_step",
        "ev_tick", "ev_actions_received", "ev_transfer_complete",
        "ev_transfer_failed", "ev_pad"};
    for (int i = 0; i < 12; i++) {
        PyObject *v = Py_BuildValue("KK", (unsigned long long)e->ev_cycles[i],
                                    (unsigned long long)e->ev_counts[i]);
        if (PyDictSetItemStringSteal(out, ev_names[i], v) < 0) return nullptr;
    }
    static const char *mt_names[16] = {
        "mt_preprepare", "mt_prepare", "mt_commit", "mt_checkpoint",
        "mt_suspect", "mt_epoch_change", "mt_epoch_change_ack",
        "mt_new_epoch", "mt_new_epoch_echo", "mt_new_epoch_ready",
        "mt_fetch_batch", "mt_forward_batch", "mt_fetch_request",
        "mt_ack", "mt_ack_batch", "mt_msg_batch"};
    for (int i = 0; i < 16; i++) {
        PyObject *v = Py_BuildValue("KK", (unsigned long long)e->msg_cycles[i],
                                    (unsigned long long)e->msg_counts[i]);
        if (PyDictSetItemStringSteal(out, mt_names[i], v) < 0) return nullptr;
    }
    return out;
}

// pending_device_work() -> (list[bytes] hash_content,
//                            list[(client_id, need_verdicts_up_to)])
PyObject *engine_pending_device_work(PyObject *self, PyObject *) {
    Engine *e = ((PyEngine *)self)->engine;
    PyObject *contents = PyList_New(0);
    if (!contents) return nullptr;
    // Head needs first (these gate the pause), then every other
    // unsupplied content visible in the queue (served in the same
    // dispatch so later pauses usually find digests present).  Skip the
    // deep scan on verdict-only pauses — nothing hash-related changed.
    deque<string> deep;
    if (e->device_hash_mode && !e->need_hash_content.empty())
        e->collect_pending_hash_deep(deep);
    vector<const string *> all;
    for (const auto &c : e->need_hash_content) all.push_back(&c);
    for (const auto &c : deep) all.push_back(&c);
    for (const string *cp : all) {
        const string &c = *cp;
        PyObject *b = PyBytes_FromStringAndSize(c.data(), (Py_ssize_t)c.size());
        if (!b || PyList_Append(contents, b) < 0) {
            Py_XDECREF(b);
            Py_DECREF(contents);
            return nullptr;
        }
        Py_DECREF(b);
    }
    PyObject *verdicts = PyList_New(0);
    if (!verdicts) {
        Py_DECREF(contents);
        return nullptr;
    }
    for (const auto &pr : e->need_verdicts) {
        PyObject *t = Py_BuildValue("LL", (long long)pr.first,
                                    (long long)pr.second);
        if (!t || PyList_Append(verdicts, t) < 0) {
            Py_XDECREF(t);
            Py_DECREF(contents);
            Py_DECREF(verdicts);
            return nullptr;
        }
        Py_DECREF(t);
    }
    return Py_BuildValue("NN", contents, verdicts);
}

// supply_digests([(content_bytes, digest_bytes), ...])
PyObject *engine_supply_digests(PyObject *self, PyObject *args) {
    PyObject *items;
    if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    Py_ssize_t n = PySequence_Size(items);
    if (n < 0) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyRef it(PySequence_GetItem(items, i));
        if (!it) return nullptr;
        const char *content, *digest;
        Py_ssize_t clen, dlen;
        if (!PyArg_ParseTuple(it.p, "y#y#", &content, &clen, &digest, &dlen))
            return nullptr;
        e->device_digests[string(content, (size_t)clen)] =
            e->ctx.intern.put(string(digest, (size_t)dlen));
    }
    Py_RETURN_NONE;
}

// supply_verdicts(client_id, verdict_bytes) — appends to the client's
// verdict array (streaming-auth mode).
PyObject *engine_supply_verdicts(PyObject *self, PyObject *args) {
    long long client_id;
    const char *buf;
    Py_ssize_t blen;
    if (!PyArg_ParseTuple(args, "Ly#", &client_id, &buf, &blen))
        return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    ClientSpec *cs = e->spec_of(client_id);
    if (!cs) {
        PyErr_SetString(PyExc_KeyError, "unknown client");
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < blen; i++) cs->verdicts.push_back((u8)buf[i]);
    Py_RETURN_NONE;
}

// set_device_modes(device_hash, streaming_auth)
PyObject *engine_set_device_modes(PyObject *self, PyObject *args) {
    int dh, sa;
    if (!PyArg_ParseTuple(args, "ii", &dh, &sa)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    e->device_hash_mode = dh != 0;
    e->streaming_auth_mode = sa != 0;
    Py_RETURN_NONE;
}

// run_pdes(partitions, threaded, timeout, stop_time, stop_steps) -> dict.
// Measurement mode (stop_steps < 0) runs to the drain flip and returns the
// exact step count / fake-time; exact mode replays to the given stop and
// leaves the engine state bit-identical to the sequential run there.
PyObject *engine_run_pdes(PyObject *self, PyObject *args) {
    long long partitions, threaded, timeout, stop_time, stop_steps;
    if (!PyArg_ParseTuple(args, "LLLLL", &partitions, &threaded, &timeout,
                          &stop_time, &stop_steps))
        return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    PdesResult r;
    string error;
    {
        PyThreadState *save = PyEval_SaveThread();
        try {
            r = e->run_pdes(partitions, threaded != 0, timeout, stop_time,
                            stop_steps);
        } catch (const std::exception &ex) {
            error = ex.what();
            if (error.empty()) error = "fastengine error";
        }
        PyEval_RestoreThread(save);
    }
    if (!error.empty()) {
        PyErr_SetString(PyExc_RuntimeError, error.c_str());
        return nullptr;
    }
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L,s:i,s:i,s:L,s:K,s:K,s:K,s:K,s:L,s:L,s:L,s:i}",
        "steps", (long long)r.steps, "fake_time", (long long)r.fake_time,
        "flip_step", (long long)r.flip_step, "flip_time",
        (long long)r.flip_time, "done", r.done ? 1 : 0, "timed_out",
        r.timed_out ? 1 : 0, "windows", (long long)r.windows,
        "barrier_cycles", (unsigned long long)r.barrier_cycles,
        "barrier_ns", (unsigned long long)r.barrier_ns, "sum_part_cycles",
        (unsigned long long)r.sum_part_cycles, "max_part_cycles",
        (unsigned long long)r.max_part_cycles, "tail_steps",
        (long long)r.tail_steps, "repartitions", (long long)r.repartitions,
        "lookahead", (long long)r.lookahead, "ledger_on",
        r.ledger_on ? 1 : 0);
}

// pdes_check(partitions) -> None (eligible) or the structured
// "pdes_envelope[<code>]: <detail>" reason string.  Probe only: no state
// is touched, so bench.py can classify configs without running them.
PyObject *engine_pdes_check(PyObject *self, PyObject *args) {
    long long partitions;
    if (!PyArg_ParseTuple(args, "L", &partitions)) return nullptr;
    Engine *e = ((PyEngine *)self)->engine;
    string reason;
    try {
        reason = e->pdes_check(partitions);
    } catch (const std::exception &ex) {
        PyErr_SetString(PyExc_RuntimeError, ex.what());
        return nullptr;
    }
    if (reason.empty()) Py_RETURN_NONE;
    return PyUnicode_FromStringAndSize(reason.data(),
                                       (Py_ssize_t)reason.size());
}

PyMethodDef engine_methods[] = {
    {"run", engine_run, METH_VARARGS, nullptr},
    {"run_pdes", engine_run_pdes, METH_VARARGS, nullptr},
    {"pdes_check", engine_pdes_check, METH_VARARGS, nullptr},
    {"pending_device_work", engine_pending_device_work, METH_NOARGS, nullptr},
    {"supply_digests", engine_supply_digests, METH_VARARGS, nullptr},
    {"supply_verdicts", engine_supply_verdicts, METH_VARARGS, nullptr},
    {"set_device_modes", engine_set_device_modes, METH_VARARGS, nullptr},
    {"stats", engine_stats, METH_NOARGS, nullptr},
    {"drain_state", engine_drain_state, METH_NOARGS, nullptr},
    {"node_summary", engine_node_summary, METH_VARARGS, nullptr},
    {"node_ack_state", engine_node_ack_state, METH_VARARGS, nullptr},
    {"set_fail_transfers", engine_set_fail_transfers, METH_VARARGS, nullptr},
    {"node_transfers", engine_node_transfers, METH_VARARGS, nullptr},
    {"pop_hash_log", engine_pop_hash_log, METH_NOARGS, nullptr},
    {"profile", engine_profile, METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// profile_globals() -> dict of the process-wide profiling counters
// (cumulative across engines; callers diff snapshots to attribute a run).
PyObject *mod_profile_globals(PyObject *, PyObject *) {
    static const char *part_names[6] = {"p_ackbatch", "p_votes", "p_fixpoint",
                                        "p_coalesce", "p_ackrun", "p_other"};
    PyObject *out = PyDict_New();
    if (!out) return nullptr;
    for (int i = 0; i < 6; i++) {
        PyObject *v = PyLong_FromUnsignedLongLong(
            g_parts[i].load(std::memory_order_relaxed));
        if (PyDictSetItemStringSteal(out, part_names[i], v) < 0)
            return nullptr;
    }
    return out;
}

PyMethodDef fast_module_methods[] = {
    {"profile_globals", mod_profile_globals, METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef fast_moduledef = {
    PyModuleDef_HEAD_INIT, "_fast",
    "Native fast-path cluster engine (C++ twin of the Python testengine).",
    -1, fast_module_methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__fast(void) {
    EngineType.tp_name = "mirbft_tpu._native._fast.FastEngine";
    EngineType.tp_basicsize = sizeof(PyEngine);
    EngineType.tp_flags = Py_TPFLAGS_DEFAULT;
    EngineType.tp_new = engine_new;
    EngineType.tp_dealloc = engine_dealloc;
    EngineType.tp_methods = engine_methods;
    if (PyType_Ready(&EngineType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&fast_moduledef);
    if (!m) return nullptr;
    Py_INCREF(&EngineType);
    if (PyModule_AddObject(m, "FastEngine", (PyObject *)&EngineType) < 0) {
        Py_DECREF(&EngineType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
