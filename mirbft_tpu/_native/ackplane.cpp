// Native ack-vote plane: the O(N^2)-per-request hot path of the client
// request dissemination protocol (reference pkg/statemachine/
// client_hash_disseminator.go:806-876), reduced to packed bitmask
// accumulation in C.
//
// Design contract with mirbft_tpu/statemachine/disseminator.py:
//
//  * The plane owns vote accumulation ONLY for the green path of a
//    (client, req_no): every observed ack carries the same single non-null
//    digest ("canonical").  Anything else — a null digest, a second distinct
//    digest, a forced ack, a buffered-replay ack — is returned to Python
//    ("pyfall"), which EJECTS the slot (syncs the native mask into the
//    Python ClientRequest objects and marks the slot ejected) and runs the
//    exact reference semantics from then on.
//
//  * Quorum crossings are returned as records and REPLAYED by Python
//    through the same tail logic as the pure-Python path, preserving action
//    order and content exactly.  The crossing condition mirrors
//    Client.ack_into / Client.ack_run: emit when count == weak_q, when
//    count == strong_q, or when source == my_id and count >= weak_q —
//    including for duplicate votes (a duplicate arriving while the count
//    sits at a threshold re-runs the tail in the reference semantics, so it
//    must here too).
//
//  * Digests are interned process-wide: digest bytes <-> int32 id.  Ids
//    never leave the process and never enter hashes or the wire format.
//
// No external dependencies; CPython C API only (the environment provides no
// pybind11 — see repo docs/tpu_plane.md).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Digest interning (module-global).

struct BytesKey {
    std::string data;
    bool operator==(const BytesKey &o) const { return data == o.data; }
};

struct BytesKeyHash {
    size_t operator()(const BytesKey &k) const {
        return std::hash<std::string>()(k.data);
    }
};

struct InternTable {
    std::unordered_map<BytesKey, int32_t, BytesKeyHash> ids;
    std::vector<PyObject *> objects;  // id -> bytes object (owned ref)
    size_t cap = 1u << 20;  // bound on distinct digests held native

    // Returns the digest id, or -1 when the digest cannot be owned
    // natively: the null digest, or the table is at capacity.  -1 routes
    // the ack to the Python path, which works from the original bytes —
    // correctness is unaffected, the native fast path just stops covering
    // new digests (memory stays bounded against digest-flooding peers).
    // -2 signals a Python error.
    int32_t intern(PyObject *bytes_obj) {
        char *buf;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(bytes_obj, &buf, &len) < 0) return -2;
        if (len == 0) return -1;  // null digest sentinel
        BytesKey key{std::string(buf, (size_t)len)};
        auto it = ids.find(key);
        if (it != ids.end()) return it->second;
        if (objects.size() >= cap) return -1;  // full: Python path takes over
        int32_t id = (int32_t)objects.size();
        Py_INCREF(bytes_obj);
        objects.push_back(bytes_obj);
        ids.emplace(std::move(key), id);
        return id;
    }
};

InternTable *g_intern = nullptr;

// ---------------------------------------------------------------------------
// Plane object.

constexpr uint8_t SLOT_EJECTED = 1;

struct ClientWin {
    int64_t low = 0;
    int64_t high = -1;  // inclusive; high < low -> empty
    std::vector<int32_t> canonical;  // digest id, -1 = none yet
    std::vector<uint16_t> count;
    std::vector<uint8_t> flags;
    std::vector<uint64_t> votes;  // width * words

    int64_t width() const { return high - low + 1; }
};

struct Plane {
    PyObject_HEAD
    int n_nodes;
    int my_id;
    int weak_q;
    int strong_q;
    int words;
    std::unordered_map<int64_t, ClientWin> *clients;
};

PyObject *mask_to_bytes(const uint64_t *w, int words) {
    // Little-endian byte string, words*8 long; Python: int.from_bytes(b,'little')
    return PyBytes_FromStringAndSize((const char *)w, (Py_ssize_t)words * 8);
}

int bytes_to_mask(PyObject *b, uint64_t *out, int words) {
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(b, &buf, &len) < 0) return -1;
    std::memset(out, 0, (size_t)words * 8);
    if (len > (Py_ssize_t)words * 8) len = (Py_ssize_t)words * 8;
    std::memcpy(out, buf, (size_t)len);
    return 0;
}

void plane_dealloc(PyObject *self) {
    Plane *p = (Plane *)self;
    delete p->clients;
    Py_TYPE(self)->tp_free(self);
}

PyObject *plane_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    static const char *kwlist[] = {"n_nodes", "my_id", "weak_q", "strong_q",
                                   nullptr};
    int n_nodes, my_id, weak_q, strong_q;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "iiii", (char **)kwlist,
                                     &n_nodes, &my_id, &weak_q, &strong_q))
        return nullptr;
    if (n_nodes <= 0 || n_nodes > 4096) {
        PyErr_SetString(PyExc_ValueError, "n_nodes out of range");
        return nullptr;
    }
    Plane *p = (Plane *)type->tp_alloc(type, 0);
    if (!p) return nullptr;
    p->n_nodes = n_nodes;
    p->my_id = my_id;
    p->weak_q = weak_q;
    p->strong_q = strong_q;
    p->words = (n_nodes + 63) / 64;
    p->clients = new std::unordered_map<int64_t, ClientWin>();
    return (PyObject *)p;
}

// set_client(client_id, low, high): create or rebase a client window.
// Slots in the [low, high] overlap with the previous window are preserved;
// everything else starts empty.
PyObject *plane_set_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, low, high;
    if (!PyArg_ParseTuple(args, "LLL", &client_id, &low, &high)) return nullptr;
    if (high < low || high - low > 1 << 20) {
        PyErr_SetString(PyExc_ValueError, "bad window");
        return nullptr;
    }
    const int words = p->words;
    int64_t w = high - low + 1;
    ClientWin fresh;
    fresh.low = low;
    fresh.high = high;
    fresh.canonical.assign((size_t)w, -1);
    fresh.count.assign((size_t)w, 0);
    fresh.flags.assign((size_t)w, 0);
    fresh.votes.assign((size_t)w * words, 0);

    auto it = p->clients->find(client_id);
    if (it != p->clients->end()) {
        ClientWin &old = it->second;
        int64_t from = low > old.low ? low : old.low;
        int64_t to = high < old.high ? high : old.high;
        for (int64_t rn = from; rn <= to; rn++) {
            size_t oi = (size_t)(rn - old.low), ni = (size_t)(rn - low);
            fresh.canonical[ni] = old.canonical[oi];
            fresh.count[ni] = old.count[oi];
            fresh.flags[ni] = old.flags[oi];
            std::memcpy(&fresh.votes[ni * words], &old.votes[oi * words],
                        (size_t)words * 8);
        }
        it->second = std::move(fresh);
    } else {
        p->clients->emplace(client_id, std::move(fresh));
    }
    Py_RETURN_NONE;
}

PyObject *plane_drop_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id;
    if (!PyArg_ParseTuple(args, "L", &client_id)) return nullptr;
    p->clients->erase(client_id);
    Py_RETURN_NONE;
}

PyObject *plane_clear(PyObject *self, PyObject *) {
    Plane *p = (Plane *)self;
    p->clients->clear();
    Py_RETURN_NONE;
}

// import_slot(client_id, req_no, digest_bytes|None, mask_bytes, count)
// (Re-)take native ownership of a slot with known state; un-ejects.
PyObject *plane_import_slot(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    PyObject *digest_obj, *mask_obj;
    int count;
    if (!PyArg_ParseTuple(args, "LLOOi", &client_id, &req_no, &digest_obj,
                          &mask_obj, &count))
        return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) {
        PyErr_SetString(PyExc_KeyError, "unknown client");
        return nullptr;
    }
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) {
        PyErr_SetString(PyExc_IndexError, "req_no outside window");
        return nullptr;
    }
    int32_t did = -1;
    if (digest_obj != Py_None) {
        did = g_intern->intern(digest_obj);
        if (did == -2) return nullptr;
        if (did == -1) {
            // Null digest (caller bug) or intern table at capacity: the
            // slot cannot be owned natively.
            Py_RETURN_FALSE;
        }
    }
    size_t i = (size_t)(req_no - win.low);
    win.canonical[i] = did;
    win.count[i] = (uint16_t)count;
    win.flags[i] = 0;
    if (bytes_to_mask(mask_obj, &win.votes[i * p->words], p->words) < 0)
        return nullptr;
    Py_RETURN_TRUE;
}

PyObject *plane_mark_ejected(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it != p->clients->end()) {
        ClientWin &win = it->second;
        if (req_no >= win.low && req_no <= win.high)
            win.flags[(size_t)(req_no - win.low)] |= SLOT_EJECTED;
    }
    Py_RETURN_NONE;
}

PyObject *slot_state_tuple(Plane *p, ClientWin &win, size_t i) {
    PyObject *mask = mask_to_bytes(&win.votes[i * p->words], p->words);
    if (!mask) return nullptr;
    PyObject *res = Py_BuildValue("iNi", (int)win.canonical[i], mask,
                                  (int)win.count[i]);
    return res;
}

// peek(client_id, req_no) -> (digest_id, mask_bytes, count) | None
// None when the plane has nothing live for the slot (unknown client,
// out of window, or ejected).
PyObject *plane_peek(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) Py_RETURN_NONE;
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) Py_RETURN_NONE;
    size_t i = (size_t)(req_no - win.low);
    if (win.flags[i] & SLOT_EJECTED) Py_RETURN_NONE;
    if (win.canonical[i] == -1 && win.count[i] == 0) Py_RETURN_NONE;
    return slot_state_tuple(p, win, i);
}

// eject(client_id, req_no) -> slot state (digest_id, mask_bytes, count) or
// None, and marks the slot ejected.  Unlike peek(), an already-ejected
// slot's state is STILL returned: apply_core may mark a slot mid-batch and
// Python must still be able to merge the accumulated votes (the merge is an
// idempotent bitmask OR, so repeated ejects are harmless).
PyObject *plane_eject(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) Py_RETURN_NONE;
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) Py_RETURN_NONE;
    size_t i = (size_t)(req_no - win.low);
    win.flags[i] |= SLOT_EJECTED;
    if (win.canonical[i] == -1 && win.count[i] == 0) Py_RETURN_NONE;
    return slot_state_tuple(p, win, i);
}

// Core per-ack application.  Returns:
//   0 applied, no crossing;  1 python-fallback;  2 past (drop);
//   3 crossing (out_* filled).
//
// A fallback on an existing in-window slot marks it EJECTED immediately, so
// every LATER ack for the same slot — including later acks in the same
// batch — also falls back, preserving the reference's strict per-ack
// ordering (e.g. the first-non-null-binding rule when one batch carries
// conflicting digests from one source).  Python retrieves the accumulated
// votes via eject(), which stays valid after the mark.
inline int apply_core(Plane *p, int64_t client_id, int64_t req_no,
                      int32_t digest_id, int source, ClientWin **out_win,
                      size_t *out_idx) {
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) return 1;  // unknown client -> buffer
    ClientWin &win = it->second;
    if (req_no < win.low) return 2;   // past
    if (req_no > win.high) return 1;  // future -> buffer
    size_t i = (size_t)(req_no - win.low);
    if (win.flags[i] & SLOT_EJECTED) return 1;
    if (digest_id < 0) {
        win.flags[i] |= SLOT_EJECTED;  // null digest -> python semantics
        return 1;
    }
    if (win.canonical[i] == -1)
        win.canonical[i] = digest_id;
    else if (win.canonical[i] != digest_id) {
        win.flags[i] |= SLOT_EJECTED;  // conflicting digest -> python
        return 1;
    }
    uint64_t *w = &win.votes[i * p->words + (source >> 6)];
    uint64_t bit = 1ULL << (source & 63);
    if (!(*w & bit)) {
        *w |= bit;
        win.count[i]++;
    }
    int c = win.count[i];
    if (c == p->weak_q || c == p->strong_q ||
        (source == p->my_id && c >= p->weak_q)) {
        *out_win = &win;
        *out_idx = i;
        return 3;
    }
    return 0;
}

// apply_batch(packed_bytes, source) -> list of records in ack order:
//   (idx,)                                   python-fallback
//   (idx, client_id, req_no, digest_id, count, mask_bytes)   crossing
// Packed record layout (little-endian, 16 bytes):
//   int32 client_id, int32 digest_id (-1 null), int64 req_no.
PyObject *plane_apply_batch(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    Py_buffer packed;
    int source;
    if (!PyArg_ParseTuple(args, "y*i", &packed, &source)) return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyBuffer_Release(&packed);
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    PyObject *out = PyList_New(0);
    if (!out) {
        PyBuffer_Release(&packed);
        return nullptr;
    }
    const char *base = (const char *)packed.buf;
    Py_ssize_t n = packed.len / 16;
    for (Py_ssize_t k = 0; k < n; k++) {
        const char *rec = base + k * 16;
        int32_t client_id, digest_id;
        int64_t req_no;
        std::memcpy(&client_id, rec, 4);
        std::memcpy(&digest_id, rec + 4, 4);
        std::memcpy(&req_no, rec + 8, 8);
        ClientWin *win;
        size_t idx;
        int r = apply_core(p, client_id, req_no, digest_id, source, &win, &idx);
        if (r == 0 || r == 2) continue;
        PyObject *item;
        if (r == 1) {
            item = Py_BuildValue("(n)", (Py_ssize_t)k);
        } else {
            PyObject *mask = mask_to_bytes(&win->votes[idx * p->words], p->words);
            if (!mask) {
                Py_DECREF(out);
                PyBuffer_Release(&packed);
                return nullptr;
            }
            item = Py_BuildValue("nLLiiN", (Py_ssize_t)k, (long long)client_id,
                                 (long long)req_no, (int)digest_id,
                                 (int)win->count[idx], mask);
        }
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            PyBuffer_Release(&packed);
            return nullptr;
        }
        Py_DECREF(item);
    }
    PyBuffer_Release(&packed);
    return out;
}

// apply_one(client_id, req_no, digest_bytes, source) ->
//   0 | 1 | 2 (as apply_core) | (count, digest_id, mask_bytes) on crossing.
PyObject *plane_apply_one(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    PyObject *digest_obj;
    int source;
    if (!PyArg_ParseTuple(args, "LLOi", &client_id, &req_no, &digest_obj,
                          &source))
        return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    int32_t did = g_intern->intern(digest_obj);
    if (did == -2) return nullptr;
    ClientWin *win;
    size_t idx;
    int r = apply_core(p, client_id, req_no, did, source, &win, &idx);
    if (r == 3) {
        PyObject *mask = mask_to_bytes(&win->votes[idx * p->words], p->words);
        if (!mask) return nullptr;
        return Py_BuildValue("iiN", (int)win->count[idx], (int)did, mask);
    }
    return PyLong_FromLong(r);
}

// export_client(client_id) -> list of (req_no, digest_id, mask_bytes, count)
// for live (non-ejected, touched) slots; used at reinitialize.
PyObject *plane_export_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id;
    if (!PyArg_ParseTuple(args, "L", &client_id)) return nullptr;
    PyObject *out = PyList_New(0);
    if (!out) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) return out;
    ClientWin &win = it->second;
    for (int64_t rn = win.low; rn <= win.high; rn++) {
        size_t i = (size_t)(rn - win.low);
        if (win.flags[i] & SLOT_EJECTED) continue;
        if (win.canonical[i] == -1 && win.count[i] == 0) continue;
        PyObject *mask = mask_to_bytes(&win.votes[i * p->words], p->words);
        if (!mask) {
            Py_DECREF(out);
            return nullptr;
        }
        PyObject *item =
            Py_BuildValue("LiNi", (long long)rn, (int)win.canonical[i], mask,
                          (int)win.count[i]);
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(item);
    }
    return out;
}

PyMethodDef plane_methods[] = {
    {"set_client", plane_set_client, METH_VARARGS, nullptr},
    {"drop_client", plane_drop_client, METH_VARARGS, nullptr},
    {"clear", plane_clear, METH_NOARGS, nullptr},
    {"import_slot", plane_import_slot, METH_VARARGS, nullptr},
    {"mark_ejected", plane_mark_ejected, METH_VARARGS, nullptr},
    {"peek", plane_peek, METH_VARARGS, nullptr},
    {"eject", plane_eject, METH_VARARGS, nullptr},
    {"apply_batch", plane_apply_batch, METH_VARARGS, nullptr},
    {"apply_one", plane_apply_one, METH_VARARGS, nullptr},
    {"export_client", plane_export_client, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject PlaneType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------------------------------------------------------------------------
// Sequence-vote plane: the O(N^2)-per-sequence Prepare/Commit hot path of
// the three-phase commit (reference pkg/statemachine/sequence.go:257-355,
// epoch_active.go:142-213).  Vote accumulation (replica bitmasks + per-digest
// counts) runs here; Python keeps the sequence lifecycle and reads counts
// lazily at its quorum checks, so the records this plane returns are HINTS —
// liberal is fine, Python re-validates every transition condition.
//
// Contract with mirbft_tpu/statemachine/sequence.py + machine.py:
//
//  * The plane mirrors the active epoch's watermark window exactly
//    (set_window after every extension/trim); Python phase changes are
//    pushed via set_phase, the batch digest via set_expected.
//  * apply_votes() applies one packed envelope of votes from one source,
//    mirroring the _step_prepare/_step_commit filters (owner-INVALID,
//    planned-expiration-INVALID, past-drop); FUTURE and wrong-epoch votes
//    come back as fallback records and Python routes the original message
//    objects through the slow path (buffering, epoch tracker).
//  * Per-slot digest tables are bounded (VOTE_DIGEST_CAP).  Votes for a
//    digest that does not fit are still mask-deduplicated but not counted —
//    harmless for every observable: quorum checks only ever read the
//    expected digest's count, and set_expected's entry always fits (the
//    cap applies to vote-created entries only).

constexpr int VOTE_DIGEST_CAP = 64;
constexpr uint8_t PH_PENDING_REQUESTS = 2;
constexpr uint8_t PH_READY = 3;
constexpr uint8_t PH_PREPREPARED = 4;
constexpr uint8_t PH_PREPARED = 5;

struct DigestCount {
    std::string digest;
    int32_t prep = 0;
    int32_t commit = 0;
};

struct SeqSlot {
    uint8_t phase = 0;          // SeqState numeric value
    bool expected_set = false;  // set_expected called
    std::string expected;       // batch digest ("" = null batch until set)
    bool my_prep_set = false;
    std::string my_prep;        // digest our own prepare carried
    std::vector<uint64_t> prep_mask, commit_mask;  // words each
    std::vector<DigestCount> counts;

    DigestCount *find_count(const char *d, size_t dlen, bool create,
                            bool force) {
        for (auto &c : counts)
            if (c.digest.size() == dlen &&
                std::memcmp(c.digest.data(), d, dlen) == 0)
                return &c;
        if (!create) return nullptr;
        if (!force && counts.size() >= VOTE_DIGEST_CAP) return nullptr;
        counts.push_back(DigestCount{std::string(d, dlen), 0, 0});
        return &counts.back();
    }
};

struct SeqPlaneObj {
    PyObject_HEAD
    int n_nodes, my_id, iq, words, nb;
    int64_t epoch, planned_expiration;
    int64_t low, high;  // inclusive window; high < low -> empty
    std::vector<int32_t> *buckets;
    std::vector<SeqSlot> *slots;  // index: seq_no - low
};

void seqplane_dealloc(PyObject *self) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    delete p->buckets;
    delete p->slots;
    Py_TYPE(self)->tp_free(self);
}

PyObject *seqplane_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    static const char *kwlist[] = {"n_nodes", "my_id", "iq", nullptr};
    int n_nodes, my_id, iq;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "iii", (char **)kwlist,
                                     &n_nodes, &my_id, &iq))
        return nullptr;
    if (n_nodes <= 0 || n_nodes > 4096) {
        PyErr_SetString(PyExc_ValueError, "n_nodes out of range");
        return nullptr;
    }
    SeqPlaneObj *p = (SeqPlaneObj *)type->tp_alloc(type, 0);
    if (!p) return nullptr;
    p->n_nodes = n_nodes;
    p->my_id = my_id;
    p->iq = iq;
    p->words = (n_nodes + 63) / 64;
    p->nb = 0;
    p->epoch = -1;
    p->planned_expiration = -1;
    p->low = 0;
    p->high = -1;
    p->buckets = new std::vector<int32_t>();
    p->slots = new std::vector<SeqSlot>();
    return (PyObject *)p;
}

// reset(epoch, planned_expiration, buckets_bytes): start an (empty) window
// for a new active epoch.  buckets_bytes: little-endian int32 per bucket
// (bucket index -> owning node id).
PyObject *seqplane_reset(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long epoch, planned_expiration;
    Py_buffer buckets;
    if (!PyArg_ParseTuple(args, "LLy*", &epoch, &planned_expiration, &buckets))
        return nullptr;
    p->epoch = epoch;
    p->planned_expiration = planned_expiration;
    p->nb = (int)(buckets.len / 4);
    p->buckets->assign((size_t)p->nb, 0);
    std::memcpy(p->buckets->data(), buckets.buf, (size_t)p->nb * 4);
    PyBuffer_Release(&buckets);
    p->low = 0;
    p->high = -1;
    p->slots->clear();
    Py_RETURN_NONE;
}

// set_window(low, high): rebase to [low, high] preserving overlapping slots.
PyObject *seqplane_set_window(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long low, high;
    if (!PyArg_ParseTuple(args, "LL", &low, &high)) return nullptr;
    if (high - low >= (1 << 22)) {
        PyErr_SetString(PyExc_ValueError, "window too large");
        return nullptr;
    }
    if (low == p->low && high == p->high) Py_RETURN_NONE;  // unchanged
    std::vector<SeqSlot> fresh((size_t)(high >= low ? high - low + 1 : 0));
    for (auto &s : fresh) {
        s.prep_mask.assign((size_t)p->words, 0);
        s.commit_mask.assign((size_t)p->words, 0);
    }
    int64_t from = low > p->low ? low : p->low;
    int64_t to = high < p->high ? high : p->high;
    for (int64_t sn = from; sn <= to; sn++)
        fresh[(size_t)(sn - low)] = std::move((*p->slots)[(size_t)(sn - p->low)]);
    *p->slots = std::move(fresh);
    p->low = low;
    p->high = high;
    Py_RETURN_NONE;
}

inline SeqSlot *seq_slot(SeqPlaneObj *p, int64_t seq_no) {
    if (seq_no < p->low || seq_no > p->high) return nullptr;
    return &(*p->slots)[(size_t)(seq_no - p->low)];
}

PyObject *seqplane_set_phase(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long seq_no;
    int phase;
    if (!PyArg_ParseTuple(args, "Li", &seq_no, &phase)) return nullptr;
    SeqSlot *s = seq_slot(p, seq_no);
    if (!s) {
        PyErr_SetString(PyExc_IndexError, "seq_no outside plane window");
        return nullptr;
    }
    s->phase = (uint8_t)phase;
    Py_RETURN_NONE;
}

PyObject *seqplane_set_expected(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long seq_no;
    const char *d;
    Py_ssize_t dlen;
    if (!PyArg_ParseTuple(args, "Ly#", &seq_no, &d, &dlen)) return nullptr;
    SeqSlot *s = seq_slot(p, seq_no);
    if (!s) {
        PyErr_SetString(PyExc_IndexError, "seq_no outside plane window");
        return nullptr;
    }
    s->expected.assign(d, (size_t)dlen);
    s->expected_set = true;
    s->find_count(d, (size_t)dlen, true, /*force=*/true);
    Py_RETURN_NONE;
}

// Core vote application.  Returns the post-increment count for the vote's
// digest (0 when deduplicated or uncounted), and sets *hint when Python
// should run the corresponding transition check.
inline int32_t seq_apply_core(SeqPlaneObj *p, SeqSlot *s, int kind,
                              const char *d, size_t dlen, int source,
                              bool *dup, bool *hint) {
    *dup = false;
    *hint = false;
    uint64_t *pw = &s->prep_mask[(size_t)(source >> 6)];
    uint64_t *cw = &s->commit_mask[(size_t)(source >> 6)];
    uint64_t bit = 1ULL << (source & 63);
    bool matches_expected =
        s->expected.size() == dlen &&
        std::memcmp(s->expected.data(), d, dlen) == 0;
    if (kind == 0) {  // prepare: dedup on (prep|commit) bit
        if ((*pw | *cw) & bit) {
            *dup = true;
            return 0;
        }
        *pw |= bit;
        if (source == p->my_id) {
            s->my_prep.assign(d, dlen);
            s->my_prep_set = true;
        }
        DigestCount *c = s->find_count(d, dlen, true, false);
        int32_t n = 0;
        if (c) n = ++c->prep;
        if (s->phase == PH_PREPREPARED) {
            if (matches_expected && n >= p->iq) *hint = true;
        } else if (s->phase == PH_READY || s->phase == PH_PENDING_REQUESTS) {
            *hint = true;  // digest-arrival path: Python advance_state
        }
        return n;
    }
    // commit: dedup on commit bit only
    if (*cw & bit) {
        *dup = true;
        return 0;
    }
    *cw |= bit;
    DigestCount *c = s->find_count(d, dlen, true, false);
    int32_t n = 0;
    if (c) n = ++c->commit;
    if (s->phase == PH_PREPARED && matches_expected && n >= p->iq)
        *hint = true;
    return n;
}

// apply_vote(kind, seq_no, digest_bytes, source) -> None (duplicate) | count.
// The slow-path entry used by Sequence.apply_prepare_msg/apply_commit_msg;
// the caller has already passed the epoch_active filters.
PyObject *seqplane_apply_vote(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    int kind, source;
    long long seq_no;
    const char *d;
    Py_ssize_t dlen;
    if (!PyArg_ParseTuple(args, "iLy#i", &kind, &seq_no, &d, &dlen, &source))
        return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    SeqSlot *s = seq_slot(p, seq_no);
    if (!s) {
        PyErr_SetString(PyExc_IndexError, "seq_no outside plane window");
        return nullptr;
    }
    bool dup, hint;
    int32_t n = seq_apply_core(p, s, kind, d, (size_t)dlen, source, &dup, &hint);
    if (dup) Py_RETURN_NONE;
    return PyLong_FromLong((long)n);
}

// apply_votes(packed, source) -> list of records, in vote order:
//   (k,)             fallback: Python routes the original message (future
//                    buffering, wrong epoch, unpackable digest)
//   (kind, seq_no)   hint: Python runs the transition check
// Packed record layout (56 bytes, little-endian):
//   u8 kind (0 prepare, 1 commit, 255 unpackable), u8 dlen (<=32), pad[6],
//   i64 seq_no, i64 epoch, u8 digest[32].
PyObject *seqplane_apply_votes(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    Py_buffer packed;
    int source;
    if (!PyArg_ParseTuple(args, "y*i", &packed, &source)) return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyBuffer_Release(&packed);
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    PyObject *out = PyList_New(0);
    if (!out) {
        PyBuffer_Release(&packed);
        return nullptr;
    }
    const char *base = (const char *)packed.buf;
    Py_ssize_t n = packed.len / 56;
    for (Py_ssize_t k = 0; k < n; k++) {
        const char *rec = base + k * 56;
        uint8_t kind = (uint8_t)rec[0];
        uint8_t dlen = (uint8_t)rec[1];
        int64_t seq_no, epoch;
        std::memcpy(&seq_no, rec + 8, 8);
        std::memcpy(&epoch, rec + 16, 8);
        const char *d = rec + 24;

        PyObject *item = nullptr;
        if (kind > 1 || epoch != p->epoch) {
            item = Py_BuildValue("(n)", (Py_ssize_t)k);  // fallback
        } else {
            // Mirror _step_prepare/_step_commit filters (all pre-window
            // verdicts are silent drops, so their relative order is not
            // observable).  PAST first: it also rejects negative seq_no
            // before the bucket modulo, whose C++ sign would otherwise
            // index out of bounds.
            if (seq_no < p->low) continue;  // PAST
            if (kind == 0 && p->nb > 0 &&
                (*p->buckets)[(size_t)(seq_no % p->nb)] == source)
                continue;  // INVALID: owners never send Prepare
            if (seq_no > p->planned_expiration) continue;  // INVALID
            if (seq_no > p->high) {
                item = Py_BuildValue("(n)", (Py_ssize_t)k);  // FUTURE
            } else {
                SeqSlot *s = &(*p->slots)[(size_t)(seq_no - p->low)];
                bool dup, hint;
                seq_apply_core(p, s, kind, d, dlen, source, &dup, &hint);
                if (!hint) continue;
                item = Py_BuildValue("iL", (int)kind, (long long)seq_no);
            }
        }
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            PyBuffer_Release(&packed);
            return nullptr;
        }
        Py_DECREF(item);
    }
    PyBuffer_Release(&packed);
    return out;
}

// query(seq_no) -> (prep_count, commit_count, self_prep_or_commit,
//                   self_commit, my_prep_matches_expected)
// Everything Python's _check_prepare_quorum/_check_commit_quorum read.
// Counts are for the expected digest ("" until set_expected — matching the
// Python path's `digest or b""` keying).
PyObject *seqplane_query(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long seq_no;
    if (!PyArg_ParseTuple(args, "L", &seq_no)) return nullptr;
    SeqSlot *s = seq_slot(p, seq_no);
    if (!s) {
        PyErr_SetString(PyExc_IndexError, "seq_no outside plane window");
        return nullptr;
    }
    DigestCount *c =
        s->find_count(s->expected.data(), s->expected.size(), false, false);
    uint64_t self_p = s->prep_mask[(size_t)(p->my_id >> 6)] &
                      (1ULL << (p->my_id & 63));
    uint64_t self_c = s->commit_mask[(size_t)(p->my_id >> 6)] &
                      (1ULL << (p->my_id & 63));
    // Python dict path compares (my_prepare_digest or b"") != (digest or b"");
    // an unset my_prep is the empty string here, matching.
    bool my_matches = s->my_prep == s->expected;
    return Py_BuildValue("iiiii", c ? (int)c->prep : 0,
                         c ? (int)c->commit : 0,
                         (self_p | self_c) ? 1 : 0, self_c ? 1 : 0,
                         my_matches ? 1 : 0);
}

// export_slot(seq_no) -> (prep_mask, commit_mask, counts_list, my_prep|None)
// for the pure-Python rebuild in tests / debugging.
PyObject *seqplane_export_slot(PyObject *self, PyObject *args) {
    SeqPlaneObj *p = (SeqPlaneObj *)self;
    long long seq_no;
    if (!PyArg_ParseTuple(args, "L", &seq_no)) return nullptr;
    SeqSlot *s = seq_slot(p, seq_no);
    if (!s) Py_RETURN_NONE;
    PyObject *pm = mask_to_bytes(s->prep_mask.data(), p->words);
    PyObject *cm = mask_to_bytes(s->commit_mask.data(), p->words);
    PyObject *counts = PyList_New(0);
    if (!pm || !cm || !counts) {
        Py_XDECREF(pm);
        Py_XDECREF(cm);
        Py_XDECREF(counts);
        return nullptr;
    }
    for (auto &c : s->counts) {
        PyObject *item = Py_BuildValue(
            "y#ii", c.digest.data(), (Py_ssize_t)c.digest.size(),
            (int)c.prep, (int)c.commit);
        if (!item || PyList_Append(counts, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(pm);
            Py_DECREF(cm);
            Py_DECREF(counts);
            return nullptr;
        }
        Py_DECREF(item);
    }
    PyObject *my_prep;
    if (s->my_prep_set)
        my_prep = PyBytes_FromStringAndSize(s->my_prep.data(),
                                            (Py_ssize_t)s->my_prep.size());
    else {
        my_prep = Py_None;
        Py_INCREF(Py_None);
    }
    if (!my_prep) {
        Py_DECREF(pm);
        Py_DECREF(cm);
        Py_DECREF(counts);
        return nullptr;
    }
    return Py_BuildValue("NNNN", pm, cm, counts, my_prep);
}

PyMethodDef seqplane_methods[] = {
    {"reset", seqplane_reset, METH_VARARGS, nullptr},
    {"set_window", seqplane_set_window, METH_VARARGS, nullptr},
    {"set_phase", seqplane_set_phase, METH_VARARGS, nullptr},
    {"set_expected", seqplane_set_expected, METH_VARARGS, nullptr},
    {"apply_vote", seqplane_apply_vote, METH_VARARGS, nullptr},
    {"apply_votes", seqplane_apply_votes, METH_VARARGS, nullptr},
    {"query", seqplane_query, METH_VARARGS, nullptr},
    {"export_slot", seqplane_export_slot, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject SeqPlaneType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------------------------------------------------------------------------
// Module-level functions.

PyObject *interned_str_client_id;
PyObject *interned_str_req_no;
PyObject *interned_str_digest;
PyObject *interned_str_seq_no;
PyObject *interned_str_epoch;

// Message classes registered once by the Python glue so pack_votes can
// classify by exact type (borrowed refs held for the process lifetime).
PyObject *g_prepare_type = nullptr;
PyObject *g_commit_type = nullptr;

// register_vote_types(Prepare, Commit)
PyObject *mod_register_vote_types(PyObject *, PyObject *args) {
    PyObject *prep, *commit;
    if (!PyArg_ParseTuple(args, "OO", &prep, &commit)) return nullptr;
    Py_XDECREF(g_prepare_type);
    Py_XDECREF(g_commit_type);
    Py_INCREF(prep);
    Py_INCREF(commit);
    g_prepare_type = prep;
    g_commit_type = commit;
    Py_RETURN_NONE;
}

// pack_votes(msgs) -> (packed_bytes, vote_msgs, rest)
// Splits an envelope's messages into the Prepare/Commit vote stream (packed
// for SeqPlane.apply_votes, originals kept aligned by index for fallback
// routing) and the rest.  A vote whose digest exceeds 32 bytes is packed as
// kind 255 (unpackable -> fallback).  Record layout matches SeqPlane.apply_votes (56 bytes).
PyObject *mod_pack_votes(PyObject *, PyObject *arg) {
    if (!g_prepare_type) {
        PyErr_SetString(PyExc_RuntimeError, "vote types not registered");
        return nullptr;
    }
    PyObject *seq = PySequence_Fast(arg, "pack_votes expects a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *votes = PyList_New(0);
    PyObject *rest = PyList_New(0);
    PyObject *packed = nullptr;
    std::string buf;
    buf.reserve((size_t)n * 56);
    if (!votes || !rest) goto fail;
    for (Py_ssize_t k = 0; k < n; k++) {
        PyObject *msg = PySequence_Fast_GET_ITEM(seq, k);
        PyObject *t = (PyObject *)Py_TYPE(msg);
        int kind;
        if (t == g_prepare_type)
            kind = 0;
        else if (t == g_commit_type)
            kind = 1;
        else {
            if (PyList_Append(rest, msg) < 0) goto fail;
            continue;
        }
        PyObject *sn_o = PyObject_GetAttr(msg, interned_str_seq_no);
        PyObject *ep_o = sn_o ? PyObject_GetAttr(msg, interned_str_epoch) : nullptr;
        PyObject *dg_o = ep_o ? PyObject_GetAttr(msg, interned_str_digest) : nullptr;
        if (!dg_o) {
            Py_XDECREF(sn_o);
            Py_XDECREF(ep_o);
            goto fail;
        }
        {
            int64_t seq_no = PyLong_AsLongLong(sn_o);
            int64_t epoch = PyLong_AsLongLong(ep_o);
            char *d = nullptr;
            Py_ssize_t dlen = 0;
            int bad = PyBytes_AsStringAndSize(dg_o, &d, &dlen) < 0;
            if (bad) PyErr_Clear();
            char rec[56];
            std::memset(rec, 0, 56);
            if (bad || dlen > 32 || PyErr_Occurred()) {
                PyErr_Clear();
                rec[0] = (char)(uint8_t)255;  // unpackable -> fallback
            } else {
                rec[0] = (char)(uint8_t)kind;
                rec[1] = (char)(uint8_t)dlen;
                std::memcpy(rec + 24, d, (size_t)dlen);
            }
            std::memcpy(rec + 8, &seq_no, 8);
            std::memcpy(rec + 16, &epoch, 8);
            buf.append(rec, 56);
        }
        Py_DECREF(sn_o);
        Py_DECREF(ep_o);
        Py_DECREF(dg_o);
        if (PyList_Append(votes, msg) < 0) goto fail;
    }
    packed = PyBytes_FromStringAndSize(buf.data(), (Py_ssize_t)buf.size());
    if (!packed) goto fail;
    Py_DECREF(seq);
    return Py_BuildValue("NNN", packed, votes, rest);
fail:
    Py_XDECREF(votes);
    Py_XDECREF(rest);
    Py_XDECREF(packed);
    Py_DECREF(seq);
    return nullptr;
}

// pack_acks(acks: sequence of RequestAck) -> bytes (16 bytes per ack).
PyObject *mod_pack_acks(PyObject *, PyObject *arg) {
    PyObject *seq = PySequence_Fast(arg, "pack_acks expects a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 16);
    if (!out) {
        Py_DECREF(seq);
        return nullptr;
    }
    char *buf = PyBytes_AS_STRING(out);
    for (Py_ssize_t k = 0; k < n; k++) {
        PyObject *ack = PySequence_Fast_GET_ITEM(seq, k);
        PyObject *cid_o = PyObject_GetAttr(ack, interned_str_client_id);
        if (!cid_o) goto fail;
        PyObject *rn_o = PyObject_GetAttr(ack, interned_str_req_no);
        if (!rn_o) {
            Py_DECREF(cid_o);
            goto fail;
        }
        PyObject *dg_o = PyObject_GetAttr(ack, interned_str_digest);
        if (!dg_o) {
            Py_DECREF(cid_o);
            Py_DECREF(rn_o);
            goto fail;
        }
        {
            int32_t client_id = (int32_t)PyLong_AsLongLong(cid_o);
            int64_t req_no = PyLong_AsLongLong(rn_o);
            int32_t digest_id = g_intern->intern(dg_o);
            Py_DECREF(cid_o);
            Py_DECREF(rn_o);
            Py_DECREF(dg_o);
            if (digest_id == -2 || PyErr_Occurred()) goto fail;
            char *rec = buf + k * 16;
            std::memcpy(rec, &client_id, 4);
            std::memcpy(rec + 4, &digest_id, 4);
            std::memcpy(rec + 8, &req_no, 8);
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject *mod_digest_bytes(PyObject *, PyObject *arg) {
    long id = PyLong_AsLong(arg);
    if (id == -1 && PyErr_Occurred()) return nullptr;
    if (id < 0 || (size_t)id >= g_intern->objects.size()) {
        PyErr_SetString(PyExc_IndexError, "unknown digest id");
        return nullptr;
    }
    PyObject *o = g_intern->objects[(size_t)id];
    Py_INCREF(o);
    return o;
}

PyMethodDef module_methods[] = {
    {"pack_acks", mod_pack_acks, METH_O, nullptr},
    {"digest_bytes", mod_digest_bytes, METH_O, nullptr},
    {"register_vote_types", mod_register_vote_types, METH_VARARGS, nullptr},
    {"pack_votes", mod_pack_votes, METH_O, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_core",
    "Native hot-path planes for mirbft_tpu (ack-vote accumulation).",
    -1, module_methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__core(void) {
    PlaneType.tp_name = "mirbft_tpu._native._core.AckPlane";
    PlaneType.tp_basicsize = sizeof(Plane);
    PlaneType.tp_flags = Py_TPFLAGS_DEFAULT;
    PlaneType.tp_new = plane_new;
    PlaneType.tp_dealloc = plane_dealloc;
    PlaneType.tp_methods = plane_methods;
    if (PyType_Ready(&PlaneType) < 0) return nullptr;

    SeqPlaneType.tp_name = "mirbft_tpu._native._core.SeqPlane";
    SeqPlaneType.tp_basicsize = sizeof(SeqPlaneObj);
    SeqPlaneType.tp_flags = Py_TPFLAGS_DEFAULT;
    SeqPlaneType.tp_new = seqplane_new;
    SeqPlaneType.tp_dealloc = seqplane_dealloc;
    SeqPlaneType.tp_methods = seqplane_methods;
    if (PyType_Ready(&SeqPlaneType) < 0) return nullptr;

    g_intern = new InternTable();
    interned_str_client_id = PyUnicode_InternFromString("client_id");
    interned_str_req_no = PyUnicode_InternFromString("req_no");
    interned_str_digest = PyUnicode_InternFromString("digest");
    interned_str_seq_no = PyUnicode_InternFromString("seq_no");
    interned_str_epoch = PyUnicode_InternFromString("epoch");

    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return nullptr;
    Py_INCREF(&PlaneType);
    if (PyModule_AddObject(m, "AckPlane", (PyObject *)&PlaneType) < 0) {
        Py_DECREF(&PlaneType);
        Py_DECREF(m);
        return nullptr;
    }
    Py_INCREF(&SeqPlaneType);
    if (PyModule_AddObject(m, "SeqPlane", (PyObject *)&SeqPlaneType) < 0) {
        Py_DECREF(&SeqPlaneType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
