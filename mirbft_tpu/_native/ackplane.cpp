// Native ack-vote plane: the O(N^2)-per-request hot path of the client
// request dissemination protocol (reference pkg/statemachine/
// client_hash_disseminator.go:806-876), reduced to packed bitmask
// accumulation in C.
//
// Design contract with mirbft_tpu/statemachine/disseminator.py:
//
//  * The plane owns vote accumulation ONLY for the green path of a
//    (client, req_no): every observed ack carries the same single non-null
//    digest ("canonical").  Anything else — a null digest, a second distinct
//    digest, a forced ack, a buffered-replay ack — is returned to Python
//    ("pyfall"), which EJECTS the slot (syncs the native mask into the
//    Python ClientRequest objects and marks the slot ejected) and runs the
//    exact reference semantics from then on.
//
//  * Quorum crossings are returned as records and REPLAYED by Python
//    through the same tail logic as the pure-Python path, preserving action
//    order and content exactly.  The crossing condition mirrors
//    Client.ack_into / Client.ack_run: emit when count == weak_q, when
//    count == strong_q, or when source == my_id and count >= weak_q —
//    including for duplicate votes (a duplicate arriving while the count
//    sits at a threshold re-runs the tail in the reference semantics, so it
//    must here too).
//
//  * Digests are interned process-wide: digest bytes <-> int32 id.  Ids
//    never leave the process and never enter hashes or the wire format.
//
// No external dependencies; CPython C API only (the environment provides no
// pybind11 — see repo docs/tpu_plane.md).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Digest interning (module-global).

struct BytesKey {
    std::string data;
    bool operator==(const BytesKey &o) const { return data == o.data; }
};

struct BytesKeyHash {
    size_t operator()(const BytesKey &k) const {
        return std::hash<std::string>()(k.data);
    }
};

struct InternTable {
    std::unordered_map<BytesKey, int32_t, BytesKeyHash> ids;
    std::vector<PyObject *> objects;  // id -> bytes object (owned ref)
    size_t cap = 1u << 20;  // bound on distinct digests held native

    // Returns the digest id, or -1 when the digest cannot be owned
    // natively: the null digest, or the table is at capacity.  -1 routes
    // the ack to the Python path, which works from the original bytes —
    // correctness is unaffected, the native fast path just stops covering
    // new digests (memory stays bounded against digest-flooding peers).
    // -2 signals a Python error.
    int32_t intern(PyObject *bytes_obj) {
        char *buf;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(bytes_obj, &buf, &len) < 0) return -2;
        if (len == 0) return -1;  // null digest sentinel
        BytesKey key{std::string(buf, (size_t)len)};
        auto it = ids.find(key);
        if (it != ids.end()) return it->second;
        if (objects.size() >= cap) return -1;  // full: Python path takes over
        int32_t id = (int32_t)objects.size();
        Py_INCREF(bytes_obj);
        objects.push_back(bytes_obj);
        ids.emplace(std::move(key), id);
        return id;
    }
};

InternTable *g_intern = nullptr;

// ---------------------------------------------------------------------------
// Plane object.

constexpr uint8_t SLOT_EJECTED = 1;

struct ClientWin {
    int64_t low = 0;
    int64_t high = -1;  // inclusive; high < low -> empty
    std::vector<int32_t> canonical;  // digest id, -1 = none yet
    std::vector<uint16_t> count;
    std::vector<uint8_t> flags;
    std::vector<uint64_t> votes;  // width * words

    int64_t width() const { return high - low + 1; }
};

struct Plane {
    PyObject_HEAD
    int n_nodes;
    int my_id;
    int weak_q;
    int strong_q;
    int words;
    std::unordered_map<int64_t, ClientWin> *clients;
};

PyObject *mask_to_bytes(const uint64_t *w, int words) {
    // Little-endian byte string, words*8 long; Python: int.from_bytes(b,'little')
    return PyBytes_FromStringAndSize((const char *)w, (Py_ssize_t)words * 8);
}

int bytes_to_mask(PyObject *b, uint64_t *out, int words) {
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(b, &buf, &len) < 0) return -1;
    std::memset(out, 0, (size_t)words * 8);
    if (len > (Py_ssize_t)words * 8) len = (Py_ssize_t)words * 8;
    std::memcpy(out, buf, (size_t)len);
    return 0;
}

void plane_dealloc(PyObject *self) {
    Plane *p = (Plane *)self;
    delete p->clients;
    Py_TYPE(self)->tp_free(self);
}

PyObject *plane_new(PyTypeObject *type, PyObject *args, PyObject *kwds) {
    static const char *kwlist[] = {"n_nodes", "my_id", "weak_q", "strong_q",
                                   nullptr};
    int n_nodes, my_id, weak_q, strong_q;
    if (!PyArg_ParseTupleAndKeywords(args, kwds, "iiii", (char **)kwlist,
                                     &n_nodes, &my_id, &weak_q, &strong_q))
        return nullptr;
    if (n_nodes <= 0 || n_nodes > 4096) {
        PyErr_SetString(PyExc_ValueError, "n_nodes out of range");
        return nullptr;
    }
    Plane *p = (Plane *)type->tp_alloc(type, 0);
    if (!p) return nullptr;
    p->n_nodes = n_nodes;
    p->my_id = my_id;
    p->weak_q = weak_q;
    p->strong_q = strong_q;
    p->words = (n_nodes + 63) / 64;
    p->clients = new std::unordered_map<int64_t, ClientWin>();
    return (PyObject *)p;
}

// set_client(client_id, low, high): create or rebase a client window.
// Slots in the [low, high] overlap with the previous window are preserved;
// everything else starts empty.
PyObject *plane_set_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, low, high;
    if (!PyArg_ParseTuple(args, "LLL", &client_id, &low, &high)) return nullptr;
    if (high < low || high - low > 1 << 20) {
        PyErr_SetString(PyExc_ValueError, "bad window");
        return nullptr;
    }
    const int words = p->words;
    int64_t w = high - low + 1;
    ClientWin fresh;
    fresh.low = low;
    fresh.high = high;
    fresh.canonical.assign((size_t)w, -1);
    fresh.count.assign((size_t)w, 0);
    fresh.flags.assign((size_t)w, 0);
    fresh.votes.assign((size_t)w * words, 0);

    auto it = p->clients->find(client_id);
    if (it != p->clients->end()) {
        ClientWin &old = it->second;
        int64_t from = low > old.low ? low : old.low;
        int64_t to = high < old.high ? high : old.high;
        for (int64_t rn = from; rn <= to; rn++) {
            size_t oi = (size_t)(rn - old.low), ni = (size_t)(rn - low);
            fresh.canonical[ni] = old.canonical[oi];
            fresh.count[ni] = old.count[oi];
            fresh.flags[ni] = old.flags[oi];
            std::memcpy(&fresh.votes[ni * words], &old.votes[oi * words],
                        (size_t)words * 8);
        }
        it->second = std::move(fresh);
    } else {
        p->clients->emplace(client_id, std::move(fresh));
    }
    Py_RETURN_NONE;
}

PyObject *plane_drop_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id;
    if (!PyArg_ParseTuple(args, "L", &client_id)) return nullptr;
    p->clients->erase(client_id);
    Py_RETURN_NONE;
}

PyObject *plane_clear(PyObject *self, PyObject *) {
    Plane *p = (Plane *)self;
    p->clients->clear();
    Py_RETURN_NONE;
}

// import_slot(client_id, req_no, digest_bytes|None, mask_bytes, count)
// (Re-)take native ownership of a slot with known state; un-ejects.
PyObject *plane_import_slot(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    PyObject *digest_obj, *mask_obj;
    int count;
    if (!PyArg_ParseTuple(args, "LLOOi", &client_id, &req_no, &digest_obj,
                          &mask_obj, &count))
        return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) {
        PyErr_SetString(PyExc_KeyError, "unknown client");
        return nullptr;
    }
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) {
        PyErr_SetString(PyExc_IndexError, "req_no outside window");
        return nullptr;
    }
    int32_t did = -1;
    if (digest_obj != Py_None) {
        did = g_intern->intern(digest_obj);
        if (did == -2) return nullptr;
        if (did == -1) {
            // Null digest (caller bug) or intern table at capacity: the
            // slot cannot be owned natively.
            Py_RETURN_FALSE;
        }
    }
    size_t i = (size_t)(req_no - win.low);
    win.canonical[i] = did;
    win.count[i] = (uint16_t)count;
    win.flags[i] = 0;
    if (bytes_to_mask(mask_obj, &win.votes[i * p->words], p->words) < 0)
        return nullptr;
    Py_RETURN_TRUE;
}

PyObject *plane_mark_ejected(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it != p->clients->end()) {
        ClientWin &win = it->second;
        if (req_no >= win.low && req_no <= win.high)
            win.flags[(size_t)(req_no - win.low)] |= SLOT_EJECTED;
    }
    Py_RETURN_NONE;
}

PyObject *slot_state_tuple(Plane *p, ClientWin &win, size_t i) {
    PyObject *mask = mask_to_bytes(&win.votes[i * p->words], p->words);
    if (!mask) return nullptr;
    PyObject *res = Py_BuildValue("iNi", (int)win.canonical[i], mask,
                                  (int)win.count[i]);
    return res;
}

// peek(client_id, req_no) -> (digest_id, mask_bytes, count) | None
// None when the plane has nothing live for the slot (unknown client,
// out of window, or ejected).
PyObject *plane_peek(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) Py_RETURN_NONE;
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) Py_RETURN_NONE;
    size_t i = (size_t)(req_no - win.low);
    if (win.flags[i] & SLOT_EJECTED) Py_RETURN_NONE;
    if (win.canonical[i] == -1 && win.count[i] == 0) Py_RETURN_NONE;
    return slot_state_tuple(p, win, i);
}

// eject(client_id, req_no) -> slot state (digest_id, mask_bytes, count) or
// None, and marks the slot ejected.  Unlike peek(), an already-ejected
// slot's state is STILL returned: apply_core may mark a slot mid-batch and
// Python must still be able to merge the accumulated votes (the merge is an
// idempotent bitmask OR, so repeated ejects are harmless).
PyObject *plane_eject(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    if (!PyArg_ParseTuple(args, "LL", &client_id, &req_no)) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) Py_RETURN_NONE;
    ClientWin &win = it->second;
    if (req_no < win.low || req_no > win.high) Py_RETURN_NONE;
    size_t i = (size_t)(req_no - win.low);
    win.flags[i] |= SLOT_EJECTED;
    if (win.canonical[i] == -1 && win.count[i] == 0) Py_RETURN_NONE;
    return slot_state_tuple(p, win, i);
}

// Core per-ack application.  Returns:
//   0 applied, no crossing;  1 python-fallback;  2 past (drop);
//   3 crossing (out_* filled).
//
// A fallback on an existing in-window slot marks it EJECTED immediately, so
// every LATER ack for the same slot — including later acks in the same
// batch — also falls back, preserving the reference's strict per-ack
// ordering (e.g. the first-non-null-binding rule when one batch carries
// conflicting digests from one source).  Python retrieves the accumulated
// votes via eject(), which stays valid after the mark.
inline int apply_core(Plane *p, int64_t client_id, int64_t req_no,
                      int32_t digest_id, int source, ClientWin **out_win,
                      size_t *out_idx) {
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) return 1;  // unknown client -> buffer
    ClientWin &win = it->second;
    if (req_no < win.low) return 2;   // past
    if (req_no > win.high) return 1;  // future -> buffer
    size_t i = (size_t)(req_no - win.low);
    if (win.flags[i] & SLOT_EJECTED) return 1;
    if (digest_id < 0) {
        win.flags[i] |= SLOT_EJECTED;  // null digest -> python semantics
        return 1;
    }
    if (win.canonical[i] == -1)
        win.canonical[i] = digest_id;
    else if (win.canonical[i] != digest_id) {
        win.flags[i] |= SLOT_EJECTED;  // conflicting digest -> python
        return 1;
    }
    uint64_t *w = &win.votes[i * p->words + (source >> 6)];
    uint64_t bit = 1ULL << (source & 63);
    if (!(*w & bit)) {
        *w |= bit;
        win.count[i]++;
    }
    int c = win.count[i];
    if (c == p->weak_q || c == p->strong_q ||
        (source == p->my_id && c >= p->weak_q)) {
        *out_win = &win;
        *out_idx = i;
        return 3;
    }
    return 0;
}

// apply_batch(packed_bytes, source) -> list of records in ack order:
//   (idx,)                                   python-fallback
//   (idx, client_id, req_no, digest_id, count, mask_bytes)   crossing
// Packed record layout (little-endian, 16 bytes):
//   int32 client_id, int32 digest_id (-1 null), int64 req_no.
PyObject *plane_apply_batch(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    Py_buffer packed;
    int source;
    if (!PyArg_ParseTuple(args, "y*i", &packed, &source)) return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyBuffer_Release(&packed);
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    PyObject *out = PyList_New(0);
    if (!out) {
        PyBuffer_Release(&packed);
        return nullptr;
    }
    const char *base = (const char *)packed.buf;
    Py_ssize_t n = packed.len / 16;
    for (Py_ssize_t k = 0; k < n; k++) {
        const char *rec = base + k * 16;
        int32_t client_id, digest_id;
        int64_t req_no;
        std::memcpy(&client_id, rec, 4);
        std::memcpy(&digest_id, rec + 4, 4);
        std::memcpy(&req_no, rec + 8, 8);
        ClientWin *win;
        size_t idx;
        int r = apply_core(p, client_id, req_no, digest_id, source, &win, &idx);
        if (r == 0 || r == 2) continue;
        PyObject *item;
        if (r == 1) {
            item = Py_BuildValue("(n)", (Py_ssize_t)k);
        } else {
            PyObject *mask = mask_to_bytes(&win->votes[idx * p->words], p->words);
            if (!mask) {
                Py_DECREF(out);
                PyBuffer_Release(&packed);
                return nullptr;
            }
            item = Py_BuildValue("nLLiiN", (Py_ssize_t)k, (long long)client_id,
                                 (long long)req_no, (int)digest_id,
                                 (int)win->count[idx], mask);
        }
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            PyBuffer_Release(&packed);
            return nullptr;
        }
        Py_DECREF(item);
    }
    PyBuffer_Release(&packed);
    return out;
}

// apply_one(client_id, req_no, digest_bytes, source) ->
//   0 | 1 | 2 (as apply_core) | (count, digest_id, mask_bytes) on crossing.
PyObject *plane_apply_one(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id, req_no;
    PyObject *digest_obj;
    int source;
    if (!PyArg_ParseTuple(args, "LLOi", &client_id, &req_no, &digest_obj,
                          &source))
        return nullptr;
    if (source < 0 || source >= p->n_nodes) {
        PyErr_SetString(PyExc_ValueError, "source out of range");
        return nullptr;
    }
    int32_t did = g_intern->intern(digest_obj);
    if (did == -2) return nullptr;
    ClientWin *win;
    size_t idx;
    int r = apply_core(p, client_id, req_no, did, source, &win, &idx);
    if (r == 3) {
        PyObject *mask = mask_to_bytes(&win->votes[idx * p->words], p->words);
        if (!mask) return nullptr;
        return Py_BuildValue("iiN", (int)win->count[idx], (int)did, mask);
    }
    return PyLong_FromLong(r);
}

// export_client(client_id) -> list of (req_no, digest_id, mask_bytes, count)
// for live (non-ejected, touched) slots; used at reinitialize.
PyObject *plane_export_client(PyObject *self, PyObject *args) {
    Plane *p = (Plane *)self;
    long long client_id;
    if (!PyArg_ParseTuple(args, "L", &client_id)) return nullptr;
    PyObject *out = PyList_New(0);
    if (!out) return nullptr;
    auto it = p->clients->find(client_id);
    if (it == p->clients->end()) return out;
    ClientWin &win = it->second;
    for (int64_t rn = win.low; rn <= win.high; rn++) {
        size_t i = (size_t)(rn - win.low);
        if (win.flags[i] & SLOT_EJECTED) continue;
        if (win.canonical[i] == -1 && win.count[i] == 0) continue;
        PyObject *mask = mask_to_bytes(&win.votes[i * p->words], p->words);
        if (!mask) {
            Py_DECREF(out);
            return nullptr;
        }
        PyObject *item =
            Py_BuildValue("LiNi", (long long)rn, (int)win.canonical[i], mask,
                          (int)win.count[i]);
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(item);
    }
    return out;
}

PyMethodDef plane_methods[] = {
    {"set_client", plane_set_client, METH_VARARGS, nullptr},
    {"drop_client", plane_drop_client, METH_VARARGS, nullptr},
    {"clear", plane_clear, METH_NOARGS, nullptr},
    {"import_slot", plane_import_slot, METH_VARARGS, nullptr},
    {"mark_ejected", plane_mark_ejected, METH_VARARGS, nullptr},
    {"peek", plane_peek, METH_VARARGS, nullptr},
    {"eject", plane_eject, METH_VARARGS, nullptr},
    {"apply_batch", plane_apply_batch, METH_VARARGS, nullptr},
    {"apply_one", plane_apply_one, METH_VARARGS, nullptr},
    {"export_client", plane_export_client, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject PlaneType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

// ---------------------------------------------------------------------------
// Module-level functions.

PyObject *interned_str_client_id;
PyObject *interned_str_req_no;
PyObject *interned_str_digest;

// pack_acks(acks: sequence of RequestAck) -> bytes (16 bytes per ack).
PyObject *mod_pack_acks(PyObject *, PyObject *arg) {
    PyObject *seq = PySequence_Fast(arg, "pack_acks expects a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 16);
    if (!out) {
        Py_DECREF(seq);
        return nullptr;
    }
    char *buf = PyBytes_AS_STRING(out);
    for (Py_ssize_t k = 0; k < n; k++) {
        PyObject *ack = PySequence_Fast_GET_ITEM(seq, k);
        PyObject *cid_o = PyObject_GetAttr(ack, interned_str_client_id);
        if (!cid_o) goto fail;
        PyObject *rn_o = PyObject_GetAttr(ack, interned_str_req_no);
        if (!rn_o) {
            Py_DECREF(cid_o);
            goto fail;
        }
        PyObject *dg_o = PyObject_GetAttr(ack, interned_str_digest);
        if (!dg_o) {
            Py_DECREF(cid_o);
            Py_DECREF(rn_o);
            goto fail;
        }
        {
            int32_t client_id = (int32_t)PyLong_AsLongLong(cid_o);
            int64_t req_no = PyLong_AsLongLong(rn_o);
            int32_t digest_id = g_intern->intern(dg_o);
            Py_DECREF(cid_o);
            Py_DECREF(rn_o);
            Py_DECREF(dg_o);
            if (digest_id == -2 || PyErr_Occurred()) goto fail;
            char *rec = buf + k * 16;
            std::memcpy(rec, &client_id, 4);
            std::memcpy(rec + 4, &digest_id, 4);
            std::memcpy(rec + 8, &req_no, 8);
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(seq);
    Py_DECREF(out);
    return nullptr;
}

PyObject *mod_digest_bytes(PyObject *, PyObject *arg) {
    long id = PyLong_AsLong(arg);
    if (id == -1 && PyErr_Occurred()) return nullptr;
    if (id < 0 || (size_t)id >= g_intern->objects.size()) {
        PyErr_SetString(PyExc_IndexError, "unknown digest id");
        return nullptr;
    }
    PyObject *o = g_intern->objects[(size_t)id];
    Py_INCREF(o);
    return o;
}

PyMethodDef module_methods[] = {
    {"pack_acks", mod_pack_acks, METH_O, nullptr},
    {"digest_bytes", mod_digest_bytes, METH_O, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_core",
    "Native hot-path planes for mirbft_tpu (ack-vote accumulation).",
    -1, module_methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__core(void) {
    PlaneType.tp_name = "mirbft_tpu._native._core.AckPlane";
    PlaneType.tp_basicsize = sizeof(Plane);
    PlaneType.tp_flags = Py_TPFLAGS_DEFAULT;
    PlaneType.tp_new = plane_new;
    PlaneType.tp_dealloc = plane_dealloc;
    PlaneType.tp_methods = plane_methods;
    if (PyType_Ready(&PlaneType) < 0) return nullptr;

    g_intern = new InternTable();
    interned_str_client_id = PyUnicode_InternFromString("client_id");
    interned_str_req_no = PyUnicode_InternFromString("req_no");
    interned_str_digest = PyUnicode_InternFromString("digest");

    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return nullptr;
    Py_INCREF(&PlaneType);
    if (PyModule_AddObject(m, "AckPlane", (PyObject *)&PlaneType) < 0) {
        Py_DECREF(&PlaneType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
