"""Socket transport plane (L3.5): the layer between the node runtime and
the world.

The reference library is transport-agnostic and never ships a real
``Link``; every transport in this tree was in-process (the testengine's
``SimLink``, the test-local ``FakeTransport``).  This package adds the
deployment story:

* :mod:`mirbft_tpu.net.framing` — the length-prefixed frame codec over the
  canonical ``wire`` serialization (magic + version + kind + length +
  CRC32), with an incremental decoder that survives partial reads and
  rejects torn/oversized/garbage frames by reporting a :class:`FrameError`
  (the connection dies, the process never does).
* :mod:`mirbft_tpu.net.tcp` — :class:`TcpTransport`, a real-socket ``Link``
  with one outbound sender thread + byte-budgeted drop-on-overflow queue
  per peer, a handshake carrying (node id, network-config fingerprint),
  and a per-peer CONNECTING → UP → BACKOFF state machine with capped
  jittered exponential backoff.

Deployment harness: ``python -m mirbft_tpu.tools.mirnet`` runs an N-node
cluster as separate OS processes over localhost TCP (docs/TRANSPORT.md).
"""

from .framing import (
    FRAME_HEADER_LEN,
    FrameDecoder,
    FrameError,
    KIND_CLIENT,
    KIND_HANDSHAKE,
    KIND_MSG,
    encode_frame,
)
from .tcp import TcpTransport, config_fingerprint

__all__ = [
    "FRAME_HEADER_LEN",
    "FrameDecoder",
    "FrameError",
    "KIND_CLIENT",
    "KIND_HANDSHAKE",
    "KIND_MSG",
    "TcpTransport",
    "config_fingerprint",
    "encode_frame",
]
