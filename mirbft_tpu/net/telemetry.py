"""KIND_TELEMETRY subframe codec: the fleet observability plane's wire
format (docs/OBSERVABILITY.md "Fleet plane").

Three subtypes ride one frame kind, mirroring the KIND_GROUP registry in
``groups/ship.py`` so mirlint's wire check can hold constants, registry,
and samples in lockstep:

- ``TEL_PULL`` (parent -> child): request one metrics + trace-ring delta.
  The header's u64 field is ``t0_us``, the parent's clock at send time,
  echoed back verbatim for Cristian-style offset estimation; the JSON
  body carries the parent's trace-ring ``cursor`` for this child.
- ``TEL_REPORT`` (child -> parent): the reply.  The header's u64 field
  echoes the pull's ``t0_us``; the JSON body carries the child's own
  clock reading (``ts_us``), its ``Registry.snapshot()``, and the drained
  trace-ring delta past the requested cursor.
- ``TEL_ANNOUNCE`` (member -> member): best-effort trace-id binding
  propagation.  A node serving a traced client submission pushes the
  ``(client_id, req_no) -> trace_id`` binding to its group peers so every
  replica's ``CommitSpanTracker`` can stamp the shared id — the header's
  u64 field is unused (zero).

Subframe layout::

    subtype 1 byte   TEL_PULL / TEL_REPORT / TEL_ANNOUNCE
    node    4 bytes  big-endian sender node id
    clock   8 bytes  big-endian u64 microseconds (semantics per subtype)
    body    JSON (UTF-8), possibly empty

The body is JSON rather than a packed struct on purpose: reports carry an
open-ended metrics snapshot whose key set grows with every instrument, and
the pull path is off the hot path (one exchange per node per collector
interval), so schema agility wins over bytes here.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Tuple

from mirbft_tpu.net.framing import FrameError

# Declarative subtype registry: mirlint's telemetry wire check walks the
# TEL_* constants and this dict and asserts they agree (tools/mirlint.py).
TEL_PULL = 0
TEL_REPORT = 1
TEL_ANNOUNCE = 2

SUBTYPE_NAMES = {
    TEL_PULL: "tel_pull",
    TEL_REPORT: "tel_report",
    TEL_ANNOUNCE: "tel_announce",
}

_SUB_HEADER = struct.Struct(">BIQ")  # subtype, node id, u64 microseconds


def encode(subtype: int, node_id: int, clock_us: int, body: bytes = b"") -> bytes:
    if subtype not in SUBTYPE_NAMES:
        raise FrameError(f"unknown telemetry subtype {subtype}")
    return _SUB_HEADER.pack(subtype, node_id, clock_us) + body


def decode(payload: bytes) -> Tuple[int, int, int, bytes]:
    """``(subtype, node_id, clock_us, body)`` from a KIND_TELEMETRY
    payload.  Raises :class:`FrameError` on truncation or an unknown
    subtype — the caller drops the connection, never the process."""
    if len(payload) < _SUB_HEADER.size:
        raise FrameError(
            f"telemetry subframe of {len(payload)} bytes is shorter than "
            f"its {_SUB_HEADER.size}-byte header"
        )
    subtype, node_id, clock_us = _SUB_HEADER.unpack_from(payload)
    if subtype not in SUBTYPE_NAMES:
        raise FrameError(f"unknown telemetry subtype {subtype}")
    return subtype, node_id, clock_us, payload[_SUB_HEADER.size:]


def _json_body(doc: Dict) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def encode_pull(node_id: int, t0_us: int, cursor: int) -> bytes:
    """Parent's pull: ``t0_us`` is the parent clock at send (echoed back),
    ``cursor`` the trace-ring position the parent has already drained."""
    return encode(TEL_PULL, node_id, t0_us, _json_body({"cursor": cursor}))


def encode_report(node_id: int, echo_t0_us: int, report: Dict) -> bytes:
    """Child's reply: echoes the pull's ``t0_us``; ``report`` must carry
    ``ts_us`` (the child's clock when it built the report)."""
    return encode(TEL_REPORT, node_id, echo_t0_us, _json_body(report))


def encode_announce(node_id: int, bindings) -> bytes:
    """Trace-binding push: ``bindings`` is ``[(client_id, req_no,
    trace_id_hex), ...]``."""
    body = _json_body(
        {"bindings": [[c, r, t] for c, r, t in bindings]}
    )
    return encode(TEL_ANNOUNCE, node_id, 0, body)


def decode_body(body: bytes) -> Dict:
    """Parse a subframe's JSON body; raises :class:`FrameError` on garbage
    so transport callers keep their drop-the-connection contract."""
    if not body:
        return {}
    try:
        doc = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError) as exc:
        raise FrameError(f"bad telemetry body: {exc}") from None
    if not isinstance(doc, dict):
        raise FrameError("telemetry body is not a JSON object")
    return doc


def sample_payloads() -> Dict[int, bytes]:
    """One representative encoded subframe per subtype — the corpus for
    mirlint's decode -> re-encode byte-identity check."""
    return {
        TEL_PULL: encode_pull(0, 17_000_000, 128),
        TEL_REPORT: encode_report(
            2,
            17_000_000,
            {
                "ts_us": 23_500_000,
                "group": 1,
                "node": "g1n0",
                "metrics": {"group_commits_total": 5.0},
                "trace": {
                    "cursor": 130,
                    "dropped": 0,
                    "events": [],
                    "meta": [],
                },
            },
        ),
        TEL_ANNOUNCE: encode_announce(
            1, [(7, 3, "00deadbeef00beef")]
        ),
    }
