"""Length-prefixed frame codec for the socket transport.

A frame is::

    magic   2 bytes  b"MB"
    version 1 byte   FRAME_VERSION
    kind    1 byte   KIND_HANDSHAKE / KIND_MSG / KIND_CLIENT / KIND_SNAPSHOT
                     / KIND_GROUP
    length  4 bytes  big-endian payload length
    crc32   4 bytes  big-endian CRC32 of the payload
    payload ``length`` bytes (``wire.encode`` output for KIND_MSG)

The payload codec stays ``mirbft_tpu.wire`` — this layer only delimits and
integrity-checks byte streams.  :class:`FrameDecoder` is incremental: feed
it whatever ``recv`` returned (a torn header, half a payload, three frames
at once) and it yields every complete frame.  Any malformed input — wrong
magic, unknown version/kind, oversized length, CRC mismatch — raises
:class:`FrameError`; the caller's contract is to drop the *connection* (the
peer re-syncs by reconnecting; there is no in-stream resynchronization),
never the process.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

FRAME_MAGIC = b"MB"
FRAME_VERSION = 1
FRAME_HEADER_LEN = 12

# Frame kinds.  KIND_HANDSHAKE must be the first frame on every connection
# (tcp.py); KIND_MSG carries one wire-encoded protocol message; KIND_CLIENT
# carries a client-submission envelope (tools/mirnet.py); KIND_SNAPSHOT
# carries one snapshot state-transfer subframe — request, chunk, or
# missing (storage/snapshot.py); KIND_GROUP carries one sharding-plane
# subframe — group-map discovery or committed-batch log shipping
# (groups/ship.py, docs/SHARDING.md); KIND_TELEMETRY carries one fleet
# observability subframe — metrics/trace pull, report, or trace-binding
# announce (net/telemetry.py, docs/OBSERVABILITY.md "Fleet plane").
KIND_HANDSHAKE = 0
KIND_MSG = 1
KIND_CLIENT = 2
KIND_SNAPSHOT = 3
KIND_GROUP = 4
KIND_TELEMETRY = 5

# Upper bound on a single payload.  Generous against the largest legitimate
# protocol message (a MsgBatch of a full iteration's sends), tight against
# a garbage length field committing us to buffer gigabytes.
MAX_FRAME_PAYLOAD = 32 * 1024 * 1024

_HEADER = struct.Struct(">2sBBII")


class FrameError(ValueError):
    """The byte stream is not a valid frame sequence; drop the connection."""


def encode_frame(kind: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME_PAYLOAD:
        raise FrameError(
            f"payload of {len(payload)} bytes exceeds frame cap "
            f"{MAX_FRAME_PAYLOAD}"
        )
    return (
        _HEADER.pack(
            FRAME_MAGIC,
            FRAME_VERSION,
            kind,
            len(payload),
            zlib.crc32(payload) & 0xFFFFFFFF,
        )
        + payload
    )


class FrameDecoder:
    """Incremental decoder over a byte stream of frames.

    ``feed(data)`` returns every frame completed by ``data`` as a list of
    ``(kind, payload)`` tuples and buffers any tail for the next call.
    Raises :class:`FrameError` on malformed input; after an error the
    decoder is poisoned (the stream has no resync point) and every further
    ``feed`` re-raises.
    """

    __slots__ = ("_buf", "_max_payload", "_error")

    def __init__(self, max_payload: int = MAX_FRAME_PAYLOAD):
        self._buf = bytearray()
        self._max_payload = max_payload
        self._error: FrameError = None

    def feed(self, data: bytes) -> List[Tuple[int, bytes]]:
        if self._error is not None:
            raise self._error
        self._buf.extend(data)
        frames: List[Tuple[int, bytes]] = []
        try:
            pos = 0
            buf = self._buf
            while len(buf) - pos >= FRAME_HEADER_LEN:
                magic, version, kind, length, crc = _HEADER.unpack_from(
                    buf, pos
                )
                if magic != FRAME_MAGIC:
                    raise FrameError(f"bad frame magic {bytes(magic)!r}")
                if version != FRAME_VERSION:
                    raise FrameError(f"unsupported frame version {version}")
                if kind not in (
                    KIND_HANDSHAKE,
                    KIND_MSG,
                    KIND_CLIENT,
                    KIND_SNAPSHOT,
                    KIND_GROUP,
                    KIND_TELEMETRY,
                ):
                    raise FrameError(f"unknown frame kind {kind}")
                if length > self._max_payload:
                    raise FrameError(
                        f"frame length {length} exceeds cap {self._max_payload}"
                    )
                if len(buf) - pos - FRAME_HEADER_LEN < length:
                    break  # torn tail: wait for more bytes
                start = pos + FRAME_HEADER_LEN
                payload = bytes(buf[start : start + length])
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    raise FrameError("frame CRC mismatch")
                frames.append((kind, payload))
                pos = start + length
            if pos:
                del buf[:pos]
        except FrameError as exc:
            self._error = exc
            raise
        return frames

    @property
    def pending_bytes(self) -> int:
        """Bytes buffered awaiting a complete frame (diagnostics only)."""
        return len(self._buf)


# --------------------------------------------------------------------------
# KIND_CLIENT group envelope (docs/SHARDING.md)
#
# Sharded deployments prefix the client submission body with a 6-byte
# envelope header so one connection can multiplex submissions to a node's
# co-hosted groups.  The decode path is versioned-compat: a payload without
# the envelope magic is a legacy single-group submission and decodes as
# group 0 with the whole payload as body, so old clients and recorded
# streams keep working unchanged.  The magic byte cannot collide with a
# legacy payload in practice: legacy bodies start with an 8-byte big-endian
# req_no, whose first byte only reaches 0xC1 for req_no >= 0xC1 << 56.

CLIENT_ENV_MAGIC = 0xC1
CLIENT_ENV_VERSION = 1
CLIENT_ENV_VERSION_TRACED = 2
CLIENT_ENV_VERSION_ROUTED = 3
_CLIENT_ENV = struct.Struct(">BBI")  # magic, version, group id
_CLIENT_ENV_TRACE = struct.Struct(">BBIQ")  # + u64 trace id (version 2)
# Version 3 ("routed", docs/SHARDING.md "Elastic resharding") appends the
# u64 client id and the u32 map version the sender routed under, so a
# node can re-route the *client* under its own (possibly newer) map
# instead of trusting the sender's group pick, and redirect stale epochs.
_CLIENT_ENV_ROUTED = struct.Struct(">BBIQQI")


def encode_client_envelope(
    group_id: int,
    body: bytes,
    trace_id: int = 0,
    client_id: int = None,
    map_version: int = None,
) -> bytes:
    """Wrap a client submission body with its destination group id.

    A nonzero ``trace_id`` upgrades the envelope to version 2, which
    appends the 8-byte id after the group id (docs/OBSERVABILITY.md
    "Fleet plane"); ``trace_id == 0`` emits the byte-identical version-1
    envelope, so untraced submissions stay compatible with old decoders.
    Passing ``client_id`` (with the sender's ``map_version``, default 0)
    emits the version-3 routed envelope.
    """
    if client_id is not None:
        return _CLIENT_ENV_ROUTED.pack(
            CLIENT_ENV_MAGIC,
            CLIENT_ENV_VERSION_ROUTED,
            group_id,
            trace_id,
            client_id,
            map_version or 0,
        ) + body
    if trace_id:
        return _CLIENT_ENV_TRACE.pack(
            CLIENT_ENV_MAGIC, CLIENT_ENV_VERSION_TRACED, group_id, trace_id
        ) + body
    return _CLIENT_ENV.pack(CLIENT_ENV_MAGIC, CLIENT_ENV_VERSION, group_id) + body


def decode_client_envelope(payload: bytes) -> Tuple[int, int, bytes]:
    """``(group_id, trace_id, body)`` from a KIND_CLIENT payload; legacy
    payloads (no envelope magic) imply group 0, and version-1 envelopes
    imply trace id 0 (untraced).  Raises :class:`FrameError` on an
    envelope from a future version."""
    group_id, trace_id, _cid, _mv, body = decode_client_envelope_routed(
        payload
    )
    return group_id, trace_id, body


def decode_client_envelope_routed(
    payload: bytes,
) -> Tuple[int, int, Optional[int], Optional[int], bytes]:
    """``(group_id, trace_id, client_id, map_version, body)``; the last
    two are ``None`` below envelope version 3 (the sender predates the
    routed form — route by its group pick, as before)."""
    if len(payload) >= _CLIENT_ENV.size and payload[0] == CLIENT_ENV_MAGIC:
        _magic, version, group_id = _CLIENT_ENV.unpack_from(payload)
        if version == CLIENT_ENV_VERSION:
            return group_id, 0, None, None, payload[_CLIENT_ENV.size:]
        if version == CLIENT_ENV_VERSION_TRACED:
            if len(payload) < _CLIENT_ENV_TRACE.size:
                raise FrameError("truncated traced client envelope")
            _m, _v, group_id, trace_id = _CLIENT_ENV_TRACE.unpack_from(
                payload
            )
            return (
                group_id, trace_id, None, None,
                payload[_CLIENT_ENV_TRACE.size:],
            )
        if version == CLIENT_ENV_VERSION_ROUTED:
            if len(payload) < _CLIENT_ENV_ROUTED.size:
                raise FrameError("truncated routed client envelope")
            (
                _m, _v, group_id, trace_id, client_id, map_version,
            ) = _CLIENT_ENV_ROUTED.unpack_from(payload)
            return (
                group_id, trace_id, client_id, map_version,
                payload[_CLIENT_ENV_ROUTED.size:],
            )
        raise FrameError(
            f"unsupported client envelope version {version}"
        )
    return 0, 0, None, None, payload
