"""Wire-level fault injection for the socket transport.

netem-style per-peer-pair fault schedules for :class:`~.tcp.TcpTransport`:
an outbound frame can be delayed, dropped, duplicated, reordered,
truncated, bit-corrupted, or blocked entirely (partition), per (src, dst)
link, with a deterministic seeded schedule.  The injector sits *between*
``send``'s frame encoding and the per-peer queue, so everything downstream
— sender threads, reconnect/backoff, the receiver's FrameDecoder poison
contract — is exercised exactly as a hostile network would exercise it.

The config object (:class:`FaultPlan`) is JSON round-trippable so
``tools/mirnet.py`` can ship one to each node process via ``cluster.json``
and rewrite ``faults.json`` mid-run for partition/heal choreography
(:meth:`FaultInjector.reconfigure`).

Observability: every injected fault counts in
``net_faults_injected_total{kind}`` and corruption additionally in
``net_frames_corrupted_total`` (docs/OBSERVABILITY.md), which is what makes
injected faults machine-checkable against the doctor's attribution
(docs/FAULTS.md "Doctor-judgment contract").

Determinism: one ``random.Random`` per (seed, src, dst) link — the same
plan over the same frame sequence injects the same faults, so scenario
failures replay.
"""

from __future__ import annotations

import heapq
import random
import struct
import threading
import time
from dataclasses import asdict, dataclass, field, fields
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics as metrics_mod
from .framing import FRAME_HEADER_LEN

# Shared-state declaration for mirlint's lock-discipline pass: submit()
# runs on node worker threads while reconfigure() runs on the control
# thread, so every attribute below may only be touched under its lock
# (docs/STATIC_ANALYSIS.md).
MIRLINT_SHARED_STATE = {
    "FaultInjector._plan": "_lock",
    "FaultInjector._held": "_lock",
    "FaultInjector._rngs": "_lock",
    "DelayScheduler._heap": "_cond",
    "DelayScheduler._counter": "_cond",
    "DelayScheduler._stopped": "_cond",
    "DelayScheduler._thread": "_cond",
}

# Injected-fault kinds (the `kind` label of net_faults_injected_total).
INJECT_KINDS = (
    "drop",
    "delay",
    "duplicate",
    "reorder",
    "truncate",
    "corrupt",
    "partition",
    # Active byzantine behaviors (net/byzantine.py) share the counter.
    "equivocate",
    "replay",
    "mangler_drop",
    "mangler_delay",
    "mangler_duplicate",
)


# ---------------------------------------------------------------------------
# Corruption corpus: every way the injector damages a frame, reusable as
# table-driven fuzz seeds for the FrameDecoder poison contract
# (tests/test_faults.py).
# ---------------------------------------------------------------------------

_HEADER_U32 = struct.Struct(">I")

CORRUPTION_KINDS = (
    "bit_flip_payload",
    "bit_flip_header",
    "bad_magic",
    "bad_version",
    "bad_kind",
    "oversize_length",
    "undersize_length",
    "bad_crc",
    "truncate_header",
    "truncate_payload",
)


def corrupt_frame(kind: str, frame: bytes, rng: random.Random) -> bytes:
    """Return a damaged copy of ``frame`` (one encoded frame).  Every kind
    yields bytes the receiving FrameDecoder must reject with FrameError
    (connection dropped) or legitimately starve on (truncation) — never
    anything that crashes the process."""
    buf = bytearray(frame)
    if kind == "bit_flip_payload":
        if len(buf) > FRAME_HEADER_LEN:
            pos = rng.randrange(FRAME_HEADER_LEN, len(buf))
        else:  # null payload: damage the CRC field instead
            pos = rng.randrange(FRAME_HEADER_LEN - 4, FRAME_HEADER_LEN)
        buf[pos] ^= 1 << rng.randrange(8)
    elif kind == "bit_flip_header":
        pos = rng.randrange(FRAME_HEADER_LEN)
        buf[pos] ^= 1 << rng.randrange(8)
    elif kind == "bad_magic":
        buf[0] ^= 0xFF
    elif kind == "bad_version":
        buf[2] = 0xEE
    elif kind == "bad_kind":
        buf[3] = 0x7F
    elif kind == "oversize_length":
        buf[4:8] = _HEADER_U32.pack(0xFFFFFFF0)
    elif kind == "undersize_length":
        # Lies short: the CRC check runs over the wrong byte range.
        buf[4:8] = _HEADER_U32.pack(max(0, len(frame) - FRAME_HEADER_LEN - 1))
    elif kind == "bad_crc":
        buf[8:12] = _HEADER_U32.pack(
            _HEADER_U32.unpack(bytes(buf[8:12]))[0] ^ 0xDEADBEEF
        )
    elif kind == "truncate_header":
        del buf[rng.randrange(1, FRAME_HEADER_LEN) :]
    elif kind == "truncate_payload":
        keep = FRAME_HEADER_LEN + rng.randrange(
            max(1, len(frame) - FRAME_HEADER_LEN)
        )
        del buf[keep:]
    else:
        raise ValueError(f"unknown corruption kind {kind!r}")
    return bytes(buf)


# ---------------------------------------------------------------------------
# Config objects
# ---------------------------------------------------------------------------


@dataclass
class FaultProfile:
    """netem-style schedule for one directed link.  Percentages are
    per-frame probabilities in [0, 100]; latency is milliseconds."""

    drop_pct: float = 0.0  # frame silently discarded
    delay_ms: float = 0.0  # fixed added latency
    jitter_ms: float = 0.0  # extra uniform latency in [0, jitter_ms]
    duplicate_pct: float = 0.0  # frame delivered twice
    reorder_pct: float = 0.0  # frame held back behind the next one
    truncate_pct: float = 0.0  # frame cut mid-stream
    corrupt_pct: float = 0.0  # frame bit-corrupted (random CORRUPTION_KINDS)
    partition: bool = False  # link blocked entirely (dial + drain fail)

    def active(self) -> bool:
        return self.partition or any(
            getattr(self, f.name) for f in fields(self) if f.name != "partition"
        )

    def as_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultProfile":
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class FaultPlan:
    """One node's injection schedule: a default profile plus per-link
    overrides keyed ``(src, dst)``.  JSON shape (``as_dict``)::

        {"seed": 7, "default": {...}, "links": {"0->3": {...}}}
    """

    seed: int = 0
    default: FaultProfile = field(default_factory=FaultProfile)
    links: Dict[Tuple[int, int], FaultProfile] = field(default_factory=dict)

    def profile_for(self, src: int, dst: int) -> FaultProfile:
        return self.links.get((src, dst), self.default)

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "default": self.default.as_dict(),
            "links": {
                f"{src}->{dst}": prof.as_dict()
                for (src, dst), prof in sorted(self.links.items())
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        links = {}
        for key, prof in d.get("links", {}).items():
            src, _, dst = key.partition("->")
            links[(int(src), int(dst))] = FaultProfile.from_dict(prof)
        return cls(
            seed=int(d.get("seed", 0)),
            default=FaultProfile.from_dict(d.get("default", {})),
            links=links,
        )


# ---------------------------------------------------------------------------
# Delay scheduler (shared with net/byzantine.py)
# ---------------------------------------------------------------------------


class DelayScheduler:
    """Lazy single-thread heap scheduler: ``schedule(delay_s, fn)`` runs
    ``fn()`` on the scheduler thread after ``delay_s``.  The thread starts
    on first use, so zero-rate injectors cost nothing."""

    def __init__(self, name: str = "fault-delay"):
        self._name = name
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._counter = 0
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    def schedule(self, delay_s: float, fn: Callable[[], None]) -> None:
        with self._cond:
            if self._stopped:
                return
            self._counter += 1
            heapq.heappush(
                self._heap, (time.monotonic() + delay_s, self._counter, fn)
            )
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and (
                    not self._heap
                    or self._heap[0][0] > time.monotonic()
                ):
                    if self._heap:
                        self._cond.wait(
                            timeout=max(
                                0.0, self._heap[0][0] - time.monotonic()
                            )
                        )
                    else:
                        self._cond.wait(timeout=0.5)
                if self._stopped:
                    return
                _, _, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:
                pass  # delivery raced a transport shutdown

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._heap.clear()
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# The injector
# ---------------------------------------------------------------------------


class FaultInjector:
    """Deterministic per-link wire-fault injector (module docstring).

    The transport binds its raw enqueue via :meth:`bind`; ``submit`` then
    stands in for the direct enqueue on every outbound frame.  Thread
    safety: ``submit`` runs on node worker threads, ``reconfigure`` on a
    control thread — both take the lock; delivery callbacks run unlocked
    (the transport's enqueue is itself synchronized)."""

    def __init__(
        self,
        node_id: int,
        plan: Optional[FaultPlan] = None,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.node_id = node_id
        self._plan = plan if plan is not None else FaultPlan()
        self._registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self._deliver: Optional[Callable[[int, bytes], None]] = None
        self._lock = threading.Lock()
        self._rngs: Dict[int, random.Random] = {}
        self._held: Dict[int, bytes] = {}  # reorder hold slot per dest
        self._scheduler = DelayScheduler(name=f"net{node_id}-faults")
        self._corrupted = self._registry.counter("net_frames_corrupted_total")

    def bind(self, deliver: Callable[[int, bytes], None]) -> None:
        self._deliver = deliver

    def _count(self, kind: str) -> None:
        self._registry.counter(
            "net_faults_injected_total", labels={"kind": kind}
        ).inc()

    def _rng(self, dest: int) -> random.Random:
        # Must lock: concurrent first-sends to distinct dests race the
        # dict insert, and reconfigure() swaps _plan out from under the
        # seed read.
        with self._lock:
            rng = self._rngs.get(dest)
            if rng is None:
                rng = self._rngs[dest] = random.Random(
                    (self._plan.seed * 1000003) ^ (self.node_id << 20) ^ dest
                )
            return rng

    def reconfigure(self, plan: FaultPlan) -> None:
        """Swap the schedule mid-run (partition/heal choreography).  Held
        reorder frames flush immediately so a heal never strands traffic."""
        with self._lock:
            self._plan = plan
            held, self._held = self._held, {}
        if self._deliver is not None:
            for dest, frame in held.items():
                self._deliver(dest, frame)

    def link_blocked(self, dest: int) -> bool:
        """True while the (self → dest) link is partitioned; the transport
        refuses to dial and fails ``_drain``, so the outage is a *real* TCP
        outage (backoff, ``peer_unreachable`` attribution) rather than a
        silent blackhole the UP gauge would lie about."""
        with self._lock:
            return self._plan.profile_for(self.node_id, dest).partition

    def submit(self, dest: int, frame: bytes) -> None:
        """Run one outbound frame through the link's schedule."""
        deliver = self._deliver
        if deliver is None:
            raise AssertionError("FaultInjector.bind was never called")
        with self._lock:
            prof = self._plan.profile_for(self.node_id, dest)
            if not prof.active():
                release = self._held.pop(dest, None)
            else:
                release = None
        if release is not None:
            deliver(dest, release)
        if not prof.active():
            deliver(dest, frame)
            return

        rng = self._rng(dest)
        if prof.partition:
            # Counted at injection; the frame would only rot in a queue the
            # blocked sender can never drain.
            self._count("partition")
            return
        if prof.drop_pct and rng.random() * 100.0 < prof.drop_pct:
            self._count("drop")
            return
        if prof.corrupt_pct and rng.random() * 100.0 < prof.corrupt_pct:
            frame = corrupt_frame(rng.choice(CORRUPTION_KINDS), frame, rng)
            self._count("corrupt")
            self._corrupted.inc()
        elif prof.truncate_pct and rng.random() * 100.0 < prof.truncate_pct:
            frame = frame[: rng.randrange(1, max(2, len(frame)))]
            self._count("truncate")
            self._corrupted.inc()

        delay_s = 0.0
        if prof.delay_ms or prof.jitter_ms:
            delay_s = (
                prof.delay_ms + rng.random() * prof.jitter_ms
            ) / 1000.0
            if delay_s > 0:
                self._count("delay")

        if prof.reorder_pct and rng.random() * 100.0 < prof.reorder_pct:
            # Hold this frame back; it rides behind the next one.
            with self._lock:
                held = self._held.get(dest)
                self._held[dest] = frame
            self._count("reorder")
            if held is None:
                return
            frame = held  # previous holdee goes out now, behind one frame
            held = None
        else:
            with self._lock:
                held = self._held.pop(dest, None)

        def out(f: bytes) -> None:
            if delay_s > 0:
                self._scheduler.schedule(delay_s, lambda: deliver(dest, f))
            else:
                deliver(dest, f)

        out(frame)
        if held is not None:
            out(held)
        if prof.duplicate_pct and rng.random() * 100.0 < prof.duplicate_pct:
            self._count("duplicate")
            dup_delay = delay_s + rng.random() * max(
                prof.jitter_ms, 1.0
            ) / 1000.0
            self._scheduler.schedule(dup_delay, lambda: deliver(dest, frame))

    def stop(self) -> None:
        self._scheduler.stop()
