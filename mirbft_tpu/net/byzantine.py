"""Byzantine wire mode: mangler DSL programs and active malice on a Link.

Two layers, both wrapping a real transport's ``send`` (the processor never
knows):

* :class:`WireMangler` — compiles ``testengine/manglers.py`` DSL programs
  (rebuilt from their JSON specs, :func:`~..testengine.manglers.
  mangler_from_spec`) into wire faults.  Message-scoped predicates
  (``of_type`` / ``with_sequence`` / ``with_epoch``) evaluate against the
  *decoded* outbound message (including ``MsgBatch`` envelope expansion,
  exactly the simulator's semantics); actions map to the wire as
  drop / delay / jitter / duplicate, with the DSL's sim-time units read as
  **milliseconds**.  ``crash_and_restart_after`` and custom actions carry
  live objects and are refused at spec time.
* :class:`ByzantineLink` — actively malicious peer behaviors beyond what a
  lossy network can do (docs/FAULTS.md):

  - **Equivocating leader** (``equivocate_epoch``): outbound Preprepares in
    the configured epoch are rewritten *per destination* with a
    protocol-invalid batch (an ack for a nonexistent client, different for
    every peer) — the exact shape ``statemachine/epoch_active.py`` must
    answer with a Suspect, not a crash.
  - **Stale replays** (``replay_kinds``): matching outbound messages
    (Suspect / EpochChange by default) are re-sent ``replay_copies`` more
    times after ``replay_ms`` — stale view-change votes and duplicated
    frames the dedup paths must absorb.

Every injected behavior counts in ``net_faults_injected_total{kind}``
(kinds ``equivocate`` / ``replay`` / ``mangler_*``), the same counter the
frame-level :class:`~.faults.FaultInjector` uses, so byzantine scenarios
are machine-checkable against the doctor's attribution.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .. import metrics as metrics_mod
from ..messages import EpochChange, Msg, MsgBatch, Preprepare, RequestAck, Suspect
from ..testengine.manglers import EventMangling, mangler_from_spec
from ..testengine.queue import SimEvent
from .faults import DelayScheduler

# Shared-state declaration for mirlint's lock-discipline pass: apply()
# runs on every sender thread, and both the mangler latch state and the
# RNG stream mutate on match, so they stay under the WireMangler lock
# (docs/STATIC_ANALYSIS.md).
MIRLINT_SHARED_STATE = {
    "WireMangler._manglers": "_lock",
    "WireMangler._rng": "_lock",
}

# Client ids this high can never exist in a standard network state; an ack
# claiming one is protocol-invalid at every honest replica.
_EQUIVOCATION_CLIENT_BASE = 1 << 20

_REPLAYABLE = {"Suspect": Suspect, "EpochChange": EpochChange}


@dataclass
class ByzantineBehaviors:
    """Active-malice knobs for one node (JSON round-trippable; shipped per
    node in mirnet's ``cluster.json``)."""

    # Rewrite own Preprepares of this epoch with per-dest invalid batches.
    equivocate_epoch: Optional[int] = None
    # Re-send matching outbound messages later (stale view-change replays).
    replay_kinds: Tuple[str, ...] = ()
    replay_ms: float = 150.0
    replay_copies: int = 1
    # Mangler DSL programs (spec_from_mangler output), applied after the
    # behaviors above.
    manglers: List[dict] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "equivocate_epoch": self.equivocate_epoch,
            "replay_kinds": list(self.replay_kinds),
            "replay_ms": self.replay_ms,
            "replay_copies": self.replay_copies,
            "manglers": list(self.manglers),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ByzantineBehaviors":
        for kind in d.get("replay_kinds", ()):
            if kind not in _REPLAYABLE:
                raise ValueError(f"unreplayable message kind {kind!r}")
        return cls(
            equivocate_epoch=d.get("equivocate_epoch"),
            replay_kinds=tuple(d.get("replay_kinds", ())),
            replay_ms=float(d.get("replay_ms", 150.0)),
            replay_copies=int(d.get("replay_copies", 1)),
            manglers=list(d.get("manglers", [])),
        )


class WireMangler:
    """Apply mangler DSL programs at ``Link.send`` granularity.

    Each outbound ``(dest, msg)`` becomes a synthetic
    ``SimEvent(target=dest, msg_received=(node_id, msg))`` so the DSL's
    matchers evaluate unchanged; action semantics on the wire:
    ``drop`` → discard, ``delay(d)`` → deliver after d ms, ``jitter(m)`` →
    deliver after (r % m) ms, ``duplicate(m)`` → deliver now plus a copy
    after (r % m) ms.  Programs chain in order, each consuming the
    previous one's output (the simulator applies one mangler per queue;
    chaining is the wire-mode extension)."""

    def __init__(
        self,
        node_id: int,
        manglers: List[EventMangling],
        seed: int = 0,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.node_id = node_id
        self._manglers = manglers
        self._rng = random.Random((seed * 7919) ^ node_id)
        self._registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self._lock = threading.Lock()  # latch state + rng

    def _count(self, kind: str) -> None:
        self._registry.counter(
            "net_faults_injected_total", labels={"kind": kind}
        ).inc()

    def apply(self, dest: int, msg: Msg) -> List[Tuple[float, Msg]]:
        """Returns ``[(delay_ms, msg), ...]`` — empty when dropped."""
        out = [(0.0, msg)]
        with self._lock:
            for mangler in self._manglers:
                nxt: List[Tuple[float, Msg]] = []
                for base_delay, m in out:
                    event = SimEvent(
                        target=dest,
                        time=0,
                        msg_received=(self.node_id, m),
                    )
                    rand = self._rng.getrandbits(62)
                    if not mangler._applies(rand, event):
                        nxt.append((base_delay, m))
                        continue
                    kind = mangler.action_kind
                    if kind == "drop":
                        self._count("mangler_drop")
                        continue
                    if kind == "delay":
                        (delay,) = mangler.action_params
                        self._count("mangler_delay")
                        nxt.append((base_delay + float(delay), m))
                    elif kind == "jitter":
                        (max_delay,) = mangler.action_params
                        self._count("mangler_delay")
                        nxt.append((base_delay + rand % max_delay, m))
                    elif kind == "duplicate":
                        (max_delay,) = mangler.action_params
                        self._count("mangler_duplicate")
                        nxt.append((base_delay, m))
                        nxt.append((base_delay + rand % max_delay, m))
                    else:
                        raise AssertionError(
                            f"unsupported wire action {kind!r}"
                        )
                out = nxt
                if not out:
                    break
        return out


class ByzantineLink:
    """A ``Link`` decorator injecting active malice before a real
    transport (module docstring).  Only the Link surface (``send``) is
    wrapped — lifecycle stays on the inner transport."""

    def __init__(
        self,
        inner,
        node_id: int,
        behaviors: Optional[ByzantineBehaviors] = None,
        seed: int = 0,
        registry: Optional[metrics_mod.Registry] = None,
    ):
        self.inner = inner
        self.node_id = node_id
        self.behaviors = (
            behaviors if behaviors is not None else ByzantineBehaviors()
        )
        self._registry = (
            registry if registry is not None else metrics_mod.default_registry
        )
        self._wire = WireMangler(
            node_id,
            [mangler_from_spec(s) for s in self.behaviors.manglers],
            seed=seed,
            registry=registry,
        )
        self._scheduler = DelayScheduler(name=f"net{node_id}-byz")
        self._replay_types = tuple(
            _REPLAYABLE[k] for k in self.behaviors.replay_kinds
        )

    def _count(self, kind: str) -> None:
        self._registry.counter(
            "net_faults_injected_total", labels={"kind": kind}
        ).inc()

    # --- behaviors ---

    def _equivocate(self, dest: int, msg: Msg) -> Msg:
        """Rewrite own Preprepares of the configured epoch with a per-dest
        protocol-invalid batch (an ack for a client that cannot exist) —
        a different lie for every peer."""
        epoch = self.behaviors.equivocate_epoch
        if isinstance(msg, Preprepare) and msg.epoch == epoch:
            self._count("equivocate")
            poisoned = RequestAck(
                client_id=_EQUIVOCATION_CLIENT_BASE + dest,
                req_no=0,
                digest=b"\x5a" * 32,
            )
            return Preprepare(
                seq_no=msg.seq_no, epoch=msg.epoch, batch=(poisoned,)
            )
        if isinstance(msg, MsgBatch):
            rewritten = tuple(self._equivocate(dest, m) for m in msg.msgs)
            if any(a is not b for a, b in zip(rewritten, msg.msgs)):
                return MsgBatch(msgs=rewritten)
        return msg

    def _maybe_replay(self, dest: int, msg: Msg) -> None:
        for m in self._expand(msg):
            if isinstance(m, self._replay_types):
                for copy_no in range(1, self.behaviors.replay_copies + 1):
                    self._count("replay")
                    self._scheduler.schedule(
                        copy_no * self.behaviors.replay_ms / 1000.0,
                        lambda d=dest, stale=m: self.inner.send(d, stale),
                    )

    @staticmethod
    def _expand(msg: Msg):
        yield msg
        if isinstance(msg, MsgBatch):
            for inner in msg.msgs:
                yield from ByzantineLink._expand(inner)

    # --- Link ---

    def send(self, dest: int, msg: Msg) -> None:
        if self.behaviors.equivocate_epoch is not None:
            msg = self._equivocate(dest, msg)
        if self._replay_types:
            self._maybe_replay(dest, msg)
        for delay_ms, out in self._wire.apply(dest, msg):
            if delay_ms > 0:
                self._scheduler.schedule(
                    delay_ms / 1000.0,
                    lambda d=dest, m=out: self.inner.send(d, m),
                )
            else:
                self.inner.send(dest, out)

    def stop(self) -> None:
        self._scheduler.stop()
