"""Real-socket ``Link``: framed TCP with reconnect/backoff per peer.

:class:`TcpTransport` implements the same ``Link`` protocol the node
runtime consumes (``processor/interfaces.py``): ``send(dest, msg)`` must
not block, and drop-on-backpressure is acceptable.  Design:

* **One outbound connection + sender thread per peer.**  ``send`` encodes
  the message once (``wire.encode`` + frame) and enqueues it on the peer's
  byte-budgeted queue; overflow drops the *newest* frame (the counterpart
  of ``msgbuffers.py``'s drop-on-overflow — consensus tolerates loss, and
  every protocol message is re-derivable by retry/fetch).
* **Per-peer connection state machine** CONNECTING → UP → BACKOFF.  A dial
  failure or mid-stream send error moves the peer to BACKOFF with capped
  exponential backoff plus jitter, then back to CONNECTING.  A peer stuck
  in BACKOFF past ``unreachable_after_s`` is attributed to the health
  plane as a ``peer_unreachable`` fault, once per outage.
* **Handshake.**  The first frame on every connection (both directions) is
  KIND_HANDSHAKE carrying the sender's node id and the network-config
  fingerprint; a fingerprint mismatch (peer from a different network or
  config revision) drops the connection before any protocol traffic.
* **Inbound.**  An accept loop spawns one reader thread per connection;
  frames are decoded incrementally (partial reads, coalesced frames) and
  malformed input — bad magic, CRC mismatch, oversized length, garbage
  payload — drops that connection only, never the process.

Observability (docs/OBSERVABILITY.md "Socket transport"): counters
``net_tx_bytes_total`` / ``net_rx_bytes_total`` / ``net_tx_dropped_total``
/ ``net_reconnects_total``, per-peer gauges ``net_peer_queue_depth`` and
``net_peer_up``, tracer instant events ``net_peer_connect`` /
``net_peer_drop``.
"""

from __future__ import annotations

import hashlib
import random
import select
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics as metrics_mod
from .. import tracing, wire
from .framing import (
    FrameDecoder,
    FrameError,
    KIND_CLIENT,
    KIND_GROUP,
    KIND_HANDSHAKE,
    KIND_MSG,
    KIND_SNAPSHOT,
    KIND_TELEMETRY,
    encode_frame,
)

# Per-peer connection states (exported for tests/status).
CONNECTING = "connecting"
UP = "up"
BACKOFF = "backoff"

# Shared-state declaration for mirlint's lock-discipline pass: the send
# queue is filled by node worker threads and drained by the per-peer
# sender thread, so queue state may only be touched under the peer's
# condition; the accepted-connection list is shared between the acceptor
# and stop() (docs/STATIC_ANALYSIS.md).  The remaining _Peer fields
# (state/backoff_s/down_since/fault_recorded) are single-writer sender-
# thread state and stay out of the map.
MIRLINT_SHARED_STATE = {
    "_Peer.frames": "cond",
    "_Peer.queued_bytes": "cond",
    "_ConnSender.pending": "cond",
    "_ConnSender.pending_bytes": "cond",
    "_ConnSender.writing": "cond",
    "_ConnSender.error": "cond",
    "TcpTransport._conns": "_conns_lock",
}

_HANDSHAKE = struct.Struct(">I")


def config_fingerprint(network_config) -> bytes:
    """Canonical fingerprint of a NetworkConfig (or any wire-encodable
    object): nodes speaking for different networks/config revisions fail
    the handshake instead of exchanging undeliverable protocol traffic."""
    return hashlib.sha256(wire.encode(network_config)).digest()[:16]


class _Peer:
    """Outbound half of one peer link: queue + sender thread state."""

    __slots__ = (
        "peer_id",
        "addr",
        "frames",
        "queued_bytes",
        "cond",
        "state",
        "backoff_s",
        "down_since",
        "fault_recorded",
        "thread",
    )

    def __init__(self, peer_id: int, addr: Tuple[str, int]):
        self.peer_id = peer_id
        self.addr = addr
        self.frames: deque = deque()
        self.queued_bytes = 0
        self.cond = threading.Condition()
        self.state = CONNECTING
        self.backoff_s = 0.0
        self.down_since: Optional[float] = None
        self.fault_recorded = False
        self.thread: Optional[threading.Thread] = None


class _ConnSender:
    """Writer-combining batched sender for one accepted connection.

    Group-plane pushes (ShipFeed), client replies, and telemetry answers
    can all race on the same inbound socket, and ``sendall`` under a
    plain lock serializes every producer behind the slowest subscriber
    (docs/PERFORMANCE.md §16).  Producers instead append the encoded
    frame under the condition and the first appender becomes the
    *writer*: it swaps the whole pending batch out, drops the lock, and
    pushes the batch with one ``sendall`` — a burst of N frames costs
    one syscall, and every non-writer producer returns after a list
    append instead of queueing behind the socket.  Pending bytes are
    bounded: a producer over the budget blocks until the writer drains
    (the pre-batching behaviour — blocking in ``sendall`` under the
    lock — with the socket timeout surfacing as a latched connection
    error that every later sender re-raises)."""

    MAX_PENDING_BYTES = 4 << 20

    __slots__ = ("conn", "cond", "pending", "pending_bytes", "writing", "error")

    def __init__(self, conn: socket.socket):
        self.conn = conn
        self.cond = threading.Condition()
        self.pending: List[bytes] = []
        self.pending_bytes = 0
        self.writing = False
        self.error: Optional[BaseException] = None

    def send(self, frame: bytes, wait_hist, tx_bytes) -> None:
        t0 = time.perf_counter()
        with self.cond:
            wait_hist.observe(time.perf_counter() - t0)
            while (
                self.error is None
                and self.writing
                and self.pending_bytes >= self.MAX_PENDING_BYTES
            ):
                self.cond.wait()
            if self.error is not None:
                raise self.error
            self.pending.append(frame)
            self.pending_bytes += len(frame)
            if self.writing:
                return  # the active writer flushes this frame
            self.writing = True
        while True:
            with self.cond:
                if not self.pending:
                    self.writing = False
                    self.cond.notify_all()
                    return
                batch = b"".join(self.pending)
                self.pending.clear()
                self.pending_bytes = 0
                self.cond.notify_all()
            try:
                self.conn.sendall(batch)
            except BaseException as exc:
                with self.cond:
                    self.error = exc
                    self.writing = False
                    self.cond.notify_all()
                raise
            tx_bytes.inc(len(batch))


class TcpTransport:
    """A ``Link`` over localhost/LAN TCP (see module docstring)."""

    def __init__(
        self,
        node_id: int,
        peers: Dict[int, Tuple[str, int]],
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        fingerprint: bytes = b"",
        queue_budget_bytes: int = 8 * 1024 * 1024,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        backoff_jitter: float = 0.3,
        unreachable_after_s: float = 5.0,
        dial_timeout_s: float = 1.0,
        tracer: Optional[tracing.Tracer] = None,
        health_monitor=None,
        logger=None,
        fault_injector=None,
    ):
        self.node_id = node_id
        self.fingerprint = fingerprint
        self.queue_budget_bytes = queue_budget_bytes
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.unreachable_after_s = unreachable_after_s
        self.dial_timeout_s = dial_timeout_s
        self.tracer = tracer if tracer is not None else tracing.default_tracer
        self.health_monitor = health_monitor
        self.logger = logger
        # Optional wire-fault injector (net/faults.py): when set, every
        # outbound frame routes through its per-link schedule before the
        # peer queue, and partitioned links refuse to dial/drain so the
        # outage is a real TCP outage (docs/FAULTS.md).
        self.fault_injector = fault_injector
        if fault_injector is not None:
            fault_injector.bind(self._enqueue_frame)
        self._rng = random.Random(node_id)  # jitter only; never protocol-visible

        self._peers: Dict[int, _Peer] = {
            pid: _Peer(pid, addr)
            for pid, addr in peers.items()
            if pid != node_id
        }
        self._on_message: Optional[Callable[[int, object], None]] = None
        self._on_client: Optional[Callable[[bytes, Callable], None]] = None
        self._on_snapshot: Optional[Callable[[bytes], Optional[bytes]]] = None
        self._on_group: Optional[Callable[[bytes, Callable], None]] = None
        self._on_telemetry: Optional[Callable[[bytes, Callable], None]] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns: list = []
        self._conns_lock = threading.Lock()

        self._tx_bytes = metrics_mod.counter("net_tx_bytes_total")
        self._rx_bytes = metrics_mod.counter("net_rx_bytes_total")
        self._tx_dropped = metrics_mod.counter("net_tx_dropped_total")
        self._reconnects = metrics_mod.counter("net_reconnects_total")
        # Wait to acquire a reader connection's send lock: reply traffic
        # and ship-feed pushes contend on it, and this histogram is the
        # measured answer to whether that contention matters
        # (docs/OBSERVABILITY.md, ROADMAP item 3).
        self._send_lock_wait = metrics_mod.histogram(
            "net_send_lock_wait_seconds"
        )

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, listen_port))
        self._listener.listen(64)
        self._listener.settimeout(0.2)

    # --- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    def start(
        self,
        on_message: Callable[[int, object], None],
        on_client: Optional[Callable[[bytes, Callable], None]] = None,
        on_snapshot: Optional[Callable[[bytes], Optional[bytes]]] = None,
        on_group: Optional[Callable[[bytes, Callable], None]] = None,
        on_telemetry: Optional[Callable[[bytes, Callable], None]] = None,
    ) -> None:
        """Begin accepting and dialing.  ``on_message(source, msg)`` is
        invoked on reader threads for every inbound protocol message (the
        node's thread-safe ``step``); ``on_client(payload, reply)`` for
        KIND_CLIENT frames (``reply(payload)`` answers on the same
        connection — the mirnet submission path); ``on_snapshot(digest)``
        returns the local snapshot body (or None) for KIND_SNAPSHOT
        state-transfer requests (storage/snapshot.py); ``on_group(payload,
        send)`` handles KIND_GROUP sharding-plane frames — ``send(payload)``
        answers (and may keep answering: log-ship subscriptions hold the
        connection open) on the same connection (groups/ship.py);
        ``on_telemetry(payload, send)`` handles KIND_TELEMETRY fleet
        observability frames the same way (net/telemetry.py)."""
        self._on_message = on_message
        self._on_client = on_client
        self._on_snapshot = on_snapshot
        self._on_group = on_group
        self._on_telemetry = on_telemetry
        accept = threading.Thread(
            target=self._accept_loop,
            name=f"net{self.node_id}-accept",
            daemon=True,
        )
        accept.start()
        self._threads.append(accept)
        for peer in self._peers.values():
            peer.thread = threading.Thread(
                target=self._sender_loop,
                args=(peer,),
                name=f"net{self.node_id}-tx{peer.peer_id}",
                daemon=True,
            )
            peer.thread.start()
            self._threads.append(peer.thread)

    def stop(self) -> None:
        self._stop.set()
        if self.fault_injector is not None:
            self.fault_injector.stop()
        for peer in self._peers.values():
            with peer.cond:
                peer.cond.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        for thread in list(self._threads):
            thread.join(timeout=2)

    def peer_state(self, peer_id: int) -> str:
        return self._peers[peer_id].state

    # --- Link --------------------------------------------------------------

    def send(self, dest: int, msg) -> None:
        """Non-blocking enqueue; drops on overflow (Link contract)."""
        if dest not in self._peers:
            return  # self or unknown peer: nothing to do
        frame = encode_frame(KIND_MSG, wire.encode(msg))
        if self.fault_injector is not None:
            self.fault_injector.submit(dest, frame)
        else:
            self._enqueue_frame(dest, frame)

    def _enqueue_frame(self, dest: int, frame: bytes) -> None:
        peer = self._peers.get(dest)
        if peer is None:
            return
        with peer.cond:
            if peer.queued_bytes + len(frame) > self.queue_budget_bytes:
                self._tx_dropped.inc()
                return
            peer.frames.append(frame)
            peer.queued_bytes += len(frame)
            metrics_mod.gauge(
                "net_peer_queue_depth", labels={"peer": str(dest)}
            ).set(peer.queued_bytes)
            peer.cond.notify()

    # --- outbound ----------------------------------------------------------

    def _sender_loop(self, peer: _Peer) -> None:
        up_gauge = metrics_mod.gauge(
            "net_peer_up", labels={"peer": str(peer.peer_id)}
        )
        up_gauge.set(0)
        while not self._stop.is_set():
            sock = self._dial(peer)
            if sock is None:
                if self._stop.is_set():
                    return
                self._enter_backoff(peer, up_gauge, was_up=False)
                continue
            peer.state = UP
            peer.backoff_s = 0.0
            peer.down_since = None
            peer.fault_recorded = False
            up_gauge.set(1)
            self.tracer.instant(
                "net_peer_connect",
                pid=self.node_id,
                args={"peer": peer.peer_id},
            )
            try:
                self._drain(peer, sock)
            except OSError:
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
            if self._stop.is_set():
                return
            self._enter_backoff(peer, up_gauge, was_up=True)

    def _dial(self, peer: _Peer) -> Optional[socket.socket]:
        if self.fault_injector is not None and self.fault_injector.link_blocked(
            peer.peer_id
        ):
            return None  # partitioned: behaves exactly like a dead network
        try:
            sock = socket.create_connection(
                peer.addr, timeout=self.dial_timeout_s
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            sock.sendall(
                encode_frame(
                    KIND_HANDSHAKE,
                    _HANDSHAKE.pack(self.node_id) + self.fingerprint,
                )
            )
            return sock
        except OSError:
            return None

    def _drain(self, peer: _Peer, sock: socket.socket) -> None:
        """Pump the peer queue into the socket until error or stop."""
        depth_gauge = metrics_mod.gauge(
            "net_peer_queue_depth", labels={"peer": str(peer.peer_id)}
        )
        while not self._stop.is_set():
            if (
                self.fault_injector is not None
                and self.fault_injector.link_blocked(peer.peer_id)
            ):
                # A partition starting mid-connection severs the link the
                # way a cable pull would: the sender reconnects into the
                # (refused) dial path and enters backoff.
                raise OSError("link partitioned (fault injection)")
            with peer.cond:
                if not peer.frames:
                    peer.cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                frame = peer.frames.popleft() if peer.frames else None
                if frame is not None:
                    peer.queued_bytes -= len(frame)
                    depth_gauge.set(peer.queued_bytes)
            if frame is None:
                # Idle liveness probe: the outbound half of a link never
                # receives data (each direction has its own connection), so
                # readability means EOF/RST — without this, an idle link
                # only notices a dead peer on the next send and the
                # UP/BACKOFF state machine would lie to the health plane.
                readable, _, _ = select.select([sock], [], [], 0)
                if readable and not sock.recv(4096):
                    raise OSError("peer closed connection")
                continue
            sock.sendall(frame)  # OSError here → caller reconnects
            self._tx_bytes.inc(len(frame))

    def _enter_backoff(self, peer: _Peer, up_gauge, was_up: bool) -> None:
        peer.state = BACKOFF
        up_gauge.set(0)
        now = time.monotonic()
        if peer.down_since is None:
            peer.down_since = now
        self._reconnects.inc()
        if was_up:
            self.tracer.instant(
                "net_peer_drop",
                pid=self.node_id,
                args={"peer": peer.peer_id},
            )
        if (
            self.health_monitor is not None
            and not peer.fault_recorded
            and now - peer.down_since >= self.unreachable_after_s
        ):
            peer.fault_recorded = True
            self.health_monitor.record_fault(
                peer.peer_id,
                "peer_unreachable",
                down_seconds=round(now - peer.down_since, 3),
            )
        peer.backoff_s = min(
            self.backoff_max_s,
            max(self.backoff_base_s, peer.backoff_s * 2),
        )
        delay = peer.backoff_s * (
            1 + self.backoff_jitter * self._rng.random()
        )
        self._stop.wait(timeout=delay)
        if not self._stop.is_set():
            peer.state = CONNECTING

    # --- inbound -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(0.2)
            with self._conns_lock:
                self._conns.append(conn)
            reader = threading.Thread(
                target=self._reader_loop,
                args=(conn,),
                name=f"net{self.node_id}-rx",
                daemon=True,
            )
            reader.start()
            self._threads.append(reader)

    def _reader_loop(self, conn: socket.socket) -> None:
        decoder = FrameDecoder()
        source: Optional[int] = None
        # Group-plane pushes (ShipFeed) come from the node's app thread
        # while this reader may be answering on the same socket, so every
        # send on this connection goes through one writer-combining
        # batcher: frames enqueue under the condition, one producer at a
        # time drains the batch with a single sendall outside it.
        sender = _ConnSender(conn)

        def locked_send(kind: int, payload: bytes) -> None:
            sender.send(
                encode_frame(kind, payload),
                self._send_lock_wait,
                self._tx_bytes,
            )

        def reply(payload: bytes) -> None:
            locked_send(KIND_CLIENT, payload)

        def group_send(payload: bytes) -> None:
            locked_send(KIND_GROUP, payload)

        def telemetry_send(payload: bytes) -> None:
            locked_send(KIND_TELEMETRY, payload)

        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    return  # peer closed
                self._rx_bytes.inc(len(data))
                for kind, payload in decoder.feed(data):
                    if kind == KIND_HANDSHAKE:
                        peer_id = _HANDSHAKE.unpack_from(payload)[0]
                        if payload[_HANDSHAKE.size :] != self.fingerprint:
                            self._log_drop(
                                f"peer {peer_id}: config fingerprint mismatch"
                            )
                            return
                        source = peer_id
                    elif kind == KIND_MSG:
                        if source is None:
                            self._log_drop("protocol frame before handshake")
                            return
                        self._on_message(source, wire.decode(payload))
                    elif kind == KIND_CLIENT:
                        if self._on_client is None:
                            self._log_drop("unexpected client frame")
                            return
                        self._on_client(payload, reply)
                    elif kind == KIND_SNAPSHOT:
                        if self._on_snapshot is None:
                            self._log_drop("unexpected snapshot frame")
                            return
                        self._serve_snapshot(conn, payload)
                    elif kind == KIND_GROUP:
                        if self._on_group is None:
                            self._log_drop("unexpected group frame")
                            return
                        self._on_group(payload, group_send)
                    elif kind == KIND_TELEMETRY:
                        if self._on_telemetry is None:
                            self._log_drop("unexpected telemetry frame")
                            return
                        self._on_telemetry(payload, telemetry_send)
        except FrameError as exc:
            self._log_drop(f"frame error from peer {source}: {exc}")
        except Exception as exc:  # decode error, stopped node, ...
            self._log_drop(f"dropping connection from peer {source}: {exc!r}")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_snapshot(self, conn: socket.socket, payload: bytes) -> None:
        """Answer one snapshot state-transfer request on the requester's
        connection.  The chunk stream can be many MiB, so the 0.2 s reader
        timeout is lifted for the duration of the sendall burst."""
        # Local import: storage depends on net.framing, so importing at
        # module level would make the dependency circular.
        from ..storage import snapshot as snapmod

        replies = snapmod.serve_request(payload, self._on_snapshot)
        conn.settimeout(None)
        try:
            for reply_payload in replies:
                frame = encode_frame(KIND_SNAPSHOT, reply_payload)
                conn.sendall(frame)
                self._tx_bytes.inc(len(frame))
        finally:
            conn.settimeout(0.2)

    def _log_drop(self, why: str) -> None:
        self.tracer.instant(
            "net_conn_drop", pid=self.node_id, args={"why": why}
        )
        if self.logger is not None:
            self.logger.warn("net: " + why)
