"""Durable write-ahead log (L4).

Rebuild of reference ``pkg/simplewal`` (tidwall/wal-backed): a segmented
append-only log of canonically-encoded ``Persistent`` entries with explicit
``sync`` (no per-write fsync) and front truncation.

Layout: a directory of segment files named ``seg-<first_index>.wal``, each a
stream of framed records ``uvarint(len) || uvarint(index) || entry-bytes``.
Appends go to the active (highest) segment, rotating at
``segment_max_bytes``; ``truncate`` drops whole segments whose entries all
precede the cut index (lazy, like tidwall's TruncateFront) and the loader
skips residual entries below the logical low index.
"""

from __future__ import annotations

import os
import struct
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from . import wire
from .messages import Persistent
from .storage.segments import fsync_dir

_LOW_MARK_FILE = "lowmark"


def _write_frame(fh, index: int, payload: bytes) -> None:
    head = bytearray()
    wire.write_uvarint(head, len(payload))
    wire.write_uvarint(head, index)
    fh.write(bytes(head))
    fh.write(payload)


def _read_frames(data: bytes):
    view = memoryview(data)
    pos = 0
    while pos < len(view):
        try:
            length, pos = wire.read_uvarint(view, pos)
            index, pos = wire.read_uvarint(view, pos)
        except ValueError:
            return  # torn tail (crash mid-append); ignore
        if pos + length > len(view):
            return  # torn payload
        yield index, bytes(view[pos : pos + length])
        pos += length


class WAL:
    """File-backed ``processor.WAL`` implementation."""

    def __init__(self, path: str, segment_max_bytes: int = 4 * 1024 * 1024):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = segment_max_bytes
        self._fh = None
        self._active_path: Optional[Path] = None
        self._active_size = 0
        self._next_index: Optional[int] = None  # unknown until load/append
        self._low_index = self._read_low_mark()

    # --- low-watermark bookkeeping ---

    def _read_low_mark(self) -> int:
        mark = self.dir / _LOW_MARK_FILE
        if mark.exists():
            return int(mark.read_text())
        return 1

    def _write_low_mark(self, index: int) -> None:
        tmp = self.dir / (_LOW_MARK_FILE + ".tmp")
        tmp.write_text(str(index))
        os.replace(tmp, self.dir / _LOW_MARK_FILE)
        fsync_dir(self.dir)  # the rename must survive a crash

    # --- segments ---

    def _segments(self) -> List[Tuple[int, Path]]:
        segments = []
        for entry in self.dir.iterdir():
            if entry.name.startswith("seg-") and entry.name.endswith(".wal"):
                segments.append((int(entry.name[4:-4]), entry))
        return sorted(segments)

    @staticmethod
    def _valid_length(data: bytes) -> int:
        """Byte length of the valid frame prefix (excludes any torn tail)."""
        view = memoryview(data)
        pos = 0
        while pos < len(view):
            start = pos
            try:
                length, pos = wire.read_uvarint(view, pos)
                _, pos = wire.read_uvarint(view, pos)
            except ValueError:
                return start
            if pos + length > len(view):
                return start
            pos += length
        return pos

    def _open_segment(self, first_index: int) -> None:
        if self._fh is not None:
            self._fh.close()
        self._active_path = self.dir / f"seg-{first_index}.wal"
        if self._active_path.exists():
            # Reopening after a crash: cut any torn tail BEFORE appending,
            # or new frames land after garbage and are lost to the loader.
            data = self._active_path.read_bytes()
            valid = self._valid_length(data)
            if valid != len(data):
                with open(self._active_path, "r+b") as fh:
                    fh.truncate(valid)
                    fh.flush()
                    os.fsync(fh.fileno())
        self._fh = open(self._active_path, "ab")
        self._active_size = self._active_path.stat().st_size
        # A crash between creating the segment and syncing the directory
        # loses the file even though its data was fsynced.
        fsync_dir(self.dir)

    # --- WAL protocol ---

    def write(self, index: int, entry: Persistent) -> None:
        if self._next_index is not None and index != self._next_index:
            raise ValueError(
                f"WAL out of order: expected index {self._next_index}, got {index}"
            )
        if self._fh is None or self._active_size >= self.segment_max_bytes:
            self._open_segment(index)
        payload = wire.encode(entry)
        before = self._active_size
        _write_frame(self._fh, index, payload)
        self._active_size = before + len(payload) + 20  # frame overhead bound
        self._next_index = index + 1

    def truncate(self, index: int) -> None:
        """Logically drop entries below ``index``; physically remove whole
        segments entirely below it."""
        if index < self._low_index:
            raise ValueError(
                f"truncate to {index} below low index {self._low_index}"
            )
        self._low_index = index
        self._write_low_mark(index)
        segments = self._segments()
        unlinked = False
        for i, (first, path) in enumerate(segments):
            next_first = (
                segments[i + 1][0] if i + 1 < len(segments) else None
            )
            if next_first is not None and next_first <= index and path != self._active_path:
                path.unlink()
                unlinked = True
        if unlinked:
            # A crash before the directory syncs can resurrect an unlinked
            # segment; harmless for reads (lowmark filters it) but it would
            # un-reclaim the space truncate just promised to free.
            fsync_dir(self.dir)

    def sync(self) -> None:
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())

    def load_all(self, for_each: Callable[[int, Persistent], None]) -> None:
        records: List[Tuple[int, bytes]] = []
        for first, path in self._segments():
            for index, payload in _read_frames(path.read_bytes()):
                if index >= self._low_index:
                    records.append((index, payload))
        records.sort(key=lambda r: r[0])
        expected = None
        for index, payload in records:
            if expected is not None and index != expected:
                raise ValueError(
                    f"WAL gap: expected index {expected}, found {index}"
                )
            for_each(index, wire.decode(payload))
            expected = index + 1
        if expected is not None:
            self._next_index = expected

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
